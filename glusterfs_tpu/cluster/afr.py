"""cluster/replicate — synchronous N-way replication (AFR).

Reference: xlators/cluster/afr (30k LoC).  Behaviors kept:

* **Transactions** (afr-transaction.c:1087,629): pre-op mark dirty, wind
  the write to every up child, post-op bump the committed version on the
  children that succeeded — divergence marks heal candidates.  The
  reference's per-peer pending-xattr matrix collapses to per-brick
  (version, dirty) counters, which identify staleness the same way the
  EC layer's do (shared transaction skeleton, SURVEY.md §7 phase 3).
* **Quorum** (afr quorum-type auto): writes need a majority (or the
  configured ``quorum-count``); reads need one up-to-date child.
* **Read transactions** (afr-read-txn.c:94-229): reads pick one
  consistent child per ``read-hash-mode`` and fail over to another on
  error.
* **Self-heal** (afr-self-heal-data.c): full-file copy from a good child
  to stale ones under lock, then counter realignment; entry heal
  reconciles directory listings.

Xattr schema per brick: ``trusted.afr.version`` (2 u64: data, metadata),
``trusted.afr.dirty`` (2 u64) — same codec as the EC layer.
"""

from __future__ import annotations

import asyncio
import errno
import struct
from collections import Counter

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt, gfid_new
from ..core.layer import Event, FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("afr")

XA_VERSION = "trusted.afr.version"
XA_DIRTY = "trusted.afr.dirty"
# per-target blame counters (trusted.afr.<brick>.pending analog):
# pending.<j> on brick i counts writes i took that j missed — the
# matrix afr_selfheal_find_direction reads; mutual blame = split-brain
XA_PENDING = "trusted.afr.pending."


def _u64x2(data: bytes | None) -> tuple[int, int]:
    if not data:
        return (0, 0)
    return struct.unpack(">QQ", data.ljust(16, b"\0")[:16])


def _pack_u64x2(a: int, b: int) -> bytes:
    return struct.pack(">QQ", a, b)


class AfrFdCtx:
    __slots__ = ("child_fds", "flags")

    def __init__(self, child_fds: dict[int, FdObj], flags: int):
        self.child_fds = child_fds
        self.flags = flags


@register("cluster/replicate")
class ReplicateLayer(Layer):
    OPTIONS = (
        Option("quorum-type", "enum", default="auto",
               values=("auto", "fixed", "none"),
               description="write-quorum model (cluster.quorum-type, "
                           "afr_has_quorum): auto = strict majority, "
                           "fixed = quorum-count, none = any one "
                           "child"),
        Option("quorum-reads", "bool", default="off",
               description="reads too fail without quorum "
                           "(cluster.quorum-reads): off serves reads "
                           "from any consistent child like the "
                           "reference default"),
        Option("data-self-heal", "bool", default="on",
               description="heal file CONTENT (cluster.data-self-heal); "
                           "off leaves data divergence to the operator"),
        Option("metadata-self-heal", "bool", default="on",
               description="heal mode/times (cluster.metadata-self-"
                           "heal)"),
        Option("entry-self-heal", "bool", default="on",
               description="heal directory entries "
                           "(cluster.entry-self-heal)"),
        Option("data-self-heal-algorithm", "enum", default="diff",
               values=("diff", "full"),
               description="diff = rchecksum handshake per window, "
                           "copy only differing blocks; full = copy "
                           "everything (cluster.data-self-heal-"
                           "algorithm)"),
        Option("ensure-durability", "bool", default="on",
               description="fsync healed sinks before declaring the "
                           "heal done (cluster.ensure-durability)"),
        Option("choose-local", "bool", default="on",
               description="prefer a wire-free (same-process) child "
                           "for reads (cluster.choose-local)"),
        Option("read-subvolume-index", "int", default=-1, min=-1,
               description="pin reads to this child index when it is "
                           "consistent (cluster.read-subvolume-index; "
                           "-1 = policy)"),
        Option("read-subvolume", "str", default="",
               description="pin reads to this child NAME "
                           "(cluster.read-subvolume)"),
        Option("quorum-count", "int", default=0, min=0,
               description="0 = auto (majority)"),
        Option("read-hash-mode", "enum", default="gfid-hash",
               values=("first-up", "gfid-hash", "round-robin")),
        Option("self-heal-window-size", "size", default="1M"),
        Option("favorite-child", "int", default=-1, min=-1,
               description="split-brain resolution source (-1 = none)"),
        Option("favorite-child-policy", "enum", default="none",
               values=("none", "size", "mtime", "majority"),
               description="automatic split-brain resolution "
                           "(cluster.favorite-child-policy): pick the "
                           "biggest / latest-mtime / most-common copy"),
        Option("arbiter-count", "int", default=0, min=0, max=1,
               description="the group's LAST brick is a metadata-only "
                           "witness (features/arbiter on the brick): "
                           "counted for quorum and blame, never read "
                           "from, never a data-heal source"),
        Option("thin-arbiter", "bool", default="off",
               description="the LAST child is a remote tie-breaker "
                           "holding one mark file per volume "
                           "(features/thin-arbiter): consulted only "
                           "when a data replica is down — a degraded "
                           "write marks the absent replica bad there, "
                           "and a lone replica may only serve if it is "
                           "not the marked one"),
    )

    TA_PATH = "/.thin-arbiter"
    TA_KEY = "trusted.afr.ta.bad."

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.n = len(self.children)
        # gfids a read found split-brained (inode-ctx refresh analog):
        # writes consult this so they don't deepen a known divergence
        self._sb_cache: set[bytes] = set()
        self.ta = None
        self.ta_up = True
        if self.opts["thin-arbiter"]:
            # the tie-breaker child is NOT a replica: it leaves the
            # data-plane index space entirely
            self.ta = self.children[-1]
            self.n -= 1
            if self.n != 2:
                raise ValueError(f"{self.name}: thin-arbiter needs "
                                 f"exactly 2 data replicas")
        self.arbiters: set[int] = set(
            range(self.n - self.opts["arbiter-count"], self.n))
        if self.n < 2:
            raise ValueError(f"{self.name}: replicate needs >= 2 children")
        self.up = [True] * self.n
        self._locks: dict[bytes, asyncio.Lock] = {}
        self._rr = 0
        self._lk_owner = gfid_new()
        self._locks_supported: bool | None = None
        # last announced quorum state (events.h EVENT_AFR_QUORUM_MET /
        # EVENT_AFR_QUORUM_FAIL fire only on the TRANSITION)
        self._quorum_ok = True

    # -- membership --------------------------------------------------------

    def notify(self, event: Event, source=None, data=None):
        if event is Event.UPCALL:
            for p in self.parents:
                p.notify(event, self, data)
            return
        if source in self.children:
            idx = self.children.index(source)
            if idx >= self.n:  # the thin-arbiter child
                self.ta_up = event is not Event.CHILD_DOWN
                return
            if event is Event.CHILD_DOWN:
                self.up[idx] = False
            elif event is Event.CHILD_UP:
                self.up[idx] = True
            ok = self._quorum_met(
                {i for i, u in enumerate(self.up) if u})
            if ok != self._quorum_ok:
                # quorum edge (afr_notify, events.h): LOST means this
                # replica set stopped accepting writes — the cluster's
                # pulse, not a per-fop error someone may never read
                self._quorum_ok = ok
                from ..core.events import gf_event

                gf_event("AFR_QUORUM_MET" if ok else "AFR_QUORUM_FAIL",
                         subvol=self.name, up=sum(self.up),
                         children=self.n)
            ev = Event.CHILD_UP if ok else Event.CHILD_DOWN
            for p in self.parents:
                p.notify(ev, self, data)
            return
        super().notify(event, source, data)

    def set_child_up(self, idx: int, up: bool) -> None:
        self.up[idx] = up

    def _up_idx(self) -> list[int]:
        return [i for i, u in enumerate(self.up) if u]

    def _quorum(self) -> int:
        q = self.opts["quorum-count"]
        return q if q else self.n // 2 + 1

    def _quorum_met(self, good) -> bool:
        """afr_has_quorum per cluster.quorum-type: none = any child;
        fixed = quorum-count; auto = a strict majority, OR — for EVEN
        replica counts with exactly half alive — the half containing
        the FIRST brick wins the tie (so a 2-way replica keeps writing
        when brick 1 dies, but not when brick 0 does)."""
        qt = self.opts["quorum-type"]
        if qt == "none":
            return len(good) >= 1
        q = self.opts["quorum-count"]
        if qt == "fixed" and not q:
            # fixed without a count must not silently mean quorum=1
            # (both partition sides would write; the reference refuses
            # the combination at volume-set): fall back to majority
            qt = "auto"
        if qt == "fixed" or q:
            return len(good) >= max(1, q)
        if len(good) >= self.n // 2 + 1:
            return True
        return (self.n % 2 == 0 and len(good) == self.n // 2
                and 0 in good)

    def _lock(self, key: bytes) -> asyncio.Lock:
        lk = self._locks.get(key)
        if lk is None:
            lk = self._locks[key] = asyncio.Lock()
        return lk

    # -- dispatch / combine ------------------------------------------------

    async def _dispatch(self, idxs, op: str, argfn):
        async def one(i):
            args, kwargs = argfn(i)
            return await getattr(self.children[i], op)(*args, **kwargs)

        results = await asyncio.gather(*(one(i) for i in idxs),
                                       return_exceptions=True)
        return dict(zip(idxs, results))

    def _combine(self, res: dict, min_ok: int | None = None):
        good = {i: r for i, r in res.items()
                if not isinstance(r, BaseException)}
        if min_ok is None:
            if self._quorum_met(good):
                return good
        elif len(good) >= min_ok:
            return good
        errs = [r.err for r in res.values() if isinstance(r, FopError)]
        if errs:
            raise FopError(Counter(errs).most_common(1)[0][0],
                           f"{len(good)}/{len(res)} children succeeded")
        for r in res.values():
            if isinstance(r, BaseException):
                raise r
        raise FopError(errno.EIO, "quorum failure")

    async def _get_meta(self, idxs, loc: Loc):
        res = await self._dispatch(idxs, "getxattr",
                                   lambda i: ((loc, None), {}))
        out = {}
        for i, r in res.items():
            if isinstance(r, BaseException):
                out[i] = r
            else:
                pend = {}
                for j in range(self.n):
                    v = _u64x2(r.get(XA_PENDING + str(j)))[0]
                    if v:
                        pend[j] = v
                out[i] = {"version": _u64x2(r.get(XA_VERSION)),
                          "dirty": _u64x2(r.get(XA_DIRTY)),
                          "pending": pend}
        return out

    @staticmethod
    def _accused(vals: dict) -> set[int]:
        """Bricks blamed by any OTHER reachable brick's pending matrix
        (afr_selfheal_find_direction: pending counters point away from
        sources)."""
        out: set[int] = set()
        for i, m in vals.items():
            for j, cnt in m["pending"].items():
                if j != i and cnt > 0:
                    out.add(j)
        return out

    # -- thin-arbiter marks (thin-arbiter.c ta_update_fav_child) -----------

    async def _ta_marks(self) -> dict[int, int]:
        """Per-replica bad marks on the tie-breaker's volume file."""
        if self.ta is None or not self.ta_up:
            raise FopError(errno.ENOTCONN, "thin-arbiter unreachable")
        loc = Loc(self.TA_PATH)
        try:
            xa = await self.ta.getxattr(loc, None)
        except FopError as e:
            if e.err == errno.ENOENT:
                return {}
            raise
        out = {}
        for j in range(self.n):
            v = _u64x2((xa or {}).get(self.TA_KEY + str(j)))[0]
            if v:
                out[j] = v
        return out

    async def _ta_mark_bad(self, bad: list[int]) -> None:
        """A degraded write first brands the absent replica on the
        tie-breaker; only then may a single data brick accept writes."""
        loc = Loc(self.TA_PATH)
        try:
            await self.ta.mknod(loc, 0o600)
        except FopError as e:
            if e.err != errno.EEXIST:
                raise
        await self.ta.xattrop(loc, "add64",
                              {self.TA_KEY + str(j): _pack_u64x2(1, 0)
                               for j in bad})

    async def _ta_clear(self, healed: list[int]) -> None:
        if self.ta is None:
            return
        try:
            await self.ta.setxattr(
                Loc(self.TA_PATH),
                {self.TA_KEY + str(j): _pack_u64x2(0, 0) for j in healed})
        except FopError:
            pass

    async def _good_rows(self, loc: Loc) -> list[int]:
        """Up children that no peer blames, at the best version (clean
        preferred).  Mutual blame with no innocent brick is split-brain:
        reads fail EIO rather than serve whichever divergent copy
        happens to answer (afr_read_txn refuses split-brained inodes)."""
        ups = self._up_idx()
        meta = await self._get_meta(ups, loc)
        vals = {i: m for i, m in meta.items()
                if not isinstance(m, BaseException)}
        if not vals:
            raise FopError(errno.ENOTCONN, "no readable children")
        if self.ta is not None and len(vals) < self.n:
            # degraded 2-replica volume: the tie-breaker decides whether
            # the surviving replica may serve (it must not be the one a
            # degraded write branded bad)
            marks = await self._ta_marks()
            vals = {i: m for i, m in vals.items() if i not in marks}
            if not vals:
                raise FopError(errno.EIO,
                               f"{loc.path}: surviving replica is "
                               f"marked bad on the thin-arbiter")
        accused = self._accused(vals)
        innocent = {i: m for i, m in vals.items() if i not in accused}
        if not innocent:
            if loc.gfid:
                self._sb_cache.add(bytes(loc.gfid))
            raise FopError(errno.EIO,
                           f"{loc.path}: split-brain (every replica "
                           f"blamed; resolve with heal split-brain)")
        if loc.gfid:
            # the divergence may have been resolved by another mount
            # (the CLI heals on its own client); un-fence local writes
            self._sb_cache.discard(bytes(loc.gfid))
        clean = {i: m for i, m in innocent.items() if m["dirty"] == (0, 0)}
        pool = clean or innocent
        best = max(m["version"] for m in pool.values())
        return [i for i, m in pool.items() if m["version"] == best]

    def _is_local_child(self, i: int) -> bool:
        """No protocol/client anywhere below child i (choose-local)."""
        cache = getattr(self, "_local_map", None)
        if cache is None:
            from ..core.layer import walk

            cache = self._local_map = [
                all(l.type_name != "protocol/client"
                    for l in walk(ch)) for ch in self.children]
        return cache[i]

    def _read_child(self, candidates: list[int], gfid: bytes) -> int:
        if not candidates:
            raise FopError(errno.ENOTCONN, "no consistent child")
        if self.opts["quorum-reads"] and \
                not self._quorum_met(set(self._up_idx())):
            # cluster.quorum-reads: a partitioned minority side must
            # not serve possibly-stale data either
            raise FopError(errno.ENOTCONN, "quorum-reads: no quorum")
        # explicit pins first (cluster.read-subvolume[-index])
        pin = self.opts["read-subvolume-index"]
        if pin >= 0 and pin in candidates:
            return pin
        by_name = self.opts["read-subvolume"]
        if by_name:
            for i in candidates:
                if self.children[i].name == by_name:
                    return i
        if self.opts["choose-local"]:
            # cluster.choose-local: a wire-free child beats any policy
            # pick — its reads never pay an RTT
            locals_ = [i for i in candidates if self._is_local_child(i)]
            if locals_ and not all(self._is_local_child(i)
                                   for i in candidates):
                candidates = locals_
        mode = self.opts["read-hash-mode"]
        if mode == "first-up":
            return candidates[0]
        if mode == "gfid-hash":
            return candidates[int.from_bytes(gfid[-4:], "big")
                              % len(candidates)]
        self._rr = (self._rr + 1) % len(candidates)
        return candidates[self._rr]

    # -- transaction locks (same skeleton as EC) ---------------------------

    async def _inodelk_wind(self, loc: Loc, ltype: str) -> list[int]:
        if self._locks_supported is False:
            return []
        xd = {"lk-owner": self._lk_owner}
        locked: list[int] = []
        try:
            for i in self._up_idx():
                try:
                    await self.children[i].inodelk(
                        "afr.transaction", loc, "lock", ltype, 0, -1, xd)
                    locked.append(i)
                except FopError as e:
                    if e.err == errno.EOPNOTSUPP:
                        continue
                    raise
        except FopError:
            await self._inodelk_unwind(loc, locked)
            raise
        if self._locks_supported is None:
            self._locks_supported = bool(locked)
        return locked

    async def _inodelk_unwind(self, loc: Loc, locked: list[int]) -> None:
        xd = {"lk-owner": self._lk_owner}
        for i in locked:
            try:
                await self.children[i].inodelk(
                    "afr.transaction", loc, "unlock", "wr", 0, -1, xd)
            except FopError:
                pass

    class _Txn:
        def __init__(self, afr: "ReplicateLayer", loc: Loc, gfid: bytes,
                     ltype: str = "wr"):
            self.afr = afr
            self.loc = loc
            self.gfid = gfid
            self.ltype = ltype
            self.locked: list[int] = []
            self.local = ltype == "wr" or afr._locks_supported is False

        async def __aenter__(self):
            if self.local:
                await self.afr._lock(self.gfid).acquire()
            try:
                self.locked = await self.afr._inodelk_wind(self.loc,
                                                           self.ltype)
            except BaseException:
                if self.local:
                    self.afr._lock(self.gfid).release()
                raise
            if not self.locked and not self.local:
                self.local = True
                await self.afr._lock(self.gfid).acquire()
            return self

        async def __aexit__(self, *exc):
            await self.afr._inodelk_unwind(self.loc, self.locked)
            if self.local:
                self.afr._lock(self.gfid).release()
            return False

    # -- namespace fops ----------------------------------------------------

    def _pick(self, good: dict):
        """Representative answer: never the arbiter's if a data
        replica answered — its iatt carries size 0 for every file."""
        for i in sorted(good):
            if i not in self.arbiters:
                return good[i]
        return next(iter(good.values()))

    async def _all(self, op: str, *args, **kw):
        res = await self._dispatch(self._up_idx(), op, lambda i: (args, kw))
        good = self._combine(res)
        return self._pick(good)

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "lookup",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        return self._pick(good)

    async def stat(self, loc: Loc, xdata: dict | None = None):
        rows = [i for i in await self._good_rows(loc)
                if i not in self.arbiters]
        if not rows:
            raise FopError(errno.ENOTCONN,
                           "no data replica for stat (arbiter only)")
        return await self.children[rows[0]].stat(loc, xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        return await self.stat(Loc(fd.path, gfid=fd.gfid), xdata)

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("mkdir", loc, mode, xdata)

    async def mknod(self, loc: Loc, mode: int = 0o644, rdev: int = 0,
                    xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("mknod", loc, mode, rdev, xdata)

    async def symlink(self, target: str, loc: Loc, xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("symlink", target, loc, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        return await self._all("unlink", loc, xdata)

    async def rmdir(self, loc: Loc, flags: int = 0,
                    xdata: dict | None = None):
        return await self._all("rmdir", loc, flags, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        return await self._all("rename", oldloc, newloc, xdata)

    async def link(self, oldloc: Loc, newloc: Loc,
                   xdata: dict | None = None):
        return await self._all("link", oldloc, newloc, xdata)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        rows = await self._good_rows(loc)
        return await self.children[rows[0]].readlink(loc, xdata)

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        return await self._all("setattr", loc, attrs, valid, xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if any(k.startswith("trusted.afr.") for k in xattrs):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._all("setxattr", loc, xattrs, flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        rows = await self._good_rows(loc)
        out = await self.children[rows[0]].getxattr(loc, name, xdata)
        return {k: v for k, v in out.items()
                if not k.startswith("trusted.afr.")} if name is None else out

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        if name.startswith("trusted.afr."):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._all("removexattr", loc, name, xdata)

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "statfs",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        return min(good.values(), key=lambda s: s["bavail"] * s["bsize"])

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "opendir",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        fd = FdObj(next(iter(good.values())).gfid, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(dict(good), 0))
        return fd

    def _child_fd(self, fd: FdObj, i: int) -> FdObj:
        ctx: AfrFdCtx | None = fd.ctx_get(self)
        if ctx is None or ctx.child_fds.get(i) is None:
            return FdObj(fd.gfid, fd.flags, path=fd.path, anonymous=True)
        return ctx.child_fds[i]

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        for i in self._up_idx():
            try:
                return await self.children[i].readdir(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError:
                continue
        raise FopError(errno.ENOTCONN, "no child for readdir")

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        for i in self._up_idx():
            try:
                return await self.children[i].readdirp(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError:
                continue
        raise FopError(errno.ENOTCONN, "no child for readdirp")

    # -- open / create -----------------------------------------------------

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        res = await self._dispatch(self._up_idx(), "create",
                                   lambda i: ((loc, flags, mode, xdata), {}))
        good = self._combine(res)
        child_fds = {i: r[0] for i, r in good.items()}
        ia = next(iter(good.values()))[1]
        zero = {XA_VERSION: _pack_u64x2(0, 0), XA_DIRTY: _pack_u64x2(0, 0)}
        await self._dispatch(list(good), "setxattr",
                             lambda i: ((loc, dict(zero)), {}))
        fd = FdObj(ia.gfid, flags, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(child_fds, flags))
        return fd, ia

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "open",
                                   lambda i: ((loc, flags), {}))
        good = self._combine(res, min_ok=1)
        fd = FdObj(next(iter(good.values())).gfid, flags, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(dict(good), flags))
        return fd

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        await self._dispatch(self._up_idx(), "flush",
                             lambda i: ((self._child_fd(fd, i),), {}))
        return {}

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        res = await self._dispatch(
            self._up_idx(), "fsync",
            lambda i: ((self._child_fd(fd, i), datasync), {}))
        self._combine(res)
        return {}

    async def release(self, fd: FdObj):
        ctx: AfrFdCtx | None = fd.ctx_del(self)
        if ctx:
            for i, cfd in ctx.child_fds.items():
                rel = getattr(self.children[i], "release", None)
                if rel:
                    try:
                        await rel(cfd)
                    except Exception:
                        pass

    # -- data path ---------------------------------------------------------

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        candidates = [i for i in await self._good_rows(loc)
                      if i not in self.arbiters]
        if not candidates:
            raise FopError(errno.ENOTCONN,
                           "no data replica readable (arbiter only)")
        last: FopError | None = None
        for _ in range(len(candidates)):
            i = self._read_child(candidates, fd.gfid)
            try:
                return await self.children[i].readv(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError as e:
                last = e
                candidates = [c for c in candidates if c != i]
                if not candidates:
                    break
        raise last or FopError(errno.ENOTCONN, "read failed")

    async def _write_txn(self, loc: Loc, gfid: bytes, op: str, argfn):
        """The replicated write transaction (afr-transaction.c:1087,629):
        pre-op dirty on all up replicas, dispatch, quorum, post-op
        version bump on the good ones — dirty is released only when
        EVERY replica took the write (a partial success keeps the mark,
        and the brick-side pending-index entry, for the shd)."""
        if gfid and bytes(gfid) in self._sb_cache:
            raise FopError(errno.EIO,
                           f"{loc.path}: split-brain (resolve first)")
        async with self._Txn(self, loc, gfid, "wr"):
            idxs = self._up_idx()
            if self.ta is not None and len(idxs) < self.n:
                if not idxs:
                    # never brand with no survivor: marking both
                    # replicas would poison every future degraded read
                    raise FopError(errno.ENOTCONN,
                                   f"{op}: no data replica up")
                # tie-breaker gate: the lone survivor may take writes
                # only after branding the absent replica bad — and
                # never if it is itself the branded one.  Marks are
                # RE-READ every degraded write (another mount's heal
                # may have cleared a brand this client cached); only
                # the branding WRITE is skipped when already present.
                down = [j for j in range(self.n) if j not in idxs]
                marks = await self._ta_marks()
                if any(i in marks for i in idxs):
                    raise FopError(errno.EIO,
                                   f"{op}: this replica is marked "
                                   f"bad on the thin-arbiter")
                need = [j for j in down if j not in marks]
                if need:
                    await self._ta_mark_bad(need)
            await self._dispatch(
                idxs, "xattrop",
                lambda i: ((loc, "add64",
                            {XA_DIRTY: _pack_u64x2(1, 0)}), {}))
            res = await self._dispatch(idxs, op, argfn)
            good = [i for i, r in res.items()
                    if not isinstance(r, BaseException)]
            if self.ta is not None:
                # thin-arbiter volumes: ANY lone survivor may ack, but
                # only after branding the replicas that missed the
                # write on the tie-breaker — an unbranded missed
                # replica could later return alone, find no mark
                # against itself, and accept writes (mutual-blame
                # split-brain).  Covers both the pre-granted path
                # (len(idxs) < n) and mid-write failures of EITHER
                # brick, including tie-winning brick 0.
                failed = [i for i in idxs if i not in good]
                met = len(good) >= 1
                if met and failed:
                    try:
                        # ALWAYS re-read the tie-breaker (one RTT, not
                        # cached): another mount's heal may have
                        # cleared a mark this client cached, and a
                        # survivor that is ITSELF marked bad must not
                        # take writes — acking onto it puts the only
                        # copy of new data on a replica heal will
                        # overwrite
                        marks = await self._ta_marks()
                        if any(i in marks for i in good):
                            raise FopError(
                                errno.EIO, "surviving replica is "
                                "marked bad on the thin-arbiter")
                        need = [i for i in failed if i not in marks]
                        if need:  # write RTT only when mark is absent
                            await self._ta_mark_bad(need)
                    except FopError:
                        met = False
            else:
                met = self._quorum_met(set(good))
            if not met:
                raise FopError(errno.EIO,
                               f"{op} quorum lost ({len(good)}/{self.n})")
            post = {XA_VERSION: _pack_u64x2(1, 0)}
            if len(good) == self.n:
                post[XA_DIRTY] = _pack_u64x2(-1 & 0xFFFFFFFFFFFFFFFF, 0)
            else:
                # blame every replica that missed this write (down or
                # failed): the survivors' pending.<j> counters are what
                # heal reads as direction, and mutual marks from
                # partitioned writes are the split-brain signature
                # (afr_set_pending_dict, afr-transaction.c:629)
                for j in range(self.n):
                    if j not in good:
                        post[XA_PENDING + str(j)] = _pack_u64x2(1, 0)
            await self._dispatch(
                good, "xattrop", lambda i: ((loc, "add64", dict(post)), {}))
            return next(r for i, r in res.items() if i in good)

    async def writev(self, fd: FdObj, data: bytes, offset: int,
                     xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        return await self._write_txn(
            loc, fd.gfid, "writev",
            lambda i: ((self._child_fd(fd, i), data, offset), {}))

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # the parity-delta apply is disperse-internal (issued by EC to
        # its own children): the base-class first-child forward would
        # silently diverge the replicas, so refuse loudly instead
        raise FopError(errno.EOPNOTSUPP,
                       f"{self.name}: xorv is disperse-internal")

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        ia, _ = await self.lookup(loc)
        return await self._write_txn(loc, ia.gfid, "truncate",
                                     lambda i: ((loc, size, xdata), {}))

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        return await self.truncate(Loc(fd.path, gfid=fd.gfid), size, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "fallocate",
            lambda i: ((self._child_fd(fd, i), mode, offset, length), {}))

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "discard",
            lambda i: ((self._child_fd(fd, i), offset, length), {}))

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "zerofill",
            lambda i: ((self._child_fd(fd, i), offset, length), {}))

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        candidates = [i for i in await self._good_rows(loc)
                      if i not in self.arbiters]
        last: FopError | None = None
        for i in candidates:
            try:
                return await self.children[i].seek(
                    self._child_fd(fd, i), offset, what, xdata)
            except FopError as e:
                if e.err == errno.ENXIO:
                    raise
                last = e
        raise last or FopError(errno.ENOTCONN, "no child for seek")

    # -- heal --------------------------------------------------------------

    async def heal_info(self, loc: Loc) -> dict:
        """Heal direction from the blame matrix, then committed version
        — never clean-ness: a brick that slept through the write is
        spotlessly clean AND stale.  Sources are reachable bricks no
        peer blames, at the highest post-op version
        (afr_selfheal_find_direction); mutual blame with no innocent
        brick is split-brain.  Dirty marks on sources are expected
        after a partial write and do not disqualify them."""
        meta = await self._get_meta(list(range(self.n)), loc)
        vals = {i: m for i, m in meta.items()
                if not isinstance(m, BaseException)}
        per_brick = {i: ((m["version"], m["dirty"], m["pending"])
                         if not isinstance(m, BaseException) else None)
                     for i, m in meta.items()}
        if not vals:
            raise FopError(errno.ENOTCONN, "no bricks reachable")
        accused = self._accused(vals)
        innocent = {i: m for i, m in vals.items() if i not in accused}
        split = not innocent
        if split:
            good: list[int] = []
            best = max(m["version"] for m in vals.values())
        else:
            best = max(m["version"] for m in innocent.values())
            good = [i for i, m in innocent.items()
                    if m["version"] == best]
        bad = [i for i in range(self.n) if i not in good]
        dirty = any(m["dirty"] != (0, 0) for m in vals.values())
        return {"good": good, "bad": bad, "version": best,
                "per_brick": per_brick, "dirty": dirty,
                "split_brain": split, "accused": sorted(accused)}

    def _policy_pick(self, stats: dict[int, "Iatt"], policy: str) -> int:
        """Choose a split-brain source per favorite-child-policy
        (afr_sh_get_fav_by_policy): biggest file, latest mtime, or the
        most common (size, mtime) copy."""
        if not stats:
            raise FopError(errno.ENOTCONN, "no replica stat-able")
        if policy == "size":
            return max(stats, key=lambda i: stats[i].size)
        if policy == "mtime":
            return max(stats, key=lambda i: (stats[i].mtime, i))
        if policy == "majority":
            groups: dict[tuple, list[int]] = {}
            for i, ia in stats.items():
                groups.setdefault((ia.size, ia.mtime), []).append(i)
            members = max(groups.values(), key=len)
            if len(members) * 2 > len(stats):
                return members[0]
        raise FopError(errno.EIO, "no policy winner")

    async def _policy_stats(self, loc: Loc) -> dict:
        stats = {}
        for i in self._up_idx():
            if i in self.arbiters:
                continue  # 0-byte witness: never a policy winner
            try:
                stats[i] = await self.children[i].stat(loc)
            except FopError:
                continue
        return stats

    async def split_brain_resolve(self, path: str, policy: str,
                                  source: int = -1) -> dict:
        """glfs-heal.c split-brain resolution: bigger-file |
        latest-mtime | source-brick <idx>.  Copies the chosen replica
        over the others and clears the mutual blame."""
        loc = Loc(path)
        info = await self.heal_info(loc)
        if not info["split_brain"] and policy != "source-brick":
            raise FopError(errno.EINVAL,
                           f"{path} is not in split-brain")
        if policy == "source-brick":
            if source not in range(self.n):
                raise FopError(errno.EINVAL, f"bad source {source}")
            src = source
        else:
            stats = await self._policy_stats(loc)
            key = {"bigger-file": "size",
                   "latest-mtime": "mtime"}.get(policy, policy)
            src = self._policy_pick(stats, key)
        return await self.heal_file(path, source=src)

    async def heal_file(self, path: str, source: int = -1) -> dict:
        loc = Loc(path)
        info = await self.heal_info(loc)
        good, bad = info["good"], info["bad"]
        if info["split_brain"] and source < 0:
            # automatic resolution only under an explicit policy
            policy = self.opts["favorite-child-policy"]
            fav = self.opts["favorite-child"]
            if policy != "none":
                source = self._policy_pick(
                    await self._policy_stats(loc), policy)
            elif fav >= 0:
                source = fav
            else:
                raise FopError(errno.EIO,
                               f"{path}: split-brain; resolve with heal "
                               f"split-brain or favorite-child-policy")
        if source >= 0:
            good = [source]
            bad = [i for i in range(self.n) if i != source]
        if not good:
            raise FopError(errno.EIO, "no heal source")
        fav = self.opts["favorite-child"]
        data_good = [i for i in good if i not in self.arbiters]
        if not data_good:
            raise FopError(errno.EIO,
                           "no data replica to heal from (arbiter only)")
        src = fav if fav in data_good else data_good[0]
        if not bad:
            if not info.get("dirty"):
                return {"healed": [], "skipped": True}
            # Dirty with equal versions can hide diverged content (a
            # quorum-lost write data-lands on some replicas before the
            # fop fails, with no post-op anywhere).  Re-copy from one
            # source instead of just unmarking (afr data heal re-runs
            # whenever dirty is set).
            bad = [i for i in good if i != src]
            good = [src]
            if not bad:
                return {"healed": [], "skipped": True}
        ia, _ = await self.lookup(loc)
        async with self._Txn(self, loc, ia.gfid, "wr"):
            src_ia = await self.children[src].stat(loc)
            # ensure file exists on bad bricks
            for i in bad:
                try:
                    await self.children[i].lookup(loc)
                except FopError:
                    try:
                        await self.children[i].mknod(
                            loc, src_ia.mode, 0, {"gfid-req": ia.gfid})
                    except FopError:
                        continue
            window = int(self.opts["self-heal-window-size"])
            sfd = FdObj(ia.gfid, path=path, anonymous=True)
            off = 0
            from ..features.bit_rot_stub import HEAL_WRITE

            # arbiter sinks take only the metadata fix below, no data
            data_bad = [i for i in bad if i not in self.arbiters]
            if not self.opts["data-self-heal"]:
                data_bad = []  # cluster.data-self-heal off
            diff = self.opts["data-self-heal-algorithm"] == "diff"
            while data_bad and off < src_ia.size:
                blk = min(window, src_ia.size - off)
                if diff:
                    # rchecksum handshake first (afr_selfheal_data
                    # block compare): byte-identical windows are
                    # skipped instead of shipped — most of a file
                    # usually matches.  algorithm=full skips the
                    # handshake and copies every window.
                    src_ck = await self.children[src].rchecksum(
                        sfd, off, blk)
                    cks = await self._dispatch(
                        data_bad, "rchecksum",
                        lambda i: ((FdObj(ia.gfid, path=path,
                                          anonymous=True), off, blk),
                                   {}))
                    need = [i for i in data_bad
                            if isinstance(cks.get(i), BaseException)
                            or cks[i].get("strong") != src_ck["strong"]
                            or cks[i].get("len") != src_ck["len"]]
                else:
                    need = list(data_bad)
                if need:
                    chunk = await self.children[src].readv(sfd, blk,
                                                           off)
                    await self._dispatch(
                        need, "writev",
                        lambda i: ((FdObj(ia.gfid, path=path,
                                          anonymous=True), chunk, off),
                                   {"xdata": {HEAL_WRITE: True}}))
                off += blk
            if data_bad:
                await self._dispatch(data_bad, "truncate",
                                     lambda i: ((loc, src_ia.size), {}))
                if self.opts["ensure-durability"]:
                    # cluster.ensure-durability: the rebuilt bytes are
                    # ON DISK before counters say "healed" — a crash
                    # right after must not resurrect the divergence
                    await self._dispatch(
                        data_bad, "fsync",
                        lambda i: ((FdObj(ia.gfid, path=path,
                                          anonymous=True), 0), {}))
            if self.opts["metadata-self-heal"] and bad:
                # cluster.metadata-self-heal: sinks adopt the source's
                # mode + times (afr_selfheal_metadata)
                await self._dispatch(
                    bad, "setattr",
                    lambda i: ((loc, {"mode": src_ia.mode & 0o7777,
                                      "mtime": src_ia.mtime}), {}))
            meta = await self._get_meta([src], loc)
            zero_pend = {XA_PENDING + str(j): _pack_u64x2(0, 0)
                         for j in range(self.n)}
            # healed sinks: adopt the source's version, drop dirty AND
            # their stale accusations of others
            fix = {XA_VERSION: _pack_u64x2(*meta[src]["version"]),
                   XA_DIRTY: _pack_u64x2(0, 0), **zero_pend}
            fres = await self._dispatch(bad, "setxattr",
                                        lambda i: ((loc, dict(fix)), {}))
            healed = [i for i in bad
                      if not isinstance(fres.get(i), BaseException)]
            failed = [i for i in bad if i not in healed]
            # sources keep blaming sinks whose heal did NOT land —
            # clearing their pending (or their thin-arbiter brand)
            # would let an unhealed stale replica serve alone later
            keep = {XA_PENDING + str(j) for j in failed}
            await self._dispatch(good, "setxattr", lambda i: (
                (loc, {XA_DIRTY: _pack_u64x2(0, 0),
                       **{k: v for k, v in zero_pend.items()
                          if k not in keep}}), {}))
            if not failed:
                self._sb_cache.discard(bytes(ia.gfid))
            await self._ta_clear(healed)
            return {"healed": healed, "failed": failed,
                    "skipped": False, "source": src}

    async def heal_entry(self, path: str = "/") -> dict:
        """Directory entry heal: union the listings, copy missing entries
        from any brick that has them (afr-self-heal-entry.c)."""
        if not self.opts["entry-self-heal"]:
            return {"healed": [], "skipped": True}  # cluster.entry-self-heal
        loc = Loc(path)
        listings: dict[int, set[str]] = {}
        for i in self._up_idx():
            try:
                fd = await self.children[i].opendir(loc)
                names = await self.children[i].readdir(fd)
                listings[i] = {n for n, _ in names}
            except FopError:
                continue
        union: set[str] = set().union(*listings.values()) if listings else set()
        created = []
        for name in union:
            child_path = path.rstrip("/") + "/" + name
            have = [i for i, names in listings.items() if name in names]
            missing = [i for i in listings if name not in listings[i]]
            if not missing:
                continue
            src = have[0]
            src_ia = await self.children[src].stat(Loc(child_path))
            for i in missing:
                try:
                    if src_ia.ia_type is IAType.DIR:
                        await self.children[i].mkdir(
                            Loc(child_path), src_ia.mode,
                            {"gfid-req": src_ia.gfid})
                    else:
                        await self.children[i].mknod(
                            Loc(child_path), src_ia.mode, 0,
                            {"gfid-req": src_ia.gfid})
                    created.append((i, name))
                except FopError:
                    continue
            if src_ia.ia_type is not IAType.DIR:
                try:
                    await self.heal_file(child_path)
                except FopError:
                    # a split-brained (or unreachable) file must not
                    # stop the rest of the directory from healing
                    continue
        return {"created": created}

    def dump_private(self) -> dict:
        return {"replicas": self.n, "up": self.up,
                "quorum": self._quorum(),
                "read_hash_mode": self.opts["read-hash-mode"]}
