"""cluster/switch — pattern-routed distribute variant.

Reference: xlators/cluster/dht/src/switch.c — files whose basename
matches a glob pattern are created on a named subset of subvolumes
(option ``pattern.switch.case`` = ``pat:sub1|sub2;pat2:sub3``); the
rest follow normal DHT hashing.  Lookup still resolves anywhere via
the hashed linkto pointer, so routing only shapes placement.
"""

from __future__ import annotations

import fnmatch

from ..core.layer import Loc, register
from ..core.options import Option
from .dht import DistributeLayer, dm_hash


@register("cluster/switch")
class SwitchLayer(DistributeLayer):
    OPTIONS = DistributeLayer.OPTIONS + (
        Option("pattern-switch-case", "str", default="",
               description="';'-separated glob:subvol[|subvol...] "
               "placement rules (switch.c pattern.switch.case)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        byname = {c.name: i for i, c in enumerate(self.children)}
        self._rules: list[tuple[str, list[int]]] = []
        spec = self.opts["pattern-switch-case"].strip()
        if spec:
            for rule in spec.split(";"):
                rule = rule.strip()
                if not rule:
                    continue
                pat, _, subs = rule.partition(":")
                idxs = []
                for s in subs.split("|"):
                    s = s.strip()
                    if s not in byname:
                        raise ValueError(f"{self.name}: rule "
                                         f"{rule!r}: no child {s!r}")
                    idxs.append(byname[s])
                if not idxs:
                    raise ValueError(f"{self.name}: rule {rule!r} "
                                     "names no subvolumes")
                self._rules.append((pat.strip(), idxs))

    def _rule_idx(self, loc: Loc) -> int | None:
        name = loc.name or loc.path.rsplit("/", 1)[-1]
        for pat, idxs in self._rules:
            live = [i for i in idxs if i in self._active]
            if live and fnmatch.fnmatch(name, pat):
                # hash WITHIN the matched set so multi-subvol rules
                # still spread load (switch_local scheduling)
                return live[dm_hash(name) % len(live)]
        return None

    async def _sched(self, loc: Loc) -> int:
        idx = self._rule_idx(loc)
        return idx if idx is not None else await self._placed(loc)
