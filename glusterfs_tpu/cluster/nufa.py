"""cluster/nufa — Non-Uniform File Access distribute variant.

Reference: xlators/cluster/dht/src/nufa.c — same layout/lookup engine
as DHT, but NEW files are created on the *local* subvolume (the brick
on the creating node; option ``local-volume-name``), with a linkto
pointer left on the hashed subvolume so every other client still
resolves the file (nufa_create -> dht_linkfile semantics).  Built for
compute-on-storage deployments where a node mostly reads what it
wrote.
"""

from __future__ import annotations

from ..core.layer import Loc, register
from ..core.options import Option
from .dht import DistributeLayer


@register("cluster/nufa")
class NufaLayer(DistributeLayer):
    OPTIONS = DistributeLayer.OPTIONS + (
        Option("local-volume-name", "str", default="",
               description="child subvolume that receives new files "
               "(nufa.c local-volume-name; defaults to the first "
               "child, the in-process stand-in for 'this node's "
               "brick')"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._local = 0
        want = self.opts["local-volume-name"]
        if want:
            for i, c in enumerate(self.children):
                if c.name == want:
                    self._local = i
                    break
            else:
                raise ValueError(
                    f"{self.name}: no child named {want!r}")

    async def _sched(self, loc: Loc) -> int:
        if self._local in self._active:
            return self._local
        return await self._placed(loc)  # local brick is being removed
