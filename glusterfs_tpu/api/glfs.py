"""Embeddable client API — the libgfapi analog.

Reference: api/src/glfs.c (glfs_new/init/fini, glfs.c:835,1140) and the
132 ``glfs_*`` calls in glfs.h.  A :class:`Client` wraps an activated
layer graph and exposes file operations; :class:`SyncClient` is the
synchronous facade (the reference's SYNCOP/ucontext machinery,
syncop.c:263, becomes an event loop on a worker thread).

Path resolution walks components through ``lookup`` with an inode/dentry
cache (glfs-resolve.c analog).

The handle-based surface (``h_*``, reference api/src/glfs-handles.h:
glfs_h_lookupat/extract_handle/create_from_handle/open/...) is what
NFS-Ganesha-class consumers build on: a :class:`Handle` is a portable
16-byte gfid — extract it on one client, reconstruct it on another, and
address the object without any path.  Handle ops resolve gfid -> current
volume path through the bricks' gfid2path records, so they keep working
across renames.  See docs/gfapi_coverage.md for the symbol map.
"""

from __future__ import annotations

import asyncio
import errno
import os
import threading
from typing import Any

from ..core.fops import FopError
from ..core.graph import Graph
from ..core.iatt import Iatt, ROOT_GFID
from ..core.inode import InodeTable
from ..core.layer import Event, FdObj, Loc, walk

# one-shot whole-file read window (readv truncates at EOF); files larger
# than this continue in a loop.  Kept moderate: page-granular perf
# layers walk `size/page` bookkeeping loops per request
_READ_ALL = 64 << 20


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    out = os.path.normpath(path)
    return "/" if out in (".", "//") else out


def _split(path: str) -> tuple[str, str]:
    path = _norm(path)
    if path == "/":
        return "", "/"
    parent, name = path.rsplit("/", 1)
    return (parent or "/"), name


async def _drain_graph(graph: Graph, timeout: float = 10.0) -> None:
    """Wait until the graph's transports have no in-flight RPCs for a
    few consecutive ticks — multi-RPC fops mid-flight get scheduler
    turns to issue their next call before the graph is retired."""
    from ..protocol.client import ClientLayer

    clients = [l for l in graph.by_name.values()
               if isinstance(l, ClientLayer)]
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    streak = 0
    while loop.time() < deadline:
        if any(l._pending for l in clients):
            streak = 0
        else:
            streak += 1
            if streak >= 3:
                return
        await asyncio.sleep(0.05)


async def wait_connected(graph: Graph, timeout: float = 15.0) -> bool:
    """Poll until every protocol/client layer in the graph has finished
    its handshake (the reference blocks the mount until CHILD_UP reaches
    the top).  Returns whether all connected within the deadline."""
    from ..protocol.client import ClientLayer

    prot = [l for l in graph.by_name.values()
            if isinstance(l, ClientLayer)]
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if all(p.connected for p in prot):
            return True
        await asyncio.sleep(0.05)
    return all(p.connected for p in prot)


class Handle:
    """Opaque portable file handle (glfs-handles.h glfs_object analog):
    the 16-byte gfid.  Extract with :meth:`Client.h_extract`, rebuild
    anywhere with :meth:`Client.h_create_from_handle`."""

    __slots__ = ("gfid",)

    def __init__(self, gfid: bytes):
        self.gfid = bytes(gfid)

    def __eq__(self, other) -> bool:
        return isinstance(other, Handle) and self.gfid == other.gfid

    def __hash__(self) -> int:
        return hash(self.gfid)

    def __repr__(self) -> str:
        return f"Handle({self.gfid.hex()})"


class File:
    """An open file (glfs_fd_t analog)."""

    def __init__(self, client: "Client", fd: FdObj, path: str):
        self._client = client
        self.fd = fd
        self.path = path
        self.closed = False
        self._dirty = False  # any write/truncate since open

    async def read(self, size: int, offset: int = 0) -> bytes:
        data = await self._client.graph.top.readv(self.fd, size, offset)
        # glfs_read hands the caller plain bytes; a memoryview off the
        # wire blob lane must not escape the library boundary (it pins
        # its RPC frame and breaks bytes-only callers)
        return data if isinstance(data, bytes) else bytes(data)

    async def write(self, data: bytes, offset: int = 0) -> int:
        self._dirty = True
        await self._client.graph.top.writev(self.fd, bytes(data), offset)
        return len(data)

    async def fstat(self) -> Iatt:
        return await self._client.graph.top.fstat(self.fd)

    async def fsync(self, datasync: bool = False) -> None:
        await self._client.graph.top.fsync(self.fd, int(datasync))

    async def ftruncate(self, size: int) -> None:
        self._dirty = True
        await self._client.graph.top.ftruncate(self.fd, size)

    async def fgetxattr(self, name: str | None = None):
        return await self._client.graph.top.fgetxattr(self.fd, name)

    async def fsetxattr(self, xattrs: dict, flags: int = 0) -> None:
        await self._client.graph.top.fsetxattr(self.fd, xattrs, flags)

    async def fremovexattr(self, name: str) -> None:
        await self._client.graph.top.fremovexattr(self.fd, name)

    async def copy_range(self, dst: "File", size: int,
                         src_offset: int = 0, dst_offset: int = 0,
                         window: int = 1 << 20) -> int:
        """glfs_copy_file_range analog: windowed read+write composition
        (no dedicated fop; the reference's also degrades to this when
        the backend lacks the syscall)."""
        if dst.fd.gfid == self.fd.gfid and \
                src_offset < dst_offset + size and \
                dst_offset < src_offset + size:
            # copy_file_range(2): overlapping same-file ranges are
            # EINVAL — windowed copying would re-read its own writes
            raise FopError(errno.EINVAL,
                           "overlapping copy_range on one file")
        done = 0
        while done < size:
            chunk = await self.read(min(window, size - done),
                                    src_offset + done)
            if not chunk:
                break
            await dst.write(chunk, dst_offset + done)
            done += len(chunk)
        return done

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            if self._dirty:
                # flush reports write-back errors at close (POSIX);
                # a read-only fd has nothing to report and skips the
                # fan-out (EC release still drains any eager window)
                await self._client.graph.top.flush(self.fd)
            release = getattr(self._client.graph.top, "release", None)
            if release is not None:
                await release(self.fd)


class HeldLeases:
    """Client-held lease registry (the glfs_lease state, reference
    api/src/glfs-handleops.c glfs_h_lease + leases client tables).

    The perf caches (md-cache/quick-read/io-cache) and the gateway
    object cache key zero-round-trip mode off :meth:`held`: while a
    gfid is here, cached state is served with NO wire revalidation —
    the brick's recall contract is the coherence story.  ``drop`` fires
    its ``on_drop`` callbacks *synchronously*, so everything keyed on
    the lease is gone before the recall is acked back to the brick."""

    __slots__ = ("_m", "on_drop")

    def __init__(self):
        self._m: dict[bytes, tuple[str, str]] = {}  # gfid -> (id, type)
        self.on_drop: list = []  # callbacks fired as (gfid) on drop

    def grant(self, gfid: bytes, lease_id: str, ltype: str) -> None:
        self._m[bytes(gfid)] = (lease_id, ltype)

    def held(self, gfid) -> bool:
        return gfid is not None and bytes(gfid) in self._m

    def get(self, gfid) -> tuple[str, str] | None:
        return self._m.get(bytes(gfid))

    def drop(self, gfid) -> tuple[str, str] | None:
        out = self._m.pop(bytes(gfid), None)
        if out is not None:
            for cb in self.on_drop:
                cb(bytes(gfid))
        return out

    def clear(self) -> None:
        for gfid in list(self._m):
            self.drop(gfid)

    def __len__(self) -> int:
        return len(self._m)


class _UpcallSink:
    """Top-of-graph event tap — the glfs upcall consumer (reference
    api/src/glfs-handleops.c glfs_h_poll_upcall / the mount's
    invalidate callbacks).  A server-pushed cache-invalidation drops
    this client's cached dentry + inode identity so the NEXT resolve
    refetches.  Without it, a second front door on the same volume
    (the object gateway) deleting and recreating a path leaves this
    client resolving the dead gfid out of its itable forever — the
    layer caches (md-cache/io-cache) revalidate on upcall, but the
    api-level dentry cache must too.

    Lease recalls land here LAST: ``notify`` propagates bottom-up, so
    by the time the recall reaches this top-of-graph tap every layer
    cache below has already dropped the gfid's state.  Dropping the
    held-lease entry here and only then scheduling the release ack is
    what makes "drop cached state synchronously before the ack" true
    by construction, not by convention."""

    __slots__ = ("itable", "invalidations", "client")

    def __init__(self, itable: InodeTable, client=None):
        import weakref

        self.itable = itable
        self.invalidations = 0
        self.client = weakref.ref(client) if client is not None else None

    def notify(self, event, source=None, data=None) -> None:
        if event is Event.UPCALL and isinstance(data, dict) and \
                data.get("gfid"):
            self.invalidations += 1
            self.itable.invalidate(data["gfid"])
            client = self.client() if self.client is not None else None
            if client is not None:
                # api-consumer invalidation hooks (the glfs upcall
                # callback surface): the gateway's ETag memo rides
                # here — any out-of-band change to a gfid must dirty
                # derived validators, not just the data caches
                for cb in client.on_invalidate:
                    try:
                        cb(bytes(data["gfid"]))
                    except Exception:  # noqa: BLE001 - tap isolation
                        pass
            if data.get("event") == "lease-recall":
                client = self.client() if self.client is not None \
                    else None
                if client is not None:
                    client._lease_recalled(data["gfid"],
                                           data.get("lease-id", ""),
                                           data.get("reason", ""))
        elif event is Event.CHILD_DOWN and self.client is not None:
            # a dropped connection means the brick is reaping our
            # grants (release_client) and any recall it pushed was
            # lost with the socket — zero-RT mode MUST end locally too
            client = self.client()
            if client is not None and len(client.leases):
                client.leases.clear()


class Client:
    """Async client over an activated graph (glfs_t analog)."""

    def __init__(self, graph: Graph):
        self.graph = graph
        self.itable = InodeTable()
        self.mounted = False
        self.watchers: list = []  # background tasks (volfile watcher)
        self.upcall_sink = _UpcallSink(self.itable, client=self)
        # held-lease registry + this client's lease identity (one id
        # per glfs_t, minted once — the brick keys revocation poisoning
        # on (client, lease-id), so reusing the id across files is
        # fine and across a revoke is caught)
        self.leases = HeldLeases()
        self.lease_id = os.urandom(16).hex()
        # tri-state capability memo: None = unprobed, False = the stack
        # answered ENOTSUP (leases off / old brick) — stop asking
        self._lease_ok: bool | None = None
        self.lease_recalls = 0
        self._lease_tasks: set = set()  # in-flight release acks
        # api-consumer upcall hooks: callbacks fired as (gfid) on every
        # server-pushed invalidation (gateway ETag memo, embedders)
        self.on_invalidate: list = []
        # QoS traffic attribution (features/qos): set BEFORE mount()
        # so the first handshake already carries it; "" = ordinary
        # client, "rebalance" rides the brick's paced lane
        self.traffic_origin = ""

    def _apply_origin(self, top) -> None:
        """Stamp the origin onto every wire layer of a graph (applied
        at mount and re-applied after a reload swap — reconnects then
        re-send it in each fresh handshake's creds)."""
        if not self.traffic_origin:
            return
        for layer in walk(top):
            if hasattr(layer, "traffic_origin"):
                layer.traffic_origin = self.traffic_origin

    def _wire_lease_registry(self, top) -> None:
        """Hand every lease-aware cache layer the held-lease registry
        (zero-RT freshness checks consult it)."""
        for layer in walk(top):
            hook = getattr(layer, "set_lease_registry", None)
            if hook is not None:
                hook(self.leases)

    async def mount(self) -> None:
        # origin stamping precedes activation: the FIRST handshake of
        # every wire layer must already carry the attribution (tagging
        # after connect would leave a race window of unattributed fops)
        self._apply_origin(self.graph.top)
        if not self.graph.active:
            await self.graph.activate()
        if self.upcall_sink not in self.graph.top.parents:
            self.graph.top.parents.append(self.upcall_sink)
        self._wire_lease_registry(self.graph.top)
        self.mounted = True

    async def unmount(self) -> None:
        # cancel AND await the watchers: a mid-flight reload() must
        # finish its cleanup before we fini the graph under it
        for t in self.watchers:
            t.cancel()
        for t in self.watchers:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self.watchers.clear()
        # local lease state dies with the mount — the bricks reap our
        # grants through release_client when the connections drop
        self.leases.clear()
        for t in list(self._lease_tasks):
            t.cancel()
        if self.upcall_sink in self.graph.top.parents:
            self.graph.top.parents.remove(self.upcall_sink)
        if self.graph.active:
            await self.graph.fini()
        self.mounted = False

    async def reload(self, volfile_text: str) -> str:
        """Apply a changed volfile to the live mount (the reference's
        volfile-modified handling, graph.c:980-1089): same topology ->
        per-layer reconfigure in place; topology change -> build and
        activate the new graph, swap it in, retire the old one.  Open
        fds keep working through the new graph: their per-layer contexts
        miss, so every layer falls back to gfid-addressed anonymous fds
        (the reference migrates fds onto the new graph for the same
        reason)."""
        if self.graph.apply_volfile(volfile_text):
            return "reconfigured"
        new = Graph.construct(volfile_text)
        self._apply_origin(new.top)
        await new.activate()
        try:
            await wait_connected(new)
            old, self.graph = self.graph, new
            # the upcall tap follows the live graph (same reason fds
            # migrate: invalidations must keep landing after the swap)
            if self.upcall_sink in old.top.parents:
                old.top.parents.remove(self.upcall_sink)
            new.top.parents.append(self.upcall_sink)
            # leases were granted through the OLD graph's connections —
            # its bricks reap them at disconnect; the new stack starts
            # unleased and re-probes capability
            self.leases.clear()
            self._lease_ok = None
            self._wire_lease_registry(new.top)
        except BaseException:
            # cancelled/failed mid-swap: don't leak the half-built graph
            # (shielded — the fini must run even though we were cancelled)
            await asyncio.shield(new.fini())
            raise
        try:
            # fops that entered through the OLD graph must complete
            # before it is torn down — fini would unwind their in-flight
            # RPCs as spurious ENOTCONN (the reference drains old graphs
            # by refcount before cleanup, graph.c)
            await _drain_graph(old)
        finally:
            await asyncio.shield(old.fini())
        return "swapped"

    # -- resolution --------------------------------------------------------

    async def resolve(self, path: str) -> Loc:
        """Walk path components via lookup, populating the dentry cache."""
        path = _norm(path)
        parent_gfid = ROOT_GFID
        if path == "/":
            return Loc("/", gfid=ROOT_GFID, name="/")
        comps = path.lstrip("/").split("/")
        cur = ""
        gfid = ROOT_GFID
        for comp in comps:
            parent_gfid = gfid
            cur = f"{cur}/{comp}"
            ino = self.itable.find_dentry(parent_gfid, comp)
            if ino is not None:
                gfid = ino.gfid
                continue
            ia, _ = await self.graph.top.lookup(Loc(cur, parent=parent_gfid))
            self.itable.link(parent_gfid, comp, ia.gfid, ia.ia_type, ia)
            gfid = ia.gfid
        return Loc(path, gfid=gfid, parent=parent_gfid)

    async def _parent_loc(self, path: str) -> Loc:
        """Loc for a path that may not exist yet (parent must resolve)."""
        parent, name = _split(path)
        if not parent:
            raise FopError(errno.EINVAL, "cannot operate on /")
        ploc = await self.resolve(parent)
        return Loc(_norm(path), parent=ploc.gfid, name=name)

    # -- leases (glfs_lease analog) ----------------------------------------

    def _peers_lease_capable(self) -> bool:
        """Every protocol client in the stack advertised lease support
        at SETVOLUME (vacuously true for a wire-free local stack)."""
        from ..protocol.client import ClientLayer

        return all(l._peer_leases for l in walk(self.graph.top)
                   if isinstance(l, ClientLayer))

    async def lease_acquire(self, path: str, ltype: str = "rd") -> bool:
        """Take (or keep) a lease on *path*.  True means the caches may
        serve this gfid with zero wire fops until a recall drops it;
        False means the stack can't or won't grant (old brick, leases
        off, conflicting holder) and TTL revalidation stays the story.
        Never raises for "no lease" outcomes — callers treat the lease
        as a performance contract, not a lock."""
        loc = await self.resolve(path)
        gfid = bytes(loc.gfid)
        held = self.leases.get(gfid)
        if held is not None and (held[1] == ltype or held[1] == "rw"):
            return True
        if self._lease_ok is False:
            return False
        if not self._peers_lease_capable():
            self._lease_ok = False
            return False
        try:
            await self.graph.top.lease(loc, "grant", ltype,
                                       self.lease_id)
        except FopError as e:
            if e.err in (errno.ENOTSUP, errno.EOPNOTSUPP):
                self._lease_ok = False  # sticky: stop probing
            return False
        self._lease_ok = True
        self.leases.grant(gfid, self.lease_id, ltype)
        return True

    async def lease_release(self, path: str) -> None:
        """Voluntarily return the lease (and drop everything riding
        on it) — glfs_lease(UNLK)."""
        loc = await self.resolve(path)
        gfid = bytes(loc.gfid)
        held = self.leases.drop(gfid)
        if held is not None:
            await self.graph.top.lease(Loc(path, gfid=loc.gfid),
                                       "release", held[1], held[0])

    def _lease_recalled(self, gfid, lease_id: str,
                        reason: str = "") -> None:
        """Upcall-sink hook: the brick recalled (or expired) our
        lease.  The layer caches already dropped the gfid's state
        during the notify's bottom-up walk; drop the registry entry
        (ending zero-RT mode) and THEN ack by releasing — the brick's
        conflict gate unblocks only after nothing stale can be
        served."""
        gfid = bytes(gfid)
        held = self.leases.drop(gfid)
        if held is None:
            return
        self.lease_recalls += 1
        if reason == "expired":
            return  # the brick already dropped it; nothing to ack
        t = asyncio.ensure_future(
            self._lease_release_ack(gfid, held[1], held[0]))
        self._lease_tasks.add(t)
        t.add_done_callback(self._lease_tasks.discard)

    async def _lease_release_ack(self, gfid: bytes, ltype: str,
                                 lease_id: str) -> None:
        try:
            await self.graph.top.lease(Loc("", gfid=gfid), "release",
                                       ltype, lease_id)
        except Exception:
            pass  # the brick revokes on timeout; our state is gone

    # -- namespace ops -----------------------------------------------------

    async def stat(self, path: str) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.stat(loc)

    async def lookup(self, path: str) -> Iatt:
        loc = await self._parent_loc(path) if path != "/" else Loc("/")
        ia, _ = await self.graph.top.lookup(loc)
        return ia

    async def exists(self, path: str) -> bool:
        try:
            await self.resolve(path)
            return True
        except FopError as e:
            if e.err in (errno.ENOENT, errno.ESTALE):
                return False
            raise

    async def mkdir(self, path: str, mode: int = 0o755) -> Iatt:
        loc = await self._parent_loc(path)
        ia = await self.graph.top.mkdir(loc, mode)
        if hasattr(ia, "gfid"):
            # cache the fresh dentry like create does: the next resolve
            # under this directory must not pay a lookup round trip
            self.itable.link(loc.parent, loc.name, ia.gfid,
                             ia.ia_type, ia)
        return ia

    async def unlink(self, path: str) -> None:
        loc = await self.resolve(path)
        await self.graph.top.unlink(loc)
        self.itable.unlink(loc.parent, loc.name)

    async def rmdir(self, path: str) -> None:
        loc = await self.resolve(path)
        await self.graph.top.rmdir(loc)
        self.itable.unlink(loc.parent, loc.name)

    async def rename(self, old: str, new: str) -> None:
        oldloc = await self.resolve(old)
        newloc = await self._parent_loc(new)
        await self.graph.top.rename(oldloc, newloc)
        self.itable.unlink(oldloc.parent, oldloc.name)
        # a REPLACED destination's cached dentry now names the dead
        # gfid, and this client is the mutation's originator so no
        # upcall will correct it — drop it here
        self.itable.unlink(newloc.parent, newloc.name)

    async def symlink(self, target: str, path: str) -> Iatt:
        loc = await self._parent_loc(path)
        return await self.graph.top.symlink(target, loc)

    async def readlink(self, path: str) -> str:
        loc = await self.resolve(path)
        return await self.graph.top.readlink(loc)

    async def link(self, old: str, new: str) -> Iatt:
        oldloc = await self.resolve(old)
        newloc = await self._parent_loc(new)
        return await self.graph.top.link(oldloc, newloc)

    async def listdir(self, path: str = "/") -> list[str]:
        loc = await self.resolve(path)
        fd = await self.graph.top.opendir(loc)
        entries = await self.graph.top.readdir(fd, 0, 0)
        return [name for name, _ in entries]

    async def listdir_with_stat(self, path: str = "/"):
        loc = await self.resolve(path)
        fd = await self.graph.top.opendir(loc)
        return await self.graph.top.readdirp(fd, 0, 0)

    async def truncate(self, path: str, size: int) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.truncate(loc, size)

    async def statvfs(self, path: str = "/") -> dict:
        loc = await self.resolve(path)
        return await self.graph.top.statfs(loc)

    async def getxattr(self, path: str, name: str | None = None):
        loc = await self.resolve(path)
        return await self.graph.top.getxattr(loc, name)

    async def setxattr(self, path: str, xattrs: dict) -> None:
        loc = await self.resolve(path)
        await self.graph.top.setxattr(loc, xattrs)

    async def setattr(self, path: str, attrs: dict) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.setattr(loc, attrs)

    # -- file ops ------------------------------------------------------------

    def _use_compound(self) -> bool:
        """Is any layer of the mounted graph carrying
        ``compound-fops on``?  (volgen writes the key onto
        protocol/client and write-behind when
        cluster.use-compound-fops is set; re-checked per call so a
        live volume-set flips the fusers immediately.)"""
        from ..core.layer import walk

        for layer in walk(self.graph.top):
            v = layer.opts.get("compound-fops")
            if isinstance(v, str):
                v = v.strip().lower() in ("1", "on", "yes", "true",
                                          "enable", "enabled")
            if v:
                return True
        return False

    def _lazy_open_graph(self) -> bool:
        """Lazy open-behind makes plain open() ZERO round trips; the
        fused lookup+open (one round trip, real fd) would regress it."""
        from ..core.layer import walk

        for layer in walk(self.graph.top):
            if layer.type_name == "performance/open-behind" and \
                    layer.opts.get("lazy-open"):
                return True
        return False

    async def create(self, path: str, flags: int = os.O_RDWR,
                     mode: int = 0o644) -> File:
        loc = await self._parent_loc(path)
        fd, ia = await self.graph.top.create(loc, flags, mode)
        self.itable.link(loc.parent, loc.name, ia.gfid, ia.ia_type, ia)
        return File(self, fd, loc.path)

    async def open(self, path: str, flags: int = os.O_RDWR) -> File:
        if self._use_compound() and _norm(path) != "/" and \
                not self._lazy_open_graph():
            # lookup+open fused: the uncached leaf resolve and the open
            # ride one frame (two waves become one)
            loc = await self._parent_loc(path)
            replies = await self.graph.top.compound([
                ("lookup", (loc,), {}),
                ("open", (loc, flags), {})])
            from ..rpc import compound as cfop

            lk, fd = cfop.unwrap(replies)
            ia = lk[0] if isinstance(lk, (list, tuple)) else lk
            if hasattr(ia, "gfid"):
                self.itable.link(loc.parent, loc.name, ia.gfid,
                                 ia.ia_type, ia)
            return File(self, fd, loc.path)
        loc = await self.resolve(path)
        fd = await self.graph.top.open(loc, flags)
        return File(self, fd, loc.path)

    async def write_file(self, path: str, data: bytes) -> int:
        """Convenience: create/overwrite a file with data.

        Create-first (O_EXCL): the common fresh-file case pays no
        existence probe; an existing file falls back to the
        truncate+open overwrite path on EEXIST.

        With compound fops on, the fresh-file case is ONE chain —
        create+writev+flush+release fused into a single round trip
        where the graph carries it (the smallfile-create hot path)."""
        if self._use_compound():
            from ..rpc import compound as cfop

            loc = await self._parent_loc(path)
            replies = await self.graph.top.compound([
                ("create", (loc, os.O_RDWR | os.O_EXCL, 0o644), {}),
                ("writev", (cfop.FdRef(0), bytes(data), 0), {}),
                ("flush", (cfop.FdRef(0),), {}),
                ("release", (cfop.FdRef(0),), {})])
            err = cfop.first_error(replies)
            if err is None:
                created = replies[0][1]
                ia = created[1] if isinstance(created, (list, tuple)) \
                    and len(created) > 1 else None
                if hasattr(ia, "gfid"):
                    self.itable.link(loc.parent, loc.name, ia.gfid,
                                     ia.ia_type, ia)
                return len(data)
            if err.err != errno.EEXIST:
                raise err
            # existing file: straight to the truncate+open overwrite —
            # the chain already proved EEXIST, re-probing would waste
            # a round trip
            await self.truncate(path, 0)
            f = await self.open(path)
        else:
            try:
                f = await self.create(path, os.O_RDWR | os.O_EXCL)
            except FopError as e:
                if e.err != errno.EEXIST:
                    raise
                await self.truncate(path, 0)
                f = await self.open(path)
        try:
            return await f.write(data, 0)
        finally:
            await f.close()

    async def read_file(self, path: str, offset: int = 0,
                        size: int | None = None):
        """Whole-file read WITHOUT a leading stat wave: readv truncates
        at EOF (POSIX read semantics), so asking for a huge size in one
        call returns the file — the size probe's cluster-wide lookup
        fan-out was pure latency on every read.

        With compound fops on (and no lazy open-behind, whose open is
        already zero round trips), the whole pass is ONE chain —
        lookup+open+readv+release fused into a single round trip where
        the graph carries it (the smallfile-read hot path, the read
        mirror of write_file's create chain).

        Ranged form (``offset``/``size`` given, the glfs_pread window
        analog): the SAME single chain carries the window, and the
        return value is the RAW readv payload — an :class:`wire.SGBuf`
        of wire-frame/page-cache segment views, a memoryview, or bytes
        — NOT joined.  Callers that scatter the bytes onward (the HTTP
        gateway's ``writelines``, os.writev consumers) keep the
        zero-copy lane end to end; ``bytes(result)`` pays the one join
        where plain bytes are demanded.  The default whole-file call
        keeps returning owned ``bytes``."""
        ranged = offset != 0 or size is not None
        want = _READ_ALL if size is None else size
        if want <= 0:
            return b""
        if ranged and size is None:
            # open-ended tail (offset to EOF): loop _READ_ALL windows
            # so a >64MiB tail is never silently truncated, collecting
            # the raw windows into one unjoined segment vector
            from ..rpc.wire import SGBuf

            segs: list = []
            f = await self.open(path, os.O_RDONLY)
            try:
                pos = offset
                while True:
                    data = await self.graph.top.readv(f.fd, _READ_ALL,
                                                      pos)
                    n = len(data)
                    if n:
                        if isinstance(data, SGBuf):
                            segs.extend(data.segments)
                        else:
                            segs.append(data if isinstance(
                                data, memoryview) else memoryview(
                                    bytes(data)))
                    pos += n
                    if n < _READ_ALL:
                        break
            finally:
                await f.close()
            if not segs:
                return b""
            return segs[0] if len(segs) == 1 else SGBuf(segs)
        if self._use_compound() and _norm(path) != "/" and \
                not self._lazy_open_graph():
            from ..rpc import compound as cfop

            loc = await self._parent_loc(path)
            replies = await self.graph.top.compound([
                ("lookup", (loc,), {}),
                ("open", (loc, os.O_RDONLY), {}),
                ("readv", (cfop.FdRef(1), want, offset), {}),
                ("release", (cfop.FdRef(1),), {})])
            err = cfop.first_error(replies)
            if err is not None:
                raise err
            lk = replies[0][1]
            ia = lk[0] if isinstance(lk, (list, tuple)) else lk
            if hasattr(ia, "gfid"):
                self.itable.link(loc.parent, loc.name, ia.gfid,
                                 ia.ia_type, ia)
            data = replies[2][1]
            if ranged:
                return data  # raw window: segments stay unjoined
            out = data if isinstance(data, bytes) else bytes(data)
            if len(out) < _READ_ALL:
                return out
            # improbably huge file: keep the chain's window and read
            # on past it (re-reading from 0 would double the traffic)
            f = await self.open(path, os.O_RDONLY)
            try:
                parts = [out]
                while len(out) == _READ_ALL:
                    out = await f.read(_READ_ALL, sum(map(len, parts)))
                    parts.append(out)
                return b"".join(parts)
            finally:
                await f.close()
        f = await self.open(path, os.O_RDONLY)
        try:
            if ranged:
                # raw window through the graph top (File.read would
                # join to bytes — the ranged contract is segments)
                return await self.graph.top.readv(f.fd, want, offset)
            out = await f.read(_READ_ALL, 0)
            if len(out) < _READ_ALL:
                return out
            parts = [out]  # improbably huge file: keep reading
            while len(out) == _READ_ALL:
                out = await f.read(_READ_ALL, sum(map(len, parts)))
                parts.append(out)
            return b"".join(parts)
        finally:
            await f.close()

    async def removexattr(self, path: str, name: str) -> None:
        loc = await self.resolve(path)
        await self.graph.top.removexattr(loc, name)

    # -- handle-based API (glfs-handles.h: glfs_h_*) ----------------------

    async def h_lookupat(self, path: str) -> "Handle":
        """Path -> portable handle (glfs_h_lookupat + extract)."""
        ia = await self.stat(path)
        return Handle(ia.gfid)

    @staticmethod
    def h_extract(h: "Handle") -> bytes:
        """Handle -> 16 opaque bytes (glfs_h_extract_handle); ship them
        anywhere, rebuild with :meth:`h_create_from_handle`."""
        return bytes(h.gfid)

    async def h_create_from_handle(self, data: bytes) -> "Handle":
        """Opaque bytes -> live handle (glfs_h_create_from_handle);
        verifies the object still exists on this volume."""
        if len(data) != 16:
            raise FopError(errno.EINVAL, "handle must be 16 bytes")
        h = Handle(bytes(data))
        await self.h_stat(h)  # ESTALE/ENOENT if the object is gone
        return h

    async def _h_path(self, h: "Handle") -> str:
        """Current volume path of a handle via the bricks' gfid2path
        records (rename-safe: records track the object, not the name)."""
        from ..storage.posix import XA_GFID2PATH

        if bytes(h.gfid) == bytes(ROOT_GFID):
            return "/"
        out = await self.graph.top.getxattr(Loc("", gfid=h.gfid),
                                            XA_GFID2PATH)
        return out[XA_GFID2PATH].decode()

    async def h_stat(self, h: "Handle") -> Iatt:
        return await self.stat(await self._h_path(h))

    async def h_open(self, h: "Handle", flags: int = os.O_RDWR) -> File:
        return await self.open(await self._h_path(h), flags)

    async def h_opendir(self, h: "Handle") -> list[str]:
        return await self.listdir(await self._h_path(h))

    async def h_creat(self, parent: "Handle", name: str,
                      flags: int = os.O_RDWR,
                      mode: int = 0o644) -> tuple["Handle", File]:
        base = await self._h_path(parent)
        f = await self.create(f"{base.rstrip('/')}/{name}", flags, mode)
        ia = await f.fstat()
        return Handle(ia.gfid), f

    async def h_mkdir(self, parent: "Handle", name: str,
                      mode: int = 0o755) -> "Handle":
        base = await self._h_path(parent)
        ia = await self.mkdir(f"{base.rstrip('/')}/{name}", mode)
        return Handle(ia.gfid)

    async def h_unlink(self, parent: "Handle", name: str) -> None:
        base = await self._h_path(parent)
        await self.unlink(f"{base.rstrip('/')}/{name}")

    async def h_truncate(self, h: "Handle", size: int) -> Iatt:
        return await self.truncate(await self._h_path(h), size)

    async def h_setattrs(self, h: "Handle", attrs: dict) -> Iatt:
        return await self.setattr(await self._h_path(h), attrs)

    async def h_getxattrs(self, h: "Handle", name: str | None = None):
        return await self.getxattr(await self._h_path(h), name)

    async def h_setxattrs(self, h: "Handle", xattrs: dict) -> None:
        await self.setxattr(await self._h_path(h), xattrs)

    async def h_rename(self, src_parent: "Handle", oldname: str,
                       dst_parent: "Handle", newname: str) -> None:
        src = await self._h_path(src_parent)
        dst = await self._h_path(dst_parent)
        await self.rename(f"{src.rstrip('/')}/{oldname}",
                          f"{dst.rstrip('/')}/{newname}")

    async def h_link(self, h: "Handle", dst_parent: "Handle",
                     name: str) -> Iatt:
        base = await self._h_path(dst_parent)
        return await self.link(await self._h_path(h),
                               f"{base.rstrip('/')}/{name}")

    async def h_readlink(self, h: "Handle") -> str:
        return await self.readlink(await self._h_path(h))

    async def h_symlink(self, parent: "Handle", name: str,
                        target: str) -> "Handle":
        base = await self._h_path(parent)
        ia = await self.symlink(target, f"{base.rstrip('/')}/{name}")
        return Handle(ia.gfid)

    def h_root(self) -> "Handle":
        return Handle(bytes(ROOT_GFID))

    async def h_getattrs(self, h: "Handle") -> Iatt:
        return await self.h_stat(h)  # glfs_h_getattrs == stat shape

    async def h_removexattrs(self, h: "Handle", name: str) -> None:
        await self.removexattr(await self._h_path(h), name)

    async def h_statfs(self, h: "Handle") -> dict:
        return await self.statvfs(await self._h_path(h))

    async def h_mknod(self, parent: "Handle", name: str,
                      mode: int = 0o644) -> "Handle":
        base = await self._h_path(parent)
        path = f"{base.rstrip('/')}/{name}"
        loc = await self._parent_loc(path)
        ia = await self.graph.top.mknod(loc, mode, 0)
        self.itable.link(loc.parent, loc.name, ia.gfid, ia.ia_type, ia)
        return Handle(ia.gfid)

    async def h_anonymous_read(self, h: "Handle", size: int,
                               offset: int = 0) -> bytes:
        """One-shot read by handle, no fd held (glfs_h_anonymous_read)."""
        f = await self.h_open(h, os.O_RDONLY)
        try:
            return await f.read(size, offset)
        finally:
            await f.close()

    async def h_anonymous_write(self, h: "Handle", data: bytes,
                                offset: int = 0) -> int:
        f = await self.h_open(h, os.O_RDWR)
        try:
            return await f.write(data, offset)
        finally:
            await f.close()

    # -- introspection -------------------------------------------------------

    def statedump(self) -> dict:
        d = self.graph.statedump()
        d["itable"] = self.itable.dump()
        return d


class SyncClient:
    """Synchronous facade: runs the async client on a private loop thread
    (the reference's syncop/synctask analog, syncop.c:263,602)."""

    def __init__(self, graph: Graph):
        self._client = Client(graph)
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True)
        self._thread.start()

    def _run(self, coro) -> Any:
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    def __getattr__(self, name: str):
        target = getattr(self._client, name)
        if asyncio.iscoroutinefunction(target):
            def call(*a, **kw):
                result = self._run(target(*a, **kw))
                return _SyncFile(self, result) if isinstance(result, File) \
                    else result
            return call
        return target

    def close(self) -> None:
        self._run(self._client.unmount())
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class _SyncFile:
    def __init__(self, owner: SyncClient, f: File):
        self._owner = owner
        self._f = f

    def __getattr__(self, name: str):
        target = getattr(self._f, name)
        if asyncio.iscoroutinefunction(target):
            return lambda *a, **kw: self._owner._run(target(*a, **kw))
        return target
