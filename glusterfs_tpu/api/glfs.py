"""Embeddable client API — the libgfapi analog.

Reference: api/src/glfs.c (glfs_new/init/fini, glfs.c:835,1140) and the
132 ``glfs_*`` calls in glfs.h.  A :class:`Client` wraps an activated
layer graph and exposes file operations; :class:`SyncClient` is the
synchronous facade (the reference's SYNCOP/ucontext machinery,
syncop.c:263, becomes an event loop on a worker thread).

Path resolution walks components through ``lookup`` with an inode/dentry
cache (glfs-resolve.c analog).
"""

from __future__ import annotations

import asyncio
import errno
import os
import threading
from typing import Any

from ..core.fops import FopError
from ..core.graph import Graph
from ..core.iatt import Iatt, ROOT_GFID
from ..core.inode import InodeTable
from ..core.layer import FdObj, Loc


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    out = os.path.normpath(path)
    return "/" if out in (".", "//") else out


def _split(path: str) -> tuple[str, str]:
    path = _norm(path)
    if path == "/":
        return "", "/"
    parent, name = path.rsplit("/", 1)
    return (parent or "/"), name


async def _drain_graph(graph: Graph, timeout: float = 10.0) -> None:
    """Wait until the graph's transports have no in-flight RPCs for a
    few consecutive ticks — multi-RPC fops mid-flight get scheduler
    turns to issue their next call before the graph is retired."""
    from ..protocol.client import ClientLayer

    clients = [l for l in graph.by_name.values()
               if isinstance(l, ClientLayer)]
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    streak = 0
    while loop.time() < deadline:
        if any(l._pending for l in clients):
            streak = 0
        else:
            streak += 1
            if streak >= 3:
                return
        await asyncio.sleep(0.05)


async def wait_connected(graph: Graph, timeout: float = 15.0) -> bool:
    """Poll until every protocol/client layer in the graph has finished
    its handshake (the reference blocks the mount until CHILD_UP reaches
    the top).  Returns whether all connected within the deadline."""
    from ..protocol.client import ClientLayer

    prot = [l for l in graph.by_name.values()
            if isinstance(l, ClientLayer)]
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if all(p.connected for p in prot):
            return True
        await asyncio.sleep(0.05)
    return all(p.connected for p in prot)


class File:
    """An open file (glfs_fd_t analog)."""

    def __init__(self, client: "Client", fd: FdObj, path: str):
        self._client = client
        self.fd = fd
        self.path = path
        self.closed = False

    async def read(self, size: int, offset: int = 0) -> bytes:
        return await self._client.graph.top.readv(self.fd, size, offset)

    async def write(self, data: bytes, offset: int = 0) -> int:
        await self._client.graph.top.writev(self.fd, bytes(data), offset)
        return len(data)

    async def fstat(self) -> Iatt:
        return await self._client.graph.top.fstat(self.fd)

    async def fsync(self, datasync: bool = False) -> None:
        await self._client.graph.top.fsync(self.fd, int(datasync))

    async def ftruncate(self, size: int) -> None:
        await self._client.graph.top.ftruncate(self.fd, size)

    async def fgetxattr(self, name: str | None = None):
        return await self._client.graph.top.fgetxattr(self.fd, name)

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            await self._client.graph.top.flush(self.fd)
            release = getattr(self._client.graph.top, "release", None)
            if release is not None:
                await release(self.fd)


class Client:
    """Async client over an activated graph (glfs_t analog)."""

    def __init__(self, graph: Graph):
        self.graph = graph
        self.itable = InodeTable()
        self.mounted = False
        self.watchers: list = []  # background tasks (volfile watcher)

    async def mount(self) -> None:
        if not self.graph.active:
            await self.graph.activate()
        self.mounted = True

    async def unmount(self) -> None:
        # cancel AND await the watchers: a mid-flight reload() must
        # finish its cleanup before we fini the graph under it
        for t in self.watchers:
            t.cancel()
        for t in self.watchers:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self.watchers.clear()
        if self.graph.active:
            await self.graph.fini()
        self.mounted = False

    async def reload(self, volfile_text: str) -> str:
        """Apply a changed volfile to the live mount (the reference's
        volfile-modified handling, graph.c:980-1089): same topology ->
        per-layer reconfigure in place; topology change -> build and
        activate the new graph, swap it in, retire the old one.  Open
        fds keep working through the new graph: their per-layer contexts
        miss, so every layer falls back to gfid-addressed anonymous fds
        (the reference migrates fds onto the new graph for the same
        reason)."""
        if self.graph.apply_volfile(volfile_text):
            return "reconfigured"
        new = Graph.construct(volfile_text)
        await new.activate()
        try:
            await wait_connected(new)
            old, self.graph = self.graph, new
        except BaseException:
            # cancelled/failed mid-swap: don't leak the half-built graph
            # (shielded — the fini must run even though we were cancelled)
            await asyncio.shield(new.fini())
            raise
        try:
            # fops that entered through the OLD graph must complete
            # before it is torn down — fini would unwind their in-flight
            # RPCs as spurious ENOTCONN (the reference drains old graphs
            # by refcount before cleanup, graph.c)
            await _drain_graph(old)
        finally:
            await asyncio.shield(old.fini())
        return "swapped"

    # -- resolution --------------------------------------------------------

    async def resolve(self, path: str) -> Loc:
        """Walk path components via lookup, populating the dentry cache."""
        path = _norm(path)
        parent_gfid = ROOT_GFID
        if path == "/":
            return Loc("/", gfid=ROOT_GFID, name="/")
        comps = path.lstrip("/").split("/")
        cur = ""
        gfid = ROOT_GFID
        for comp in comps:
            parent_gfid = gfid
            cur = f"{cur}/{comp}"
            ino = self.itable.find_dentry(parent_gfid, comp)
            if ino is not None:
                gfid = ino.gfid
                continue
            ia, _ = await self.graph.top.lookup(Loc(cur, parent=parent_gfid))
            self.itable.link(parent_gfid, comp, ia.gfid, ia.ia_type, ia)
            gfid = ia.gfid
        return Loc(path, gfid=gfid, parent=parent_gfid)

    async def _parent_loc(self, path: str) -> Loc:
        """Loc for a path that may not exist yet (parent must resolve)."""
        parent, name = _split(path)
        if not parent:
            raise FopError(errno.EINVAL, "cannot operate on /")
        ploc = await self.resolve(parent)
        return Loc(_norm(path), parent=ploc.gfid, name=name)

    # -- namespace ops -----------------------------------------------------

    async def stat(self, path: str) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.stat(loc)

    async def lookup(self, path: str) -> Iatt:
        loc = await self._parent_loc(path) if path != "/" else Loc("/")
        ia, _ = await self.graph.top.lookup(loc)
        return ia

    async def exists(self, path: str) -> bool:
        try:
            await self.resolve(path)
            return True
        except FopError as e:
            if e.err in (errno.ENOENT, errno.ESTALE):
                return False
            raise

    async def mkdir(self, path: str, mode: int = 0o755) -> Iatt:
        loc = await self._parent_loc(path)
        return await self.graph.top.mkdir(loc, mode)

    async def unlink(self, path: str) -> None:
        loc = await self.resolve(path)
        await self.graph.top.unlink(loc)
        self.itable.unlink(loc.parent, loc.name)

    async def rmdir(self, path: str) -> None:
        loc = await self.resolve(path)
        await self.graph.top.rmdir(loc)
        self.itable.unlink(loc.parent, loc.name)

    async def rename(self, old: str, new: str) -> None:
        oldloc = await self.resolve(old)
        newloc = await self._parent_loc(new)
        await self.graph.top.rename(oldloc, newloc)
        self.itable.unlink(oldloc.parent, oldloc.name)

    async def symlink(self, target: str, path: str) -> Iatt:
        loc = await self._parent_loc(path)
        return await self.graph.top.symlink(target, loc)

    async def readlink(self, path: str) -> str:
        loc = await self.resolve(path)
        return await self.graph.top.readlink(loc)

    async def link(self, old: str, new: str) -> Iatt:
        oldloc = await self.resolve(old)
        newloc = await self._parent_loc(new)
        return await self.graph.top.link(oldloc, newloc)

    async def listdir(self, path: str = "/") -> list[str]:
        loc = await self.resolve(path)
        fd = await self.graph.top.opendir(loc)
        entries = await self.graph.top.readdir(fd, 0, 0)
        return [name for name, _ in entries]

    async def listdir_with_stat(self, path: str = "/"):
        loc = await self.resolve(path)
        fd = await self.graph.top.opendir(loc)
        return await self.graph.top.readdirp(fd, 0, 0)

    async def truncate(self, path: str, size: int) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.truncate(loc, size)

    async def statvfs(self, path: str = "/") -> dict:
        loc = await self.resolve(path)
        return await self.graph.top.statfs(loc)

    async def getxattr(self, path: str, name: str | None = None):
        loc = await self.resolve(path)
        return await self.graph.top.getxattr(loc, name)

    async def setxattr(self, path: str, xattrs: dict) -> None:
        loc = await self.resolve(path)
        await self.graph.top.setxattr(loc, xattrs)

    async def setattr(self, path: str, attrs: dict) -> Iatt:
        loc = await self.resolve(path)
        return await self.graph.top.setattr(loc, attrs)

    # -- file ops ------------------------------------------------------------

    async def create(self, path: str, flags: int = os.O_RDWR,
                     mode: int = 0o644) -> File:
        loc = await self._parent_loc(path)
        fd, ia = await self.graph.top.create(loc, flags, mode)
        self.itable.link(loc.parent, loc.name, ia.gfid, ia.ia_type, ia)
        return File(self, fd, loc.path)

    async def open(self, path: str, flags: int = os.O_RDWR) -> File:
        loc = await self.resolve(path)
        fd = await self.graph.top.open(loc, flags)
        return File(self, fd, loc.path)

    async def write_file(self, path: str, data: bytes) -> int:
        """Convenience: create/overwrite a file with data."""
        if await self.exists(path):
            await self.truncate(path, 0)
            f = await self.open(path)
        else:
            f = await self.create(path)
        try:
            return await f.write(data, 0)
        finally:
            await f.close()

    async def read_file(self, path: str) -> bytes:
        ia = await self.stat(path)
        f = await self.open(path, os.O_RDONLY)
        try:
            return await f.read(ia.size, 0)
        finally:
            await f.close()

    # -- introspection -------------------------------------------------------

    def statedump(self) -> dict:
        d = self.graph.statedump()
        d["itable"] = self.itable.dump()
        return d


class SyncClient:
    """Synchronous facade: runs the async client on a private loop thread
    (the reference's syncop/synctask analog, syncop.c:263,602)."""

    def __init__(self, graph: Graph):
        self._client = Client(graph)
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True)
        self._thread.start()

    def _run(self, coro) -> Any:
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    def __getattr__(self, name: str):
        target = getattr(self._client, name)
        if asyncio.iscoroutinefunction(target):
            def call(*a, **kw):
                result = self._run(target(*a, **kw))
                return _SyncFile(self, result) if isinstance(result, File) \
                    else result
            return call
        return target

    def close(self) -> None:
        self._run(self._client.unmount())
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class _SyncFile:
    def __init__(self, owner: SyncClient, f: File):
        self._owner = owner
        self._f = f

    def __getattr__(self, name: str):
        target = getattr(self._f, name)
        if asyncio.iscoroutinefunction(target):
            return lambda *a, **kw: self._owner._run(target(*a, **kw))
        return target
