"""Build-on-demand ctypes bindings for the native (C++/AVX) kernels.

The shared library is compiled from ``src/*.cpp`` on first use and cached
next to the sources keyed by a source hash, mirroring how the reference
detects and selects its fastest CPU backend at runtime
(``ec_code_detect``, reference ec-code.c:977-1059) — here the "detection"
is: does the toolchain exist and does the library build.
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = sorted(
    os.path.join(_DIR, "src", f)
    for f in os.listdir(os.path.join(_DIR, "src")) if f.endswith(".cpp"))
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_BUILD_ERROR: str | None = None

WORD = 64
BITS = 8
CHUNK = WORD * BITS


def _build() -> str:
    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    so = os.path.join(_DIR, f"libgf256_{tag}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"  # pid-unique: concurrent builds race
    cmd = [
        "g++", "-O3", "-mavx2", "-funroll-loops", "-fPIC", "-shared",
        "-std=c++17", *_SRCS, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def _lib() -> ctypes.CDLL:
    global _LIB, _BUILD_ERROR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _BUILD_ERROR is not None:
            raise RuntimeError(_BUILD_ERROR)
        try:
            lib = ctypes.CDLL(_build())
        except Exception as e:  # toolchain missing, build failure, ...
            _BUILD_ERROR = f"native kernel build failed: {e}"
            raise RuntimeError(_BUILD_ERROR) from e
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.gf_apply_bitmatrix.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_size_t]
        lib.gf_encode.argtypes = [
            u8p, u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_size_t]
        lib.gf_decode.argtypes = [u8p, u8p, u8p, ctypes.c_int, ctypes.c_size_t]
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.gf_decode_prog.argtypes = [
            u8p, u8p, i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_size_t]
        lib.adler32_batch.argtypes = [
            u8p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32)]
        _LIB = lib
        return lib


def available() -> bool:
    try:
        _lib()
        return True
    except RuntimeError:
        return False


_WIREC = None
_WIREC_ERROR: str | None = None


def wirec_module():
    """Build-on-demand CPython extension for the wire codec (the XDR
    layer's C analog — src/wirec.c).  Returns the module or raises
    RuntimeError; rpc/wire.py falls back to its pure-Python codec."""
    global _WIREC, _WIREC_ERROR
    with _LOCK:
        if _WIREC is not None:
            return _WIREC
        if _WIREC_ERROR is not None:
            raise RuntimeError(_WIREC_ERROR)
        try:
            import importlib.machinery
            import importlib.util
            import sysconfig

            src = os.path.join(_DIR, "src", "wirec.c")
            h = hashlib.sha256()
            with open(src, "rb") as f:
                h.update(f.read())
            tag = h.hexdigest()[:16]
            so = os.path.join(_DIR, f"_wirec_{tag}.so")
            if not os.path.exists(so):
                tmp = f"{so}.{os.getpid()}.tmp"
                cmd = ["gcc", "-O2", "-fPIC", "-shared",
                       "-I", sysconfig.get_paths()["include"],
                       src, "-o", tmp]
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(tmp, so)
            loader = importlib.machinery.ExtensionFileLoader("_wirec", so)
            spec = importlib.util.spec_from_loader("_wirec", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            _WIREC = mod
            return mod
        except Exception as e:
            _WIREC_ERROR = f"wirec build failed: {e}"
            raise RuntimeError(_WIREC_ERROR) from e


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


MAX_K = 16  # RowSel.idx capacity in the C++ kernel is 16*8 columns


def encode(data: np.ndarray, k: int, n: int, abits: np.ndarray) -> np.ndarray:
    """Stripe-major bytes (S*k*512) + (n*8, k*8) bitmatrix -> (n, S*512)."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if not 1 <= k <= MAX_K:
        raise ValueError(f"k must be in [1, {MAX_K}]")
    if data.size % (k * CHUNK):
        raise ValueError("data length must be a multiple of k*512")
    s = data.size // (k * CHUNK)
    abits = np.ascontiguousarray(abits, dtype=np.uint8)
    out = np.empty((n, s * CHUNK), dtype=np.uint8)
    _lib().gf_encode(_u8p(data), _u8p(out), _u8p(abits), k, n, s)
    return out


def decode(frags: np.ndarray, k: int, bbits: np.ndarray) -> np.ndarray:
    """Fragment-major (k, S*512) + (k*8, k*8) bitmatrix -> bytes (S*k*512)."""
    frags = np.ascontiguousarray(frags, dtype=np.uint8)
    if not 1 <= k <= MAX_K:
        raise ValueError(f"k must be in [1, {MAX_K}]")
    if frags.shape[0] != k or frags.shape[1] % CHUNK:
        raise ValueError("need (k, S*512) fragments")
    s = frags.shape[1] // CHUNK
    bbits = np.ascontiguousarray(bbits, dtype=np.uint8)
    out = np.empty(s * k * CHUNK, dtype=np.uint8)
    _lib().gf_decode(_u8p(frags), _u8p(out), _u8p(bbits), k, s)
    return out


@functools.lru_cache(maxsize=256)
def _prog_schedule(prog):
    """Register-allocated instruction stream of an XorProgram (hashable
    NamedTuple) — one scheduling pass per cached program instead of one
    per decode call."""
    from glusterfs_tpu.ops import gf256

    return gf256.schedule_program(prog)


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def decode_program(frags: np.ndarray, k: int, prog) -> np.ndarray:
    """Fragment-major (k, S*512) + a gf256.XorProgram (the per-mask
    compiled decode schedule) -> bytes (S*k*512).  The CSE'd schedule
    cuts the word-XOR count ~2-3x vs :func:`decode`'s row-select walk;
    the slot-reusing schedule keeps its working set cache-resident."""
    frags = np.ascontiguousarray(frags, dtype=np.uint8)
    if not 1 <= k <= MAX_K:
        raise ValueError(f"k must be in [1, {MAX_K}]")
    if frags.shape[0] != k or frags.shape[1] % CHUNK:
        raise ValueError("need (k, S*512) fragments")
    if prog.n_inputs != k * BITS or len(prog.outs) != k * BITS:
        raise ValueError(
            f"program shape {prog.n_inputs}->{len(prog.outs)} does not "
            f"match a k={k} decode")
    s = frags.shape[1] // CHUNK
    code, n_slots = _prog_schedule(prog)
    # 8-stripe blocks amortize per-instruction dispatch; with the
    # transposed live-range schedule the slab stays small enough that 8
    # wins (or ties within noise) at every geometry on a block scan
    # (1/2/4/8/16 measured; 16+4: 443/499/651/696/678 MiB/s)
    out = np.empty(s * k * CHUNK, dtype=np.uint8)
    _lib().gf_decode_prog(_u8p(frags), _u8p(out), _i32p(code),
                          len(code), n_slots, 8, k, s)
    return out


def adler32_batch(blocks: np.ndarray) -> np.ndarray:
    """[n, b] uint8 -> [n] uint32 zlib-compatible adler32 (the batched
    weak-checksum rung of the rchecksum backend ladder)."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    n, b = blocks.shape
    out = np.empty(n, dtype=np.uint32)
    _lib().adler32_batch(_u8p(blocks), n, b,
                         out.ctypes.data_as(
                             ctypes.POINTER(ctypes.c_uint32)))
    return out


def apply_bitmatrix(abits: np.ndarray, x: np.ndarray) -> np.ndarray:
    """(R, C) bitmatrix applied to plane-major (C, W) bytes -> (R, W)."""
    abits = np.ascontiguousarray(abits, dtype=np.uint8)
    x = np.ascontiguousarray(x, dtype=np.uint8)
    r, c = abits.shape
    if c > MAX_K * BITS:
        raise ValueError(f"at most {MAX_K * BITS} input planes supported")
    if x.shape[0] != c or x.shape[1] % WORD:
        raise ValueError("x must be (C, W) with W a multiple of 64")
    out = np.empty((r, x.shape[1]), dtype=np.uint8)
    _lib().gf_apply_bitmatrix(_u8p(abits), r, c, _u8p(x), _u8p(out),
                              x.shape[1])
    return out
