/* C implementation of the wire codec's value tree (rpc/wire.py).
 *
 * The reference's XDR layer is generated C (rpc/xdr/*); ours was a
 * recursive Python walk that profiled at ~30% of a served brick's CPU
 * under wire load.  This extension implements the SAME tagged format
 * byte-for-byte (tests cross-check every frame against the Python
 * codec) with the tree walk, varints and buffer appends in C.
 *
 * Python-defined classes (Iatt, Loc, FdHandle, FopError, Blob) are
 * registered at import by rpc/wire.py; encoding reads their attributes
 * via the C API, decoding constructs them through registered factory
 * callables.  Unknown types raise WireError exactly like the Python
 * path.
 *
 * Built on demand by glusterfs_tpu/native/__init__.py (same
 * build-and-cache scheme as the AVX kernels); rpc/wire.py falls back to
 * the pure-Python codec when the toolchain is missing.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

enum {
    T_NONE = 0, T_TRUE = 1, T_FALSE = 2,
    T_INT = 3, T_NEGINT = 4, T_FLOAT = 5,
    T_BYTES = 6, T_STR = 7,
    T_LIST = 8, T_DICT = 9,
    T_IATT = 10, T_LOC = 11, T_FD = 12, T_ERR = 13,
    T_BLOBREF = 14,
};

/* registered from wire.py */
static PyObject *cls_iatt, *cls_loc, *cls_fd, *cls_err, *cls_blob;
static PyObject *mk_iatt, *mk_loc, *mk_fd, *mk_err;   /* factories */
static PyObject *wire_error;                          /* WireError */
static PyObject *blob_stats;                          /* dict */

/* -- growable output buffer -------------------------------------------- */

typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} Out;

static int out_grow(Out *o, Py_ssize_t need)
{
    if (o->len + need <= o->cap)
        return 0;
    Py_ssize_t cap = o->cap ? o->cap : 256;
    while (cap < o->len + need)
        cap *= 2;
    char *nb = PyMem_Realloc(o->buf, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    o->buf = nb;
    o->cap = cap;
    return 0;
}

static inline int out_byte(Out *o, unsigned char b)
{
    if (out_grow(o, 1) < 0)
        return -1;
    o->buf[o->len++] = (char)b;
    return 0;
}

static inline int out_mem(Out *o, const void *p, Py_ssize_t n)
{
    if (out_grow(o, n) < 0)
        return -1;
    memcpy(o->buf + o->len, p, n);
    o->len += n;
    return 0;
}

static int out_uint(Out *o, unsigned long long n)
{
    do {
        unsigned char b = n & 0x7F;
        n >>= 7;
        if (out_byte(o, n ? (b | 0x80) : b) < 0)
            return -1;
    } while (n);
    return 0;
}

/* -- encode ------------------------------------------------------------ */

static int enc(PyObject *v, Out *o, PyObject *blobs);

static int enc_attr_list(PyObject *v, Out *o, const char *const *names,
                         int n, int tag)
{
    /* encode [getattr(v, name) for name in names] as a T_LIST */
    if (out_byte(o, (unsigned char)tag) < 0 || out_byte(o, T_LIST) < 0 ||
        out_uint(o, (unsigned long long)n) < 0)
        return -1;
    for (int i = 0; i < n; i++) {
        PyObject *a = PyObject_GetAttrString(v, names[i]);
        if (!a)
            return -1;
        int rc = enc(a, o, NULL);
        Py_DECREF(a);
        if (rc < 0)
            return -1;
    }
    return 0;
}

static int enc(PyObject *v, Out *o, PyObject *blobs)
{
    if (v == Py_None)
        return out_byte(o, T_NONE);
    if (v == Py_True)
        return out_byte(o, T_TRUE);
    if (v == Py_False)
        return out_byte(o, T_FALSE);

    if (PyLong_CheckExact(v)) {
        int overflow = 0;
        long long sv = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (!overflow) {
            if (sv >= 0) {
                if (out_byte(o, T_INT) < 0)
                    return -1;
                return out_uint(o, (unsigned long long)sv);
            }
            if (out_byte(o, T_NEGINT) < 0)
                return -1;
            return out_uint(o, (unsigned long long)(-sv));
        }
        /* > 63 bits: rare (tests use 2**40; xattr counters fit u64).
         * Positive ones still fit the unsigned path. */
        unsigned long long uv = PyLong_AsUnsignedLongLong(v);
        if (uv == (unsigned long long)-1 && PyErr_Occurred())
            return -1;
        if (out_byte(o, T_INT) < 0)
            return -1;
        return out_uint(o, uv);
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        unsigned char be[8];
        /* big-endian IEEE double, like struct.pack(">d") */
        union { double d; unsigned long long u; } u;
        u.d = d;
        for (int i = 0; i < 8; i++)
            be[i] = (unsigned char)(u.u >> (56 - 8 * i));
        if (out_byte(o, T_FLOAT) < 0)
            return -1;
        return out_mem(o, be, 8);
    }
    if ((PyObject *)Py_TYPE(v) == cls_blob) {
        PyObject *view = PyObject_GetAttrString(v, "view");
        if (!view)
            return -1;
        Py_buffer pb;
        if (PyObject_GetBuffer(view, &pb, PyBUF_SIMPLE) < 0) {
            Py_DECREF(view);
            return -1;
        }
        int rc = -1;
        if (blobs && blobs != Py_None) {
            /* out-of-band lane: tiny ref in the body, view appended */
            if (out_byte(o, T_BLOBREF) == 0 &&
                out_uint(o, (unsigned long long)pb.len) == 0 &&
                PyList_Append(blobs, view) == 0)
                rc = 0;
        } else {
            if (out_byte(o, T_BYTES) == 0 &&
                out_uint(o, (unsigned long long)pb.len) == 0 &&
                out_mem(o, pb.buf, pb.len) == 0)
                rc = 0;
            if (rc == 0 && blob_stats) {
                PyObject *k = PyUnicode_FromString("inline_bytes");
                PyObject *cur = k ? PyDict_GetItem(blob_stats, k) : NULL;
                if (cur) {
                    PyObject *nv = PyNumber_Add(
                        cur, PyLong_FromSsize_t(pb.len));
                    if (nv) {
                        PyDict_SetItem(blob_stats, k, nv);
                        Py_DECREF(nv);
                    } else
                        PyErr_Clear();
                }
                Py_XDECREF(k);
            }
        }
        PyBuffer_Release(&pb);
        Py_DECREF(view);
        return rc;
    }
    if (PyBytes_CheckExact(v)) {
        if (out_byte(o, T_BYTES) < 0 ||
            out_uint(o, (unsigned long long)PyBytes_GET_SIZE(v)) < 0)
            return -1;
        return out_mem(o, PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
    }
    if (PyByteArray_CheckExact(v) || PyMemoryView_Check(v)) {
        Py_buffer pb;
        if (PyObject_GetBuffer(v, &pb, PyBUF_SIMPLE) < 0)
            return -1;
        int rc = -1;
        if (out_byte(o, T_BYTES) == 0 &&
            out_uint(o, (unsigned long long)pb.len) == 0 &&
            out_mem(o, pb.buf, pb.len) == 0)
            rc = 0;
        PyBuffer_Release(&pb);
        return rc;
    }
    if (PyUnicode_CheckExact(v)) {
        /* surrogateescape round-trips raw filesystem names */
        PyObject *b = PyUnicode_AsEncodedString(v, "utf-8",
                                                "surrogateescape");
        if (!b)
            return -1;
        int rc = -1;
        if (out_byte(o, T_STR) == 0 &&
            out_uint(o, (unsigned long long)PyBytes_GET_SIZE(b)) == 0 &&
            out_mem(o, PyBytes_AS_STRING(b), PyBytes_GET_SIZE(b)) == 0)
            rc = 0;
        Py_DECREF(b);
        return rc;
    }
    if (PyList_CheckExact(v) || PyTuple_CheckExact(v)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
        if (out_byte(o, T_LIST) < 0 ||
            out_uint(o, (unsigned long long)n) < 0)
            return -1;
        PyObject **items = PySequence_Fast_ITEMS(v);
        for (Py_ssize_t i = 0; i < n; i++)
            if (enc(items[i], o, blobs) < 0)
                return -1;
        return 0;
    }
    if (PyDict_CheckExact(v)) {
        if (out_byte(o, T_DICT) < 0 ||
            out_uint(o, (unsigned long long)PyDict_GET_SIZE(v)) < 0)
            return -1;
        PyObject *k, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(v, &pos, &k, &val)) {
            if (enc(k, o, blobs) < 0 || enc(val, o, blobs) < 0)
                return -1;
        }
        return 0;
    }
    {
        PyObject *t = (PyObject *)Py_TYPE(v);
        if (t == cls_iatt) {
            static const char *const names[] = {
                "gfid", "ia_type", "mode", "nlink", "uid", "gid",
                "size", "blocks", "atime", "mtime", "ctime", "rdev",
                "blksize"};
            /* ia_type is an IntEnum: encode its .value */
            PyObject *iat = PyObject_GetAttrString(v, "ia_type");
            if (!iat)
                return -1;
            PyObject *iav = PyObject_GetAttrString(iat, "value");
            Py_DECREF(iat);
            if (!iav)
                return -1;
            if (out_byte(o, T_IATT) < 0 || out_byte(o, T_LIST) < 0 ||
                out_uint(o, 13) < 0) {
                Py_DECREF(iav);
                return -1;
            }
            for (int i = 0; i < 13; i++) {
                PyObject *a;
                if (i == 1) {
                    a = iav;
                    Py_INCREF(a);
                } else {
                    a = PyObject_GetAttrString(v, names[i]);
                }
                if (!a) {
                    Py_DECREF(iav);
                    return -1;
                }
                int rc = enc(a, o, NULL);
                Py_DECREF(a);
                if (rc < 0) {
                    Py_DECREF(iav);
                    return -1;
                }
            }
            Py_DECREF(iav);
            return 0;
        }
        if (t == cls_loc) {
            static const char *const names[] = {"path", "gfid",
                                                "parent", "name"};
            return enc_attr_list(v, o, names, 4, T_LOC);
        }
        if (t == cls_fd) {
            static const char *const names[] = {"fdid", "gfid", "path"};
            return enc_attr_list(v, o, names, 3, T_FD);
        }
        if (PyObject_IsInstance(v, cls_err) == 1) {
            /* FopError: [err, message] where message = args[1] or "";
             * a non-empty .xdata dict (the error-path reply dict, e.g.
             * the lock-revocation notice) rides as a third element
             * that two-field decoders simply ignore */
            PyObject *errno_o = PyObject_GetAttrString(v, "err");
            if (!errno_o)
                return -1;
            PyObject *args = PyObject_GetAttrString(v, "args");
            PyObject *msg = NULL;
            if (args && PyTuple_Check(args) &&
                PyTuple_GET_SIZE(args) > 1) {
                msg = PyObject_Str(PyTuple_GET_ITEM(args, 1));
            } else {
                msg = PyUnicode_FromString("");
            }
            Py_XDECREF(args);
            PyObject *xd = PyObject_GetAttrString(v, "xdata");
            if (!xd)
                PyErr_Clear(); /* pre-xdata FopError: two-field shape */
            int with_xd = xd && PyDict_CheckExact(xd) &&
                          PyDict_GET_SIZE(xd) > 0;
            int rc = -1;
            if (msg && out_byte(o, T_ERR) == 0 &&
                out_byte(o, T_LIST) == 0 &&
                out_uint(o, with_xd ? 3 : 2) == 0 &&
                enc(errno_o, o, NULL) == 0 && enc(msg, o, NULL) == 0 &&
                (!with_xd || enc(xd, o, NULL) == 0))
                rc = 0;
            Py_DECREF(errno_o);
            Py_XDECREF(msg);
            Py_XDECREF(xd);
            return rc;
        }
    }
    PyErr_Format(wire_error, "unencodable type %s",
                 Py_TYPE(v)->tp_name);
    return -1;
}

/* -- decode ------------------------------------------------------------ */

typedef struct {
    const unsigned char *buf;
    Py_ssize_t len;
    Py_ssize_t pos;
    PyObject *blobs; /* [region_memoryview, offset] or NULL */
} In;

static int in_uint(In *in, unsigned long long *out)
{
    unsigned long long n = 0;
    int shift = 0;
    for (;;) {
        if (in->pos >= in->len) {
            PyErr_SetString(wire_error, "truncated varint");
            return -1;
        }
        unsigned char b = in->buf[in->pos++];
        n |= (unsigned long long)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = n;
            return 0;
        }
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(wire_error, "varint too long");
            return -1;
        }
    }
}

static PyObject *dec(In *in);

static PyObject *dec_via(In *in, PyObject *factory)
{
    PyObject *vals = dec(in);
    if (!vals)
        return NULL;
    PyObject *out = PyObject_CallOneArg(factory, vals);
    Py_DECREF(vals);
    return out;
}

static PyObject *dec(In *in)
{
    if (in->pos >= in->len) {
        PyErr_SetString(wire_error, "truncated record");
        return NULL;
    }
    unsigned char tag = in->buf[in->pos++];
    unsigned long long n;
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_TRUE:
        Py_RETURN_TRUE;
    case T_FALSE:
        Py_RETURN_FALSE;
    case T_INT:
        if (in_uint(in, &n) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(n);
    case T_NEGINT: {
        if (in_uint(in, &n) < 0)
            return NULL;
        PyObject *p = PyLong_FromUnsignedLongLong(n);
        if (!p)
            return NULL;
        PyObject *r = PyNumber_Negative(p);
        Py_DECREF(p);
        return r;
    }
    case T_FLOAT: {
        if (in->pos + 8 > in->len) {
            PyErr_SetString(wire_error, "truncated float");
            return NULL;
        }
        unsigned long long u = 0;
        for (int i = 0; i < 8; i++)
            u = (u << 8) | in->buf[in->pos + i];
        in->pos += 8;
        union { double d; unsigned long long u; } cv;
        cv.u = u;
        return PyFloat_FromDouble(cv.d);
    }
    case T_BYTES:
        if (in_uint(in, &n) < 0)
            return NULL;
        if (in->pos + (Py_ssize_t)n > in->len) {
            PyErr_SetString(wire_error, "truncated bytes");
            return NULL;
        }
        in->pos += (Py_ssize_t)n;
        return PyBytes_FromStringAndSize(
            (const char *)in->buf + in->pos - (Py_ssize_t)n,
            (Py_ssize_t)n);
    case T_BLOBREF: {
        if (in_uint(in, &n) < 0)
            return NULL;
        if (!in->blobs || in->blobs == Py_None) {
            PyErr_SetString(wire_error,
                            "blob reference outside a FL_BLOBS record");
            return NULL;
        }
        PyObject *region = PyList_GET_ITEM(in->blobs, 0);
        PyObject *off_o = PyList_GET_ITEM(in->blobs, 1);
        Py_ssize_t off = PyLong_AsSsize_t(off_o);
        if (off < 0 && PyErr_Occurred())
            return NULL;
        Py_ssize_t rlen = PySequence_Length(region);
        if (rlen < 0)
            return NULL;
        if (off + (Py_ssize_t)n > rlen) {
            PyErr_SetString(wire_error, "blob reference beyond record");
            return NULL;
        }
        PyObject *no = PyLong_FromSsize_t(off + (Py_ssize_t)n);
        if (!no)
            return NULL;
        PyList_SetItem(in->blobs, 1, no); /* steals no */
        /* region[off:off+n] — a zero-copy memoryview slice */
        PyObject *lo = PyLong_FromSsize_t(off);
        PyObject *hi = PyLong_FromSsize_t(off + (Py_ssize_t)n);
        if (!lo || !hi) {
            Py_XDECREF(lo);
            Py_XDECREF(hi);
            return NULL;
        }
        PyObject *slice = PySlice_New(lo, hi, NULL);
        Py_DECREF(lo);
        Py_DECREF(hi);
        if (!slice)
            return NULL;
        PyObject *out = PyObject_GetItem(region, slice);
        Py_DECREF(slice);
        return out;
    }
    case T_STR:
        if (in_uint(in, &n) < 0)
            return NULL;
        if (in->pos + (Py_ssize_t)n > in->len) {
            PyErr_SetString(wire_error, "truncated str");
            return NULL;
        }
        in->pos += (Py_ssize_t)n;
        return PyUnicode_DecodeUTF8(
            (const char *)in->buf + in->pos - (Py_ssize_t)n,
            (Py_ssize_t)n, "surrogateescape");
    case T_LIST: {
        if (in_uint(in, &n) < 0)
            return NULL;
        PyObject *out = PyList_New((Py_ssize_t)n);
        if (!out)
            return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = dec(in);
            if (!item) {
                Py_DECREF(out);
                return NULL;
            }
            PyList_SET_ITEM(out, i, item);
        }
        return out;
    }
    case T_DICT: {
        if (in_uint(in, &n) < 0)
            return NULL;
        PyObject *d = PyDict_New();
        if (!d)
            return NULL;
        for (unsigned long long i = 0; i < n; i++) {
            PyObject *k = dec(in);
            if (!k) {
                Py_DECREF(d);
                return NULL;
            }
            PyObject *v = dec(in);
            if (!v) {
                Py_DECREF(k);
                Py_DECREF(d);
                return NULL;
            }
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(d);
                return NULL;
            }
        }
        return d;
    }
    case T_IATT:
        return dec_via(in, mk_iatt);
    case T_LOC:
        return dec_via(in, mk_loc);
    case T_FD:
        return dec_via(in, mk_fd);
    case T_ERR:
        return dec_via(in, mk_err);
    default:
        PyErr_Format(wire_error, "bad tag %d", (int)tag);
        return NULL;
    }
}

/* -- module API -------------------------------------------------------- */

static PyObject *py_register(PyObject *self, PyObject *args)
{
    PyObject *we, *stats;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOOO", &cls_iatt, &cls_loc,
                          &cls_fd, &cls_err, &cls_blob, &mk_iatt,
                          &mk_loc, &mk_fd, &mk_err, &we, &stats))
        return NULL;
    Py_INCREF(cls_iatt); Py_INCREF(cls_loc); Py_INCREF(cls_fd);
    Py_INCREF(cls_err); Py_INCREF(cls_blob);
    Py_INCREF(mk_iatt); Py_INCREF(mk_loc); Py_INCREF(mk_fd);
    Py_INCREF(mk_err);
    wire_error = we;
    Py_INCREF(we);
    blob_stats = stats;
    Py_INCREF(stats);
    Py_RETURN_NONE;
}

static PyObject *py_encode(PyObject *self, PyObject *args)
{
    PyObject *payload, *blobs = Py_None;
    if (!PyArg_ParseTuple(args, "O|O", &payload, &blobs))
        return NULL;
    Out o = {NULL, 0, 0};
    if (enc(payload, &o, blobs == Py_None ? NULL : blobs) < 0) {
        PyMem_Free(o.buf);
        return NULL;
    }
    PyObject *b = PyBytes_FromStringAndSize(o.buf, o.len);
    PyMem_Free(o.buf);
    return b;
}

static PyObject *py_decode(PyObject *self, PyObject *args)
{
    Py_buffer pb;
    Py_ssize_t pos;
    PyObject *blobs = Py_None;
    if (!PyArg_ParseTuple(args, "y*n|O", &pb, &pos, &blobs))
        return NULL;
    In in = {(const unsigned char *)pb.buf, pb.len, pos,
             blobs == Py_None ? NULL : blobs};
    PyObject *v = dec(&in);
    PyBuffer_Release(&pb);
    if (!v)
        return NULL;
    PyObject *out = Py_BuildValue("(Nn)", v, in.pos);
    return out;
}

static PyMethodDef methods[] = {
    {"register", py_register, METH_VARARGS, "register classes"},
    {"encode", py_encode, METH_VARARGS, "encode value tree -> bytes"},
    {"decode", py_decode, METH_VARARGS,
     "decode (buf, pos[, blobs]) -> (value, newpos)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef mod = {
    PyModuleDef_HEAD_INIT, "_wirec", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__wirec(void)
{
    return PyModule_Create(&mod);
}
