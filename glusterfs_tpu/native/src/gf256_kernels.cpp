// Native GF(2^8) erasure-coding kernels (CPU fast path + bench baseline).
//
// TPU-native framework's host-side analog of the reference's JIT-emitted
// AVX/SSE/x64 XOR-chain kernels (reference: xlators/cluster/ec/src/ec-code.c,
// ec-code-avx.c — behavior only; this is an independent implementation).
//
// Layout contract (shared with glusterfs_tpu/ops/gf256.py):
//   * data is bit-sliced in 512-byte chunks: 8 bit-planes x 64-byte words
//     (EC_METHOD_CHUNK_SIZE / EC_METHOD_WORD_SIZE, reference ec-method.h:17-29)
//   * multiplying a chunk by a GF(256) constant == applying an 8x8 GF(2)
//     bit-matrix to its planes; a full encode is one (N*8, K*8) binary
//     matrix applied per stripe.
//
// Compiled with -O3 -mavx2 -funroll-loops: the fixed 64-byte XOR loops below
// vectorize to YMM xor/load/store chains, which is the same instruction mix
// the reference JIT emits.
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kWord = 64;     // bytes per bit-plane word
constexpr int kBits = 8;      // GF(2^8)
constexpr int kChunk = kWord * kBits;  // 512

// XOR-accumulate one 64-byte word: dst ^= src.  Auto-vectorizes to 2x YMM.
inline void xor_word(uint8_t* __restrict dst, const uint8_t* __restrict src) {
  for (int b = 0; b < kWord; ++b) dst[b] ^= src[b];
}

// Variable-width helpers for the program walk.  The __restrict is what
// lets the compiler emit straight YMM loads/xor/stores: the pointers all
// point into one var slab, so without it every op pays an aliasing check
// (program destinations are always fresh vars, so the promise holds by
// construction).
inline void xor2_w(uint8_t* __restrict dst, const uint8_t* __restrict a,
                   const uint8_t* __restrict b, int w) {
  for (int i = 0; i < w; ++i) dst[i] = a[i] ^ b[i];
}

inline void xor_accum_w(uint8_t* __restrict dst,
                        const uint8_t* __restrict src, int w) {
  for (int i = 0; i < w; ++i) dst[i] ^= src[i];
}

// Per output row, the list of selected input planes (built once per call).
// cols = k*8 <= 128 (k <= 16 data fragments); rows = n*8 can exceed that
// (n up to 255), so the row table is heap-allocated.
struct RowSel {
  int idx[16 * kBits];
  int count;
};

std::vector<RowSel> build_sels(const uint8_t* abits, int rows, int cols) {
  std::vector<RowSel> sels(rows);
  for (int i = 0; i < rows; ++i) {
    sels[i].count = 0;
    for (int j = 0; j < cols; ++j) {
      if (abits[i * cols + j]) sels[i].idx[sels[i].count++] = j;
    }
  }
  return sels;
}

// y_row = XOR of selected 64-byte plane words from x (stride kWord rows).
inline void apply_row(const RowSel& sel, const uint8_t* __restrict x,
                      uint8_t* __restrict y) {
  if (sel.count == 0) {
    std::memset(y, 0, kWord);
    return;
  }
  std::memcpy(y, x + sel.idx[0] * kWord, kWord);
  for (int t = 1; t < sel.count; ++t) xor_word(y, x + sel.idx[t] * kWord);
}

}  // namespace

extern "C" {

// Generic plane-major apply: x is (c, w) bytes, y is (r, w); w a multiple of
// kWord.  abits is (r, c) in {0,1}.  Each 64-byte column block is independent.
void gf_apply_bitmatrix(const uint8_t* abits, int r, int c,
                        const uint8_t* x, uint8_t* y, size_t w) {
  std::vector<RowSel> sels = build_sels(abits, r, c);
  for (size_t off = 0; off < w; off += kWord) {
    // Gather is strided here; encode/decode below use the stripe-contiguous
    // layout instead.  This entry exists for parity testing vs the JAX path.
    for (int i = 0; i < r; ++i) {
      const RowSel& sel = sels[i];
      uint8_t* dst = y + i * w + off;
      if (sel.count == 0) {
        std::memset(dst, 0, kWord);
        continue;
      }
      std::memcpy(dst, x + sel.idx[0] * w + off, kWord);
      for (int t = 1; t < sel.count; ++t)
        xor_word(dst, x + sel.idx[t] * w + off);
    }
  }
}

// Encode: data is stripe-major (s, k*8, 64) plane words; abits (n*8, k*8);
// out is fragment-major (n, s*512) — fragment i chunk for stripe t lands at
// out + (i*s + t)*512 (matches ec_method_encode's output layout,
// reference ec-method.c:393-408).
void gf_encode(const uint8_t* __restrict data, uint8_t* __restrict out,
               const uint8_t* __restrict abits, int k, int n, size_t s) {
  const int cols = k * kBits;
  const int rows = n * kBits;
  std::vector<RowSel> sels = build_sels(abits, rows, cols);
  for (size_t t = 0; t < s; ++t) {
    const uint8_t* x = data + t * (size_t)k * kChunk;
    for (int f = 0; f < n; ++f) {
      uint8_t* frag = out + (f * s + t) * (size_t)kChunk;
      for (int p = 0; p < kBits; ++p)
        apply_row(sels[f * kBits + p], x, frag + p * kWord);
    }
  }
}

// Decode via a CSE'd straight-line XOR program, register-allocated by
// ops/gf256.py schedule_program (TRANSPOSED: output rows are fixed
// accumulator slots, values scatter into them as computed, slots recycle
// at last use).  frags is fragment-major (k, s*512), out stripe-major
// bytes (s*k*512).  code is a flat int32 instruction stream over n_slots
// reusable variable slots:
//   [0, dst, a, b]      slot dst = slot a ^ slot b
//   [1, row, nv, v...]  emit output plane row = XOR of nv slots (0 -> 0s)
//   [2, slot, f, p]     load plane p of surviving fragment f into slot
//   [3, src, n, s...]   slot s_i ^= slot src (scatter into accumulators)
//   [4, src, n, s...]   slot s_i = slot src  (first touch of those accs)
// Shared subexpressions are computed once per block instead of once per
// output row (~2-3x fewer word-XORs than the row-select kernel below —
// the same programs the TPU kernels unroll); the transposed schedule
// keeps the slab at peak-LIVE size so it stays cache-resident.  An
// unscheduled flat slab (one var per op, ~550 KiB at 16+4) measured
// SLOWER than row-select (133 vs 277 MiB/s) from cache thrash alone;
// see bench.py's native sweep rows (native_decode vs
// native_decode_rowselect) for the live numbers per geometry.
//
// The walk is blocked over `block` stripes: each slot holds `block`
// consecutive 64-byte words, so per-instruction dispatch (index loads,
// pointer math, loop overhead) amortizes across the block; the slab
// grows linearly with it, so the block stays caller-tunable (the python
// binding passes 8 — best or within noise at every geometry on a
// 1/2/4/8/16 scan once the schedule keeps the slab live-range-sized).
constexpr int kProgBlockMax = 16;

void gf_decode_prog(const uint8_t* __restrict frags, uint8_t* __restrict out,
                    const int32_t* __restrict code, int n_code, int n_slots,
                    int block, int k, size_t s) {
  if (block < 1) block = 1;
  if (block > kProgBlockMax) block = kProgBlockMax;
  const int vw = block * kWord;  // bytes per slot per block
  std::vector<uint8_t> slab((size_t)n_slots * vw);
  uint8_t* t = slab.data();
  uint8_t acc[kProgBlockMax * kWord];
  for (size_t st = 0; st < s; st += block) {
    const int nb = (s - st) < (size_t)block ? (int)(s - st) : block;
    const int w = nb * kWord;
    const int32_t* pc = code;
    const int32_t* end = code + n_code;
    while (pc < end) {
      switch (pc[0]) {
        case 0:
          xor2_w(t + (size_t)pc[1] * vw, t + (size_t)pc[2] * vw,
                 t + (size_t)pc[3] * vw, w);
          pc += 4;
          break;
        case 1: {
          const int row = pc[1], nv = pc[2];
          if (nv == 0) {
            std::memset(acc, 0, w);
          } else {
            std::memcpy(acc, t + (size_t)pc[3] * vw, w);
            for (int i = 1; i < nv; ++i)
              xor_accum_w(acc, t + (size_t)pc[3 + i] * vw, w);
          }
          // scatter plane `row` back to stripe-major output
          for (int b = 0; b < nb; ++b)
            std::memcpy(out + (st + b) * (size_t)k * kChunk + row * kWord,
                        acc + b * kWord, kWord);
          pc += 3 + nv;
          break;
        }
        case 2: {  // gather one input plane, nb stripes
          uint8_t* dst = t + (size_t)pc[1] * vw;
          const int f = pc[2], p = pc[3];
          for (int b = 0; b < nb; ++b)
            std::memcpy(dst + b * kWord,
                        frags + (f * s + st + b) * (size_t)kChunk + p * kWord,
                        kWord);
          pc += 4;
          break;
        }
        case 3: {  // scatter: acc slots ^= src
          const uint8_t* src = t + (size_t)pc[1] * vw;
          const int n = pc[2];
          for (int i = 0; i < n; ++i)
            xor_accum_w(t + (size_t)pc[3 + i] * vw, src, w);
          pc += 3 + n;
          break;
        }
        default: {  // 4: first touch: acc slots = src
          const uint8_t* src = t + (size_t)pc[1] * vw;
          const int n = pc[2];
          for (int i = 0; i < n; ++i)
            std::memcpy(t + (size_t)pc[3 + i] * vw, src, w);
          pc += 3 + n;
          break;
        }
      }
    }
  }
}

// Decode: frags is fragment-major (k, s*512) (the k surviving fragments in
// row order matching the decode matrix); bbits (k*8, k*8); out is
// stripe-major bytes (s*k*512).
void gf_decode(const uint8_t* __restrict frags, uint8_t* __restrict out,
               const uint8_t* __restrict bbits, int k, size_t s) {
  const int cols = k * kBits;
  std::vector<RowSel> sels = build_sels(bbits, cols, cols);
  // Gather one stripe's planes into a contiguous scratch (k*8 x 64), apply.
  uint8_t x[16 * kBits * kWord];
  for (size_t t = 0; t < s; ++t) {
    for (int f = 0; f < k; ++f)
      std::memcpy(x + f * (size_t)kChunk, frags + (f * s + t) * (size_t)kChunk,
                  kChunk);
    uint8_t* y = out + t * (size_t)k * kChunk;
    for (int i = 0; i < cols; ++i) apply_row(sels[i], x, y + i * kWord);
  }
}

}  // extern "C"
