// Batched block checksums — the libglusterfs checksum.c workload
// (gf_rchecksum weak sums) as a native batch kernel: one call
// checksums thousands of equal-size blocks (the scrubber/heal
// handshake regime), auto-vectorized by -O3 -mavx2.
//
// Adler-32 (zlib-compatible) decomposes into two weighted sums:
//   A = 1 + sum(d_i)                   (mod 65521)
//   B = blen + sum((blen - i) * d_i)   (mod 65521)
// which lets the whole block reduce with multiply-accumulate loops
// instead of the serial a+=d; b+=a; recurrence.  64-bit accumulators
// hold exactly while blen^2 * 255 < 2^63, i.e. blocks up to ~180 MiB.

#include <cstddef>
#include <cstdint>

namespace {
constexpr uint64_t MOD = 65521;
}

extern "C" {

// blocks: n contiguous blocks of blen bytes; out: n uint32 checksums.
void adler32_batch(const uint8_t* blocks, size_t n, size_t blen,
                   uint32_t* out) {
    for (size_t b = 0; b < n; ++b) {
        const uint8_t* d = blocks + b * blen;
        uint64_t s1 = 0, s2 = 0;
        for (size_t i = 0; i < blen; ++i) {
            s1 += d[i];
            s2 += static_cast<uint64_t>(blen - i) * d[i];
        }
        uint64_t a = (1 + s1) % MOD;
        uint64_t bb = (blen + s2) % MOD;
        out[b] = static_cast<uint32_t>((bb << 16) | a);
    }
}

}  // extern "C"
