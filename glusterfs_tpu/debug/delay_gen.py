"""debug/delay-gen — latency injection per fop (reference
xlators/debug/delay-gen/delay-gen.c:23,456: options ``delay-duration``
(usec), ``delay-percentage``, ``enable`` fop list)."""

from __future__ import annotations

import asyncio
import random

from ..core.fops import Fop
from ..core.layer import Layer, register
from ..core.options import Option


@register("debug/delay-gen")
class DelayGenLayer(Layer):
    OPTIONS = (
        Option("delay-duration", "int", default=100000, min=0,
               description="injected delay in microseconds"),
        Option("delay-percentage", "percent", default=10.0, min=0, max=100),
        Option("enable", "str", default="",
               description="comma-separated fop names ('' = all)"),
        Option("seed", "int", default=0),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._rng = random.Random(self.opts["seed"] or None)
        self._install()

    def reconfigure(self, options):
        super().reconfigure(options)
        self._install()

    def _install(self):
        enabled = {s.strip() for s in self.opts["enable"].split(",")
                   if s.strip()}
        self._enabled = enabled or {f.value for f in Fop}
        self._rate = self.opts["delay-percentage"] / 100.0
        self._delay = self.opts["delay-duration"] / 1e6

    async def _maybe_delay(self, op: str):
        if op in self._enabled and self._rate > 0 and \
                self._rng.random() < self._rate:
            await asyncio.sleep(self._delay)


def _make_delayed(op_name: str):
    async def delayed(self, *args, **kwargs):
        await self._maybe_delay(op_name)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    delayed.__name__ = op_name
    return delayed


for _fop in Fop:
    if _fop is Fop.COMPOUND:
        # as in error-gen: chains must decompose through the per-fop
        # delay wrappers, not sail past them as one forwarded frame
        continue
    setattr(DelayGenLayer, _fop.value, _make_delayed(_fop.value))
