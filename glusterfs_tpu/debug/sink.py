"""debug/sink — terminate every fop with success.

Reference: xlators/debug/sink (sink.c, 93 LoC): a graph terminator
that answers everything positively without any backend, used to
isolate upper-layer behavior and as a load-generator target.  The
same trimmed fop set here: lookups/stats answer with a synthetic
root-ish iatt, writes swallow bytes, reads return empty.
"""

from __future__ import annotations

import time

from ..core.iatt import IAType, Iatt, ROOT_GFID
from ..core.layer import FdObj, Layer, Loc, register


def _ia(loc: Loc) -> Iatt:
    now = time.time()
    return Iatt(gfid=loc.gfid or ROOT_GFID, ia_type=IAType.DIR
                if (loc.path or "/") == "/" else IAType.REG,
                mode=0o755, uid=0, gid=0, size=0, nlink=1,
                atime=now, mtime=now, ctime=now)


@register("debug/sink")
class SinkLayer(Layer):
    async def lookup(self, loc: Loc, xdata: dict | None = None):
        return _ia(loc), {}

    async def stat(self, loc: Loc, xdata: dict | None = None):
        return _ia(loc)

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        return FdObj(loc.gfid or ROOT_GFID, flags, path=loc.path)

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        return FdObj(loc.gfid or ROOT_GFID, flags,
                     path=loc.path), _ia(loc)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        return len(data)

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        return b""

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        return {}

    async def release(self, fd: FdObj):
        return None

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        return _ia(loc)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        return {}

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        return []
