"""debug/error-gen — fault injection: fail fops with a configured errno at
a configured rate (reference xlators/debug/error-gen/error-gen.c:147,218:
options ``failure``, ``error-no``, ``enable`` fop list).  The test suite's
brick-failure scenarios ride on this, as in the reference's .t tests."""

from __future__ import annotations

import random

from ..core.fops import Fop, FopError
from ..core.layer import Layer, register
from ..core.options import Option

_ERRNO = {"EIO": 5, "ENOENT": 2, "EACCES": 13, "ENOSPC": 28, "EAGAIN": 11,
          "ENOTCONN": 107, "ESTALE": 116}


@register("debug/error-gen")
class ErrorGenLayer(Layer):
    OPTIONS = (
        Option("failure", "percent", default=0.0, min=0, max=100,
               description="probability (%) of injecting a failure"),
        Option("failure-count", "int", default=0, min=0,
               description="DETERMINISTIC mode: fail exactly the first "
                           "N matching fops, then pass (chaos scenarios "
                           "assert exact outcomes instead of tuning "
                           "probability + seed).  Re-arms on "
                           "reconfigure; overrides `failure` while the "
                           "budget lasts"),
        Option("error-no", "enum", default="EIO",
               values=tuple(_ERRNO), description="errno to inject"),
        Option("enable", "str", default="",
               description="comma-separated fop names ('' = all)"),
        Option("seed", "int", default=0),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._rng = random.Random(self.opts["seed"] or None)
        self.injected = 0
        self._install()

    def reconfigure(self, options):
        super().reconfigure(options)
        self._install()

    def _install(self):
        enabled = {s.strip() for s in self.opts["enable"].split(",")
                   if s.strip()}
        self._enabled = enabled or {f.value for f in Fop}
        self._rate = self.opts["failure"] / 100.0
        self._err = _ERRNO[self.opts["error-no"]]
        # deterministic budget: every (re)configure re-arms it in full
        self._count_mode = int(self.opts["failure-count"] or 0)
        self._budget = self._count_mode

    def _maybe_fail(self, op: str):
        if op not in self._enabled:
            return
        if self._count_mode:
            # failure-count mode: exactly the first N matching fops
            # fail, every later one passes — deterministic by design
            if self._budget > 0:
                self._budget -= 1
                self.injected += 1
                raise FopError(self._err,
                               f"error-gen injected on {op} "
                               f"({self._count_mode - self._budget}"
                               f"/{self._count_mode})")
            return
        if self._rate > 0 and self._rng.random() < self._rate:
            self.injected += 1
            raise FopError(self._err, f"error-gen injected on {op}")

    def dump_private(self) -> dict:
        return {"injected": self.injected,
                "count_budget_left": self._budget}


def _make_injected(op_name: str):
    async def injected(self, *args, **kwargs):
        self._maybe_fail(op_name)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    injected.__name__ = op_name
    return injected


for _fop in Fop:
    if _fop is Fop.COMPOUND:
        # keep Layer.compound's decompose-through-own-fops: a blanket
        # "injected compound" override would forward chains INTACT and
        # the per-fop injection would silently never bite chained fops
        continue
    setattr(ErrorGenLayer, _fop.value, _make_injected(_fop.value))
