"""debug/trace — log every fop passing through with args and outcome
(reference xlators/debug/trace/trace.c)."""

from __future__ import annotations

from ..core.fops import Fop, FopError
from ..core.layer import Layer, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("core.trace")


@register("debug/trace")
class TraceLayer(Layer):
    OPTIONS = (
        Option("log-history", "bool", default="on"),
        Option("exclude-ops", "str", default=""),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.history: list[str] = []
        self._excluded = self._parse_excluded()

    def _parse_excluded(self) -> set[str]:
        return {s.strip()
                for s in self.opts["exclude-ops"].split(",")
                if s.strip()}

    def reconfigure(self, options: dict) -> None:
        """A live ``volume set ... exclude-ops`` must take effect: the
        set is derived state of the option, so re-derive it (it was
        computed once in __init__ and silently ignored changes)."""
        super().reconfigure(options)
        self._excluded = self._parse_excluded()

    def _record(self, line: str):
        log.debug(1, "%s", line)
        if self.opts["log-history"]:
            self.history.append(line)
            if len(self.history) > 4096:
                del self.history[:2048]

    def dump_private(self) -> dict:
        return {"history_len": len(self.history),
                "recent": self.history[-20:]}


def _fmt(v, limit=64):
    s = repr(v)
    return s if len(s) <= limit else s[:limit] + "..."


def _make_traced(op_name: str):
    async def traced(self, *args, **kwargs):
        if op_name in self._excluded:
            return await getattr(self.children[0], op_name)(*args, **kwargs)
        args_s = ", ".join(_fmt(a) for a in args)
        try:
            ret = await getattr(self.children[0], op_name)(*args, **kwargs)
            self._record(f"{self.name}: {op_name}({args_s}) -> {_fmt(ret)}")
            return ret
        except FopError as e:
            self._record(f"{self.name}: {op_name}({args_s}) !! {e!r}")
            raise
    traced.__name__ = op_name
    return traced


for _fop in Fop:
    setattr(TraceLayer, _fop.value, _make_traced(_fop.value))
