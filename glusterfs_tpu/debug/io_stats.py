"""debug/io-stats — per-fop counters + latency profile at a graph position
(reference xlators/debug/io-stats/io-stats.c:129-197; backs ``volume
profile``/``volume top``).  The base Layer already counts per-fop
count/errors/latency (xlator_t.stats analog); io-stats adds interval
snapshots, byte counters for read/write, and a dump API."""

from __future__ import annotations

import time

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("io-stats")


@register("debug/io-stats")
class IoStatsLayer(Layer):
    OPTIONS = (
        Option("count-fop-hits", "bool", default="on"),
        Option("latency-measurement", "bool", default="on"),
        Option("fd-hard-limit", "int", default=2048,
               description="max distinct paths tracked for `volume "
                           "top` (io-stats ios_stat_list cap)"),
        Option("log-level", "enum", default="INFO",
               values=("TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                       "CRITICAL"),
               description="process log threshold — io-stats carries "
                           "the log-level option in the reference too "
                           "(diagnostics.brick-log-level / "
                           "client-log-level, io-stats.c); applied "
                           "live at reconfigure"),
        Option("dump-fd-stats", "bool", default="off",
               description="log per-path counters when a tracked "
                           "file's activity retires "
                           "(diagnostics.dump-fd-stats)"),
        Option("ios-dump-interval", "time", default="0",
               description="periodically log the profile snapshot "
                           "(diagnostics.stats-dump-interval; 0 = "
                           "off)"),
        Option("fop-sample-interval", "int", default=0, min=0,
               description="record every Nth fop into the sample ring "
                           "(diagnostics.fop-sample-interval; 0 = "
                           "off)"),
        Option("fop-sample-buf-size", "int", default=65535, min=1,
               description="sample ring capacity "
                           "(diagnostics.fop-sample-buf-size)"),
        Option("slow-fop-threshold", "time", default="0",
               description="log the full span tree of any fop slower "
                           "than this (diagnostics.slow-fop-threshold; "
                           "0 = off).  Applied process-wide: a slow "
                           "wire readv's log names the layer the time "
                           "went to (core/tracing.py)"),
        Option("span-ring-size", "int", default=4096, min=64,
               max=1 << 20,
               description="bound on the per-process trace-span ring "
                           "(diagnostics.span-ring-size)"),
        Option("incident-dir", "str", default="",
               description="directory for auto-captured incident "
                           "bundles (diagnostics.incident-dir; empty "
                           "disables capture — the flight-recorder "
                           "ring itself is always on, core/flight.py)"),
        Option("incident-max-bytes", "size", default="64MB",
               description="total size bound on the incident dir; "
                           "oldest bundles pruned first "
                           "(diagnostics.incident-max-bytes)"),
        Option("incident-min-interval", "time", default="60",
               description="min seconds between auto-captured bundles "
                           "— one outage, one bundle, not one per "
                           "breaker flap "
                           "(diagnostics.incident-min-interval)"),
        Option("flight-ring-size", "int", default=512, min=16,
               max=1 << 16,
               description="bound on the flight-recorder record ring "
                           "(diagnostics.flight-ring-size)"),
        Option("access-log", "bool", default="off",
               description="gateway structured access-log lines "
                           "(method, path, status, bytes, ms, trace) "
                           "per HTTP request "
                           "(diagnostics.access-log)"),
        Option("history-interval", "time", default="10",
               description="metrics history sampler cadence, seconds "
                           "(diagnostics.history-interval; the ring "
                           "clamps to a 0.05s floor) — each tick "
                           "captures one delta-compressed registry "
                           "snapshot (core/history.py)"),
        Option("history-retention", "time", default="600",
               description="how far back the metrics history ring "
                           "reaches, seconds "
                           "(diagnostics.history-retention; sample "
                           "count additionally hard-bounded)"),
        Option("slo-rules", "str", default="",
               description="JSON array of SLO alert rules evaluated "
                           "against the local history ring every "
                           "sampler tick (diagnostics.slo-rules; "
                           "empty = no alerting, the default — "
                           "core/slo.py documents the rule grammar)"),
    )

    _LOG_LEVELS = {"TRACE": 5, "DEBUG": 10, "INFO": 20, "WARNING": 30,
                   "ERROR": 40, "CRITICAL": 50}

    def _apply_log_level(self) -> None:
        import logging

        # scope to THIS framework's logger tree — the embedding app's
        # root logger configuration is not ours to overwrite
        logging.getLogger("glusterfs_tpu").setLevel(
            self._LOG_LEVELS.get(self.opts["log-level"], 20))

    def _apply_observability(self) -> None:
        """Push the process-wide observability knobs this layer owns
        (io-stats carries the diagnostics.* options in the reference
        too): histogram gate, slow-fop threshold, span-ring bound.
        A darkened process (GFTPU_NO_OBSERVABILITY / bench metrics-off)
        wins over the option defaults: latency-measurement's default
        'on' must not re-arm histograms at mount time."""
        from ..core import flight, history, slo
        from ..core import layer as layer_mod
        from ..core import tracing

        layer_mod.HISTOGRAMS_ENABLED = bool(
            self.opts["latency-measurement"]) and not tracing.DARK
        tracing.SLOW_FOP_THRESHOLD = float(
            self.opts["slow-fop-threshold"])
        tracing.set_ring_size(int(self.opts["span-ring-size"]))
        flight.set_ring_size(int(self.opts["flight-ring-size"]))
        flight.configure_capture(
            incident_dir=str(self.opts["incident-dir"]),
            max_bytes=int(self.opts["incident-max-bytes"]),
            min_interval=float(self.opts["incident-min-interval"]))
        flight.set_access_log(bool(self.opts["access-log"]))
        # history + SLO plane (v19): retune the ring live, install the
        # rule set, and make sure the sampler runs — any process with
        # an io-stats layer (brick, mounted client, gateway worker)
        # keeps history; arm() is idempotent and honours the dark gate
        history.configure(
            interval=float(self.opts["history-interval"]),
            retention=float(self.opts["history-retention"]))
        slo.configure(str(self.opts["slo-rules"]))
        history.arm()

    def _restart_dump_task(self) -> None:
        """Cancel + respawn the periodic profile dump so a live
        ``diagnostics.stats-dump-interval`` change takes effect (the
        old task would sleep on the stale interval forever)."""
        import asyncio

        t = getattr(self, "_dump_task", None)
        if t is not None:
            t.cancel()
            self._dump_task = None
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (offline reconfigure): init will arm it
        if float(self.opts["ios-dump-interval"]) > 0:
            self._dump_task = asyncio.create_task(self._dump_loop())

    def reconfigure(self, options: dict) -> None:
        old = self.opts["log-level"]
        old_interval = float(self.opts["ios-dump-interval"])
        super().reconfigure(options)
        if self.opts["log-level"] != old:
            self._apply_log_level()
        self._apply_observability()
        if float(self.opts["ios-dump-interval"]) != old_interval:
            self._restart_dump_task()

    def __init__(self, *args, **kw):
        from collections import OrderedDict

        super().__init__(*args, **kw)
        self.read_bytes = 0
        self.write_bytes = 0
        self.started = time.time()
        self._interval_base: dict = {}
        # per-path counters backing `volume top` (ios_stat_head): path
        # -> {opens, reads, writes, read_bytes, write_bytes}; LRU so
        # eviction at the cap is O(1), not a full scan per hot fop
        self._per_path: "OrderedDict[str, dict]" = OrderedDict()

    def _path_stat(self, path: str | None) -> dict | None:
        if not path:
            return None
        st = self._per_path.get(path)
        if st is None:
            if len(self._per_path) >= self.opts["fd-hard-limit"]:
                # bounded like the reference's fixed-size stat list:
                # evict the least-recently-touched path
                old_path, old = self._per_path.popitem(last=False)
                if self.opts["dump-fd-stats"]:
                    # diagnostics.dump-fd-stats: a retiring file's
                    # counters go to the log (io_stats_dump_fd)
                    log.info(4, "%s: fd-stats %s: %s", self.name,
                             old_path, old)
            st = self._per_path[path] = {
                "opens": 0, "reads": 0, "writes": 0,
                "read_bytes": 0, "write_bytes": 0}
        else:
            self._per_path.move_to_end(path)
        return st

    def _sample(self, op: str, path: str | None) -> None:
        """diagnostics.fop-sample-interval: every Nth data fop lands in
        a bounded ring (ios_sample_buf) readable via statedump."""
        n = int(self.opts["fop-sample-interval"])
        if not n:
            return
        self._fop_seen = getattr(self, "_fop_seen", 0) + 1
        if self._fop_seen % n:
            return
        import collections

        ring = getattr(self, "_samples", None)
        cap = int(self.opts["fop-sample-buf-size"])
        if ring is None or ring.maxlen != cap:
            ring = collections.deque(
                list(ring or ())[-cap:], maxlen=cap)
            self._samples = ring
        ring.append({"ts": round(time.time(), 3), "op": op,
                     "path": path or ""})

    async def _dump_loop(self):
        import asyncio

        while True:
            await asyncio.sleep(float(self.opts["ios-dump-interval"]))
            log.info(5, "%s: profile %s", self.name,
                     self.profile(interval=True))

    async def init(self):
        import asyncio

        await super().init()
        if self.opts["log-level"] != "INFO":
            # only an explicit operator setting touches the level: the
            # default must not override an embedding app's config
            self._apply_log_level()
        self._apply_observability()
        self._dump_task = None
        if float(self.opts["ios-dump-interval"]) > 0:
            self._dump_task = asyncio.create_task(self._dump_loop())

    async def fini(self):
        t = getattr(self, "_dump_task", None)
        if t is not None:
            t.cancel()
            self._dump_task = None
        await super().fini()

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        fd = await self.children[0].open(loc, flags, xdata)
        self._sample("open", loc.path)
        st = self._path_stat(loc.path)
        if st is not None:
            st["opens"] += 1
        return fd

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        out = await self.children[0].create(loc, flags, mode, xdata)
        self._sample("create", loc.path)
        st = self._path_stat(loc.path)
        if st is not None:
            st["opens"] += 1
        return out

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        data = await self.children[0].readv(fd, size, offset, xdata)
        self._sample("readv", getattr(fd, "path", None))
        self.read_bytes += len(data)
        st = self._path_stat(getattr(fd, "path", None))
        if st is not None:
            st["reads"] += 1
            st["read_bytes"] += len(data)
        return data

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ret = await self.children[0].writev(fd, data, offset, xdata)
        self._sample("writev", getattr(fd, "path", None))
        self.write_bytes += len(data)
        st = self._path_stat(getattr(fd, "path", None))
        if st is not None:
            st["writes"] += 1
            st["write_bytes"] += len(data)
        return ret

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Forward chains intact (accounting is side-effect-free) and
        replay the per-fop byte/open counters from the reply vector —
        fused traffic must not vanish from `volume profile`."""
        from ..rpc import compound as cfop

        def link_path(args) -> str | None:
            """Best path for a link's per-path counters: its own
            Loc/FdObj, or — for FdRef links (the fd is minted BY the
            chain) — the producer link's Loc.  Resolving through the
            reply vector would miss: released chain fds are stripped
            from the replies before they get here."""
            for a in args:
                if isinstance(a, Loc):
                    return a.path
                if isinstance(a, FdObj):
                    return getattr(a, "path", None)
                ref = a if isinstance(a, cfop.FdRef) else None
                if ref is None and isinstance(a, dict) and \
                        len(a) == 1 and cfop.FD_LINK_KEY in a:
                    ref = cfop.FdRef(a[cfop.FD_LINK_KEY])
                if ref is not None and 0 <= ref.index < len(links):
                    for pa in links[ref.index][1]:
                        if isinstance(pa, Loc):
                            return pa.path
                    return None
            return None

        replies = await self.children[0].compound(links, xdata)
        for (fop, args, _kw), (st, val) in zip(links, replies):
            if st != "ok":
                continue
            path = link_path(args)
            self._sample(fop, path)
            st_rec = self._path_stat(path)
            if fop in ("open", "create") and st_rec is not None:
                st_rec["opens"] += 1
            elif fop == "writev":
                data = args[1] if len(args) > 1 else b""
                n = len(data) if isinstance(
                    data, (bytes, bytearray, memoryview)) else 0
                self.write_bytes += n
                if st_rec is not None:
                    st_rec["writes"] += 1
                    st_rec["write_bytes"] += n
            elif fop == "readv":
                # reply-value bytes: PR 3's fused read chains must not
                # vanish from `volume profile` — the reply is bytes, a
                # frame memoryview, or an SGBuf, all sized by len()
                try:
                    n = len(val) if val is not None else 0
                except TypeError:
                    n = 0
                self.read_bytes += n
                if st_rec is not None:
                    st_rec["reads"] += 1
                    st_rec["read_bytes"] += n
        return replies

    # -- `volume top` backend (io-stats ios_stat_list) ---------------------

    def top(self, metric: str = "open", count: int = 10) -> list:
        """Top paths by metric: open | read | write | read-bytes |
        write-bytes (gluster volume top semantics)."""
        key = {"open": "opens", "read": "reads", "write": "writes",
               "read-bytes": "read_bytes",
               "write-bytes": "write_bytes"}.get(metric)
        if key is None:
            raise ValueError(f"unknown top metric {metric!r}")
        ranked = sorted(self._per_path.items(),
                        key=lambda kv: kv[1][key], reverse=True)
        return [{"path": p, **st} for p, st in ranked[:count]
                if st[key] > 0]

    async def top_stats(self, metric: str = "open",
                        count: int = 10) -> list:
        """RPC surface for ``gluster volume top`` (the brick server
        resolves this by graph walk, like quota_usage)."""
        return self.top(metric, count)

    async def metrics_dump(self) -> dict:
        """RPC surface for ``gftpu volume metrics`` (resolved by graph
        walk like top_stats): this process's unified-registry scrape —
        counters/gauges/histograms from every subsystem that registered
        (core/metrics.py)."""
        from ..core.metrics import REGISTRY

        return REGISTRY.snapshot()

    # -- profile API (volume profile incremental/cumulative analog) --------

    def profile(self, *, interval: bool = False) -> dict:
        cur = {op: st.to_dict() for op, st in self.stats.items()}
        out = {
            "uptime_s": time.time() - self.started,
            "read_bytes": self.read_bytes,
            "write_bytes": self.write_bytes,
            "fops": cur,
        }
        if interval:
            base = self._interval_base
            delta = {}
            for op, st in cur.items():
                prev = base.get("fops", {}).get(op, {})
                delta[op] = {k: st[k] - prev.get(k, 0)
                             for k in ("count", "errors")}
            out["interval"] = delta
            self._interval_base = {"fops": cur}
        return out

    def dump_private(self) -> dict:
        out = self.profile()
        ring = getattr(self, "_samples", None)
        if ring:
            out["fop_samples"] = list(ring)[-64:]  # bounded dump slice
        return out
