"""debug/io-stats — per-fop counters + latency profile at a graph position
(reference xlators/debug/io-stats/io-stats.c:129-197; backs ``volume
profile``/``volume top``).  The base Layer already counts per-fop
count/errors/latency (xlator_t.stats analog); io-stats adds interval
snapshots, byte counters for read/write, and a dump API."""

from __future__ import annotations

import time

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("debug/io-stats")
class IoStatsLayer(Layer):
    OPTIONS = (
        Option("count-fop-hits", "bool", default="on"),
        Option("latency-measurement", "bool", default="on"),
        Option("fd-hard-limit", "int", default=2048,
               description="max distinct paths tracked for `volume "
                           "top` (io-stats ios_stat_list cap)"),
    )

    def __init__(self, *args, **kw):
        from collections import OrderedDict

        super().__init__(*args, **kw)
        self.read_bytes = 0
        self.write_bytes = 0
        self.started = time.time()
        self._interval_base: dict = {}
        # per-path counters backing `volume top` (ios_stat_head): path
        # -> {opens, reads, writes, read_bytes, write_bytes}; LRU so
        # eviction at the cap is O(1), not a full scan per hot fop
        self._per_path: "OrderedDict[str, dict]" = OrderedDict()

    def _path_stat(self, path: str | None) -> dict | None:
        if not path:
            return None
        st = self._per_path.get(path)
        if st is None:
            if len(self._per_path) >= self.opts["fd-hard-limit"]:
                # bounded like the reference's fixed-size stat list:
                # evict the least-recently-touched path
                self._per_path.popitem(last=False)
            st = self._per_path[path] = {
                "opens": 0, "reads": 0, "writes": 0,
                "read_bytes": 0, "write_bytes": 0}
        else:
            self._per_path.move_to_end(path)
        return st

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        fd = await self.children[0].open(loc, flags, xdata)
        st = self._path_stat(loc.path)
        if st is not None:
            st["opens"] += 1
        return fd

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        out = await self.children[0].create(loc, flags, mode, xdata)
        st = self._path_stat(loc.path)
        if st is not None:
            st["opens"] += 1
        return out

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        data = await self.children[0].readv(fd, size, offset, xdata)
        self.read_bytes += len(data)
        st = self._path_stat(getattr(fd, "path", None))
        if st is not None:
            st["reads"] += 1
            st["read_bytes"] += len(data)
        return data

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ret = await self.children[0].writev(fd, data, offset, xdata)
        self.write_bytes += len(data)
        st = self._path_stat(getattr(fd, "path", None))
        if st is not None:
            st["writes"] += 1
            st["write_bytes"] += len(data)
        return ret

    # -- `volume top` backend (io-stats ios_stat_list) ---------------------

    def top(self, metric: str = "open", count: int = 10) -> list:
        """Top paths by metric: open | read | write | read-bytes |
        write-bytes (gluster volume top semantics)."""
        key = {"open": "opens", "read": "reads", "write": "writes",
               "read-bytes": "read_bytes",
               "write-bytes": "write_bytes"}.get(metric)
        if key is None:
            raise ValueError(f"unknown top metric {metric!r}")
        ranked = sorted(self._per_path.items(),
                        key=lambda kv: kv[1][key], reverse=True)
        return [{"path": p, **st} for p, st in ranked[:count]
                if st[key] > 0]

    async def top_stats(self, metric: str = "open",
                        count: int = 10) -> list:
        """RPC surface for ``gluster volume top`` (the brick server
        resolves this by graph walk, like quota_usage)."""
        return self.top(metric, count)

    # -- profile API (volume profile incremental/cumulative analog) --------

    def profile(self, *, interval: bool = False) -> dict:
        cur = {op: st.to_dict() for op, st in self.stats.items()}
        out = {
            "uptime_s": time.time() - self.started,
            "read_bytes": self.read_bytes,
            "write_bytes": self.write_bytes,
            "fops": cur,
        }
        if interval:
            base = self._interval_base
            delta = {}
            for op, st in cur.items():
                prev = base.get("fops", {}).get(op, {})
                delta[op] = {k: st[k] - prev.get(k, 0)
                             for k in ("count", "errors")}
            out["interval"] = delta
            self._interval_base = {"fops": cur}
        return out

    def dump_private(self) -> dict:
        return self.profile()
