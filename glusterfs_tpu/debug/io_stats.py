"""debug/io-stats — per-fop counters + latency profile at a graph position
(reference xlators/debug/io-stats/io-stats.c:129-197; backs ``volume
profile``/``volume top``).  The base Layer already counts per-fop
count/errors/latency (xlator_t.stats analog); io-stats adds interval
snapshots, byte counters for read/write, and a dump API."""

from __future__ import annotations

import time

from ..core.layer import FdObj, Layer, register
from ..core.options import Option


@register("debug/io-stats")
class IoStatsLayer(Layer):
    OPTIONS = (
        Option("count-fop-hits", "bool", default="on"),
        Option("latency-measurement", "bool", default="on"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.read_bytes = 0
        self.write_bytes = 0
        self.started = time.time()
        self._interval_base: dict = {}

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        data = await self.children[0].readv(fd, size, offset, xdata)
        self.read_bytes += len(data)
        return data

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ret = await self.children[0].writev(fd, data, offset, xdata)
        self.write_bytes += len(data)
        return ret

    # -- profile API (volume profile incremental/cumulative analog) --------

    def profile(self, *, interval: bool = False) -> dict:
        cur = {op: st.to_dict() for op, st in self.stats.items()}
        out = {
            "uptime_s": time.time() - self.started,
            "read_bytes": self.read_bytes,
            "write_bytes": self.write_bytes,
            "fops": cur,
        }
        if interval:
            base = self._interval_base
            delta = {}
            for op, st in cur.items():
                prev = base.get("fops", {}).get(op, {})
                delta[op] = {k: st[k] - prev.get(k, 0)
                             for k in ("count", "errors")}
            out["interval"] = delta
            self._interval_base = {"fops": cur}
        return out

    def dump_private(self) -> dict:
        return self.profile()
