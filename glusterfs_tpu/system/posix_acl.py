"""system/posix-acl — POSIX ACL permission checks in the graph.

Reference: xlators/system/posix-acl (posix-acl.c): evaluates mode bits
plus ``system.posix_acl_access`` entries against the caller's
uid/gid for every access-controlled fop, so permissions hold even when
the backing store runs as root.  Caller identity rides xdata
(``uid``/``gid``/``groups`` — the FUSE bridge fills these from the
kernel request header; in-process API callers may pass them
explicitly; absent identity means a trusted internal caller and checks
are skipped, like the reference's frame->root->pid < 0 bypass).

ACL storage: the xattr value is a JSON list of entries
``[{"tag": "user"|"group"|"other"|"mask", "qual": id|null,
"perm": rwx-bits}]`` kept verbatim by the store; minimal-but-real
evaluation order per POSIX 1003.1e: owner -> named users -> owning /
named groups (masked) -> other."""

from __future__ import annotations

import errno
import json

from ..core.fops import FopError
from ..core.iatt import Iatt
from ..core.layer import Layer, Loc, register

XA_ACL = "system.posix_acl_access"

R, W, X = 4, 2, 1


def _entries(raw: bytes | None):
    if not raw:
        return None
    try:
        return json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return None


def acl_permits(ia: Iatt, acl, uid: int, gid: int, groups, want: int):
    """POSIX 1003.1e short-circuit evaluation."""
    if uid == 0:
        return True
    mode = ia.mode
    if uid == ia.uid:
        return (mode >> 6) & want == want
    groups = set(groups or ()) | {gid}
    if acl:
        mask = next((e["perm"] for e in acl if e["tag"] == "mask"), 7)
        for e in acl:
            if e["tag"] == "user" and e["qual"] == uid:
                return e["perm"] & mask & want == want
        group_es = [e for e in acl if e["tag"] == "group"]
        applicable = [e for e in group_es if e["qual"] in groups] + \
            ([{"perm": (mode >> 3) & 7}] if ia.gid in groups else [])
        if applicable:
            return any(e["perm"] & mask & want == want
                       for e in applicable)
        other = next((e["perm"] for e in acl if e["tag"] == "other"),
                     mode & 7)
        return other & want == want
    if ia.gid in groups:
        return (mode >> 3) & want == want
    return mode & want == want


@register("system/posix-acl")
class PosixAclLayer(Layer):
    async def _acl_of(self, loc: Loc):
        try:
            xa = await self.children[0].getxattr(loc, XA_ACL)
        except FopError:
            return None
        return _entries((xa or {}).get(XA_ACL))

    async def _check(self, loc: Loc, want: int,
                     xdata: dict | None) -> None:
        if not xdata or "uid" not in xdata:
            return  # trusted internal caller
        ia, _ = await self.children[0].lookup(loc)
        acl = await self._acl_of(loc)
        if not acl_permits(ia, acl, int(xdata["uid"]),
                           int(xdata.get("gid", -1)),
                           xdata.get("groups"), want):
            raise FopError(errno.EACCES,
                           f"{loc.path}: permission denied")

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        import os as _os

        acc = flags & _os.O_ACCMODE
        want = {_os.O_RDONLY: R, _os.O_WRONLY: W,
                _os.O_RDWR: R | W}.get(acc)
        if want is None:  # O_WRONLY|O_RDWR together is invalid
            raise FopError(errno.EINVAL, f"bad access mode {acc}")
        await self._check(loc, want, xdata)
        return await self.children[0].open(loc, flags, xdata)

    async def access(self, loc: Loc, mask: int = 0,
                     xdata: dict | None = None):
        await self._check(loc, mask & 7, xdata)
        return {}

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        await self._check(loc, R, xdata)
        return await self.children[0].opendir(loc, xdata)

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        if loc.path and "/" in loc.path.rstrip("/"):
            parent = loc.path.rsplit("/", 1)[0] or "/"
            await self._check(Loc(parent), W | X, xdata)
        return await self.children[0].create(loc, flags, mode, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        parent = loc.path.rsplit("/", 1)[0] or "/"
        await self._check(Loc(parent), W | X, xdata)
        return await self.children[0].unlink(loc, xdata)


def _self_write_gated(op_name: str):
    """Mutations of the object's data need W on it."""
    async def impl(self, loc: Loc, *args, **kwargs):
        from ..core.virtfs import extract_xdata

        xd = extract_xdata(self.children[0], op_name,
                           (loc, *args), kwargs)
        await self._check(loc, W, xd)
        return await getattr(self.children[0], op_name)(loc, *args,
                                                        **kwargs)
    impl.__name__ = op_name
    return impl


def _acl_key(arg) -> bool:
    """Does this setxattr dict / removexattr name touch ACL xattrs?"""
    keys = arg.keys() if isinstance(arg, dict) else (arg,)
    return any(str(k).startswith("system.posix_acl") for k in keys)


def _owner_or_write_gated(op_name: str, always_owner: bool):
    """chmod/chown (setattr) and ACL changes need OWNERSHIP, not W —
    a 0444 file's owner can still chmod it, and group-writers cannot
    (POSIX; reference posix_acl_setattr uid check).  Non-ACL xattrs
    are data-adjacent: plain W.  Timestamp-only setattr (the utimes
    path) needs only W: POSIX lets any writer touch atime/mtime, and
    the reference's posix-acl setattr gate does the same — only
    mode/uid/gid changes demand ownership."""
    async def impl(self, loc: Loc, *args, **kwargs):
        from ..core.virtfs import extract_arg, extract_xdata

        xd = extract_xdata(self.children[0], op_name,
                           (loc, *args), kwargs)
        # resolve the xattr payload by NAME: a caller may pass it
        # positionally or as a keyword, and both must hit the gate
        payload = None
        if not always_owner:
            payload = extract_arg(
                self.children[0], op_name, (loc, *args), kwargs,
                "xattrs" if op_name == "setxattr" else "name")
        owner_op = always_owner or (payload is not None
                                    and _acl_key(payload))
        touch_now = False
        if op_name == "setattr" and owner_op:
            attrs = extract_arg(self.children[0], op_name,
                                (loc, *args), kwargs, "attrs")
            if isinstance(attrs, dict) and attrs and \
                    set(attrs) <= {"atime", "mtime"} and \
                    all(v is None for v in attrs.values()):
                # touch-to-now (UTIME_NOW, value None): owner OR any
                # W-holder may do it; EXPLICIT timestamps still demand
                # ownership (utimensat(2) — else any group-writer could
                # forge mtimes and defeat mtime-based change detection)
                touch_now = True
        if xd and "uid" in xd and not owner_op:
            await self._check(loc, W, xd)
        elif xd and "uid" in xd:
            uid = int(xd["uid"])
            if uid != 0:
                ia, _ = await self.children[0].lookup(loc)
                if uid != ia.uid:
                    if not touch_now:
                        raise FopError(errno.EPERM,
                                       f"{loc.path}: not owner")
                    await self._check(loc, W, xd)  # non-owner touch
        return await getattr(self.children[0], op_name)(loc, *args,
                                                        **kwargs)
    impl.__name__ = op_name
    return impl


def _parent_write_gated(op_name: str, locidx: tuple):
    """Namespace mutations need W|X on the parent of each mutated
    name (for link only the NEW name's parent — reading the source
    needs no write access)."""
    async def impl(self, *args, **kwargs):
        from ..core.virtfs import extract_xdata

        xd = extract_xdata(self.children[0], op_name, args, kwargs)
        for i in locidx:
            a = args[i] if i < len(args) else None
            if isinstance(a, Loc) and a.path:
                parent = a.path.rsplit("/", 1)[0] or "/"
                await self._check(Loc(parent), W | X, xd)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    impl.__name__ = op_name
    return impl


setattr(PosixAclLayer, "truncate", _self_write_gated("truncate"))
setattr(PosixAclLayer, "setattr", _owner_or_write_gated("setattr", True))
for _op in ("setxattr", "removexattr"):
    setattr(PosixAclLayer, _op, _owner_or_write_gated(_op, False))
for _op, _idx in (("mkdir", (0,)), ("mknod", (0,)), ("rmdir", (0,)),
                  ("symlink", (0, 1)), ("rename", (0, 1)),
                  ("link", (1,))):
    setattr(PosixAclLayer, _op, _parent_write_gated(_op, _idx))
