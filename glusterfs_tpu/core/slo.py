"""Declarative SLO engine — burn-rate alerting over the history ring.

The history ring (:mod:`core.history`) gives every daemon a local time
series; this module evaluates **rules** against it on every sampler
tick and turns sustained badness into *transition-edge* alerts:
``ALERT_RAISED`` fires once when a rule starts breaching and
``ALERT_CLEARED`` once when it stops — never per evaluation (the
THROTTLE_START/STOP convention).  A RAISED edge is failure-class: it
rides :func:`core.events.gf_event` into eventsd/webhooks AND
auto-captures an incident bundle through the PR-19 door
(``flight.FAILURE_EVENTS``), so the bundle's embedded history section
shows the ramp that tripped the rule.

Rule grammar (``diagnostics.slo-rules``, op-version 19): a JSON array
of rule objects — shipped EMPTY by default; alerting is strictly
opt-in.  Common fields: ``name`` (unique), ``kind``, optional
``labels`` (label-subset filter on metric keys).  Kinds:

* ``latency-threshold`` — ``{"metric", "target"(s), "window"(s)}``:
  breaches while the newest value of any matching series inside the
  window exceeds ``target`` (point quantile gauges like
  ``gftpu_gateway_request_seconds{quantile="p99"}``).
* ``error-ratio`` — ``{"errors", "total", "target"(ratio),
  "window"}``: windowed ``increase(errors)/increase(total)`` above
  ``target`` breaches; zero traffic never breaches.
* ``burn-rate`` — ``{"errors", "total", "slo"(e.g. 0.999), "fast"(s),
  "slow"(s), "factor"}``: the multiwindow burn-rate alert — breaches
  only while BOTH windows burn error budget (``ratio/(1-slo)``)
  faster than ``factor``; the fast window bounds detection time, the
  slow window vetoes blips.
* ``absence`` — ``{"metric", "window"}``: breaches when no matching
  sample landed within ``window`` — covers both a vanished series and
  a stalled sampler (staleness), because a dead sampler stops
  producing points for *every* key.
"""

from __future__ import annotations

import collections
import json
import time
from typing import Any

from . import gflog, history
from .metrics import REGISTRY

log = gflog.get_logger("core.slo")

_KINDS = ("latency-threshold", "error-ratio", "burn-rate", "absence")
#: evaluation needs this much series beyond the longest rule window so
#: the window's left edge has a baseline point for increase()
_WINDOW_SLACK = 2.0

_transition_counts = {"raised": 0, "cleared": 0}


def _required(kind: str) -> tuple[str, ...]:
    return {"latency-threshold": ("metric", "target"),
            "error-ratio": ("errors", "total", "target"),
            "burn-rate": ("errors", "total", "slo"),
            "absence": ("metric",)}[kind]


def validate_rule(rule: Any) -> str | None:
    """One rule's validation error, or None.  Kept standalone so
    glusterd can reject a bad ``volume set`` value up front instead of
    letting every daemon log it."""
    if not isinstance(rule, dict):
        return f"rule is not an object: {rule!r}"
    name = rule.get("name")
    if not name or not isinstance(name, str):
        return f"rule missing a name: {rule!r}"
    kind = rule.get("kind")
    if kind not in _KINDS:
        return f"{name}: unknown kind {kind!r} (one of {_KINDS})"
    for field in _required(kind):
        if field not in rule:
            return f"{name}: {kind} rule missing {field!r}"
    for field in ("target", "window", "slo", "fast", "slow", "factor"):
        if field in rule:
            try:
                float(rule[field])
            except (TypeError, ValueError):
                return f"{name}: {field} is not a number: {rule[field]!r}"
    if kind == "burn-rate" and not 0.0 < float(rule["slo"]) < 1.0:
        return f"{name}: slo must be in (0, 1), got {rule['slo']}"
    if "labels" in rule and not isinstance(rule["labels"], dict):
        return f"{name}: labels must be an object"
    return None


def parse_rules(text: str) -> tuple[list[dict], list[str]]:
    """``diagnostics.slo-rules`` value -> (valid rules, errors).
    Empty/blank means no rules (the shipped default)."""
    text = (text or "").strip()
    if not text:
        return [], []
    try:
        raw = json.loads(text)
    except ValueError as e:
        return [], [f"slo-rules is not valid JSON: {e}"]
    if not isinstance(raw, list):
        return [], ["slo-rules must be a JSON array of rule objects"]
    rules, errors, seen = [], [], set()
    for r in raw:
        err = validate_rule(r)
        if err is None and r["name"] in seen:
            err = f"duplicate rule name {r['name']!r}"
        if err is not None:
            errors.append(err)
            continue
        seen.add(r["name"])
        rules.append(r)
    return rules, errors


class SloEngine:
    """Evaluates a rule set against one history ring; tracks breach
    state per rule and fires transition-edge events."""

    def __init__(self, ring: history.HistoryRing | None = None):
        self.ring = ring if ring is not None else history.HISTORY
        self.rules: list[dict] = []
        self.rule_errors: list[str] = []
        self.active: dict[str, dict] = {}
        self.transitions: collections.deque = collections.deque(maxlen=256)

    def set_rules(self, rules: list[dict],
                  errors: list[str] | None = None) -> None:
        self.rules = list(rules)
        self.rule_errors = list(errors or [])
        # a removed rule must not stay RAISED forever
        for name in [n for n in self.active
                     if n not in {r["name"] for r in self.rules}]:
            self._clear(name, time.time(), reason="rule-removed")

    # -- rule evaluation ---------------------------------------------------

    def _select(self, series: dict, family: str,
                labels: dict | None) -> dict[str, list]:
        out = {}
        for key, pts in series.items():
            if history.key_family(key) != family:
                continue
            if labels:
                kl = history.key_labels(key)
                if any(kl.get(lk) != str(lv)
                       for lk, lv in labels.items()):
                    continue
            out[key] = pts
        return out

    def _increase(self, series: dict, family: str, labels: dict | None,
                  t0: float, t1: float) -> float:
        return sum(history.increase(pts, t0, t1) for pts in
                   self._select(series, family, labels).values())

    def _ratio(self, series: dict, rule: dict, window: float,
               now: float) -> float | None:
        labels = rule.get("labels")
        total = self._increase(series, rule["total"], labels,
                               now - window, now)
        if total <= 0:
            return None  # zero traffic burns no budget
        errs = self._increase(series, rule["errors"], labels,
                              now - window, now)
        return errs / total

    def _observe(self, rule: dict, series: dict,
                 now: float) -> tuple[bool, float | None, float]:
        """-> (breaching, observed, target) for one rule."""
        kind = rule["kind"]
        if kind == "latency-threshold":
            window = float(rule.get("window", 60.0))
            target = float(rule["target"])
            newest = [pts[-1][1] for pts in
                      self._select(series, rule["metric"],
                                   rule.get("labels")).values()
                      if pts and now - pts[-1][0] <= window]
            observed = max(newest) if newest else None
            return (observed is not None and observed > target,
                    observed, target)
        if kind == "error-ratio":
            window = float(rule.get("window", 60.0))
            target = float(rule["target"])
            observed = self._ratio(series, rule, window, now)
            return (observed is not None and observed > target,
                    observed, target)
        if kind == "burn-rate":
            fast = float(rule.get("fast", 300.0))
            slow = float(rule.get("slow", 3600.0))
            factor = float(rule.get("factor", 14.4))
            budget = 1.0 - float(rule["slo"])
            rf = self._ratio(series, rule, fast, now)
            rs = self._ratio(series, rule, slow, now)
            burn_f = (rf / budget) if rf is not None else None
            burn_s = (rs / budget) if rs is not None else None
            breach = (burn_f is not None and burn_s is not None
                      and burn_f >= factor and burn_s >= factor)
            return breach, burn_f, factor
        # absence: no matching point within the window = breach (a
        # stalled sampler stops producing points for every key, so
        # staleness trips this too)
        window = float(rule.get("window", 120.0))
        pts = self._select(series, rule["metric"], rule.get("labels"))
        newest = max((p[-1][0] for p in pts.values() if p),
                     default=0.0)
        return now - newest > window, now - newest, window

    def evaluate(self, now: float | None = None) -> dict[str, dict]:
        """One pass over every rule (the sampler tick hook); returns
        the active-alert map after transitions fire."""
        if not self.rules:
            return self.active
        now = time.time() if now is None else float(now)
        longest = max((max(float(r.get("window", 60.0)),
                           float(r.get("slow", 3600.0))
                           if r["kind"] == "burn-rate" else 0.0)
                       for r in self.rules), default=60.0)
        series = self.ring.series(
            window=longest + _WINDOW_SLACK * max(1.0, self.ring.interval),
            now=now)
        for rule in self.rules:
            try:
                breach, observed, target = self._observe(rule, series, now)
            except Exception as e:  # noqa: BLE001 - one bad rule only
                log.warning(1, "slo rule %s evaluation failed: %r",
                            rule.get("name"), e)
                continue
            name = rule["name"]
            if breach and name not in self.active:
                self._raise(rule, now, observed, target)
            elif not breach and name in self.active:
                self._clear(name, now, observed=observed)
            elif name in self.active:
                self.active[name]["observed"] = observed
                self.active[name]["last_eval"] = now
        return self.active

    # -- transition edges --------------------------------------------------

    def _window_of(self, rule: dict) -> float:
        if rule["kind"] == "burn-rate":
            return float(rule.get("fast", 300.0))
        return float(rule.get("window",
                              120.0 if rule["kind"] == "absence"
                              else 60.0))

    def _raise(self, rule: dict, now: float, observed, target) -> None:
        from . import events

        name = rule["name"]
        alert = {"rule": name, "kind": rule["kind"], "since": now,
                 "observed": observed, "target": target,
                 "window": self._window_of(rule), "last_eval": now}
        self.active[name] = alert
        self.transitions.append({"ts": now, "edge": "RAISED", **{
            k: alert[k] for k in ("rule", "kind", "observed",
                                  "target", "window")}})
        _transition_counts["raised"] += 1
        log.warning(2, "ALERT RAISED: %s (%s) observed=%r target=%r",
                    name, rule["kind"], observed, target)
        # failure-class: the gf_event tap auto-captures an incident
        # bundle whose history section shows the ramp (flight.py)
        events.gf_event("ALERT_RAISED", rule=name, kind=rule["kind"],
                        window=alert["window"], observed=observed,
                        target=target)

    def _clear(self, name: str, now: float, observed=None,
               reason: str = "") -> None:
        from . import events

        alert = self.active.pop(name, None)
        if alert is None:
            return
        duration = round(now - alert["since"], 3)
        rec = {"ts": now, "edge": "CLEARED", "rule": name,
               "kind": alert["kind"], "observed": observed,
               "target": alert["target"], "duration": duration}
        if reason:
            rec["reason"] = reason
        self.transitions.append(rec)
        _transition_counts["cleared"] += 1
        log.info(3, "ALERT CLEARED: %s after %.1fs", name, duration)
        events.gf_event("ALERT_CLEARED", rule=name, kind=alert["kind"],
                        duration=duration, observed=observed,
                        target=alert["target"])

    # -- surfaces ----------------------------------------------------------

    def status(self) -> dict:
        """The ``__alerts__`` door / ``/alerts.json`` shape: rules as
        configured (+ validation errors), the active set, recent
        transition history."""
        return {"rules": list(self.rules),
                "rule_errors": list(self.rule_errors),
                "active": sorted(self.active.values(),
                                 key=lambda a: a["since"]),
                "history": list(self.transitions)}


#: THE process engine, bound to the process history ring; daemons feed
#: it through configure() and the sampler tick hook
ENGINE = SloEngine()

REGISTRY.register(
    "gftpu_slo_alerts_active", "gauge",
    "currently-raised SLO alerts (one sample per breaching rule)",
    lambda: [({"rule": n, "kind": a["kind"]}, 1)
             for n, a in sorted(ENGINE.active.items())])
REGISTRY.register(
    "gftpu_slo_transitions_total", "counter",
    "SLO alert transition edges by direction",
    lambda: [({"edge": k}, v)
             for k, v in sorted(_transition_counts.items())])


def configure(rules_text: str) -> list[str]:
    """The diagnostics.slo-rules option push (io-stats, both graph
    ends) / daemon argv arm: install the rule set on the process
    engine, hook evaluation onto the sampler tick, and register the
    active-alert set as an incident-bundle section.  Returns
    validation errors (also logged — a bad rule loses itself, never
    the set)."""
    rules, errors = parse_rules(rules_text)
    for err in errors:
        log.warning(4, "slo-rules: %s", err)
    ENGINE.set_rules(rules, errors)
    if rules:
        from . import flight

        history.add_tick_hook(_tick)
        flight.add_section("alerts", lambda: {
            "active": sorted(ENGINE.active.values(),
                             key=lambda a: a["since"]),
            "transitions": list(ENGINE.transitions)[-32:]})
    return errors


def _tick() -> None:
    ENGINE.evaluate()


__all__ = ["SloEngine", "ENGINE", "parse_rules", "validate_rule",
           "configure"]
