"""Inode attributes (stat) — the wire/stack representation of file metadata.

Reference: ``gf_iatt`` in rpc/xdr/src/glusterfs4-xdr.x:31 and
libglusterfs/src/glusterfs/iatt.h.  GFIDs are uuid4 bytes; ia_type uses the
same file-type vocabulary.
"""

from __future__ import annotations

import dataclasses
import enum
import stat as _stat
import time
import uuid


class IAType(enum.Enum):
    INVAL = 0
    REG = 1
    DIR = 2
    LNK = 3
    BLK = 4
    CHR = 5
    FIFO = 6
    SOCK = 7


def gfid_new() -> bytes:
    return uuid.uuid4().bytes


#: The root of every volume has the fixed GFID 00..01 (reference
#: libglusterfs: inode table root; tests address it directly).
ROOT_GFID = b"\x00" * 15 + b"\x01"


@dataclasses.dataclass
class Iatt:
    gfid: bytes = b"\x00" * 16
    ia_type: IAType = IAType.INVAL
    mode: int = 0
    nlink: int = 1
    uid: int = 0
    gid: int = 0
    size: int = 0
    blocks: int = 0
    atime: float = 0.0
    mtime: float = 0.0
    ctime: float = 0.0
    rdev: int = 0
    blksize: int = 4096

    @classmethod
    def from_stat(cls, st, gfid: bytes) -> "Iatt":
        mode = st.st_mode
        if _stat.S_ISDIR(mode):
            t = IAType.DIR
        elif _stat.S_ISLNK(mode):
            t = IAType.LNK
        elif _stat.S_ISREG(mode):
            t = IAType.REG
        elif _stat.S_ISBLK(mode):
            t = IAType.BLK
        elif _stat.S_ISCHR(mode):
            t = IAType.CHR
        elif _stat.S_ISFIFO(mode):
            t = IAType.FIFO
        elif _stat.S_ISSOCK(mode):
            t = IAType.SOCK
        else:
            t = IAType.INVAL
        return cls(
            gfid=gfid, ia_type=t, mode=_stat.S_IMODE(mode),
            nlink=st.st_nlink, uid=st.st_uid, gid=st.st_gid,
            size=st.st_size, blocks=st.st_blocks,
            atime=st.st_atime, mtime=st.st_mtime, ctime=st.st_ctime)

    def touch(self, *, m: bool = False, c: bool = True, a: bool = False):
        now = time.time()
        if a:
            self.atime = now
        if m:
            self.mtime = now
        if c:
            self.ctime = now

    def is_dir(self) -> bool:
        return self.ia_type is IAType.DIR

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["gfid"] = self.gfid.hex()
        d["ia_type"] = self.ia_type.name
        return d
