"""Trace-context propagation: per-request spans across the whole stack.

The reference threads a ``frame->root`` through every STACK_WIND so a
statedump can show which xlator a call is parked in (stack.h:283,
call-stub.c pending frames) — but it never crosses the wire: a slow
client readv cannot say whether the time went to the client graph, the
transport, the brick graph or the disk.  Here every OUTERMOST fop call
on a graph mints a 16-hex-char trace id; each timed layer method
(``core.layer._timed``) records a span ``(trace, depth, layer, op,
start, duration, err)`` into a bounded per-process ring; and
protocol/client ships the id as a trailing wire-frame field that
protocol/server re-arms before dispatching into the brick graph — so
the brick's spans carry the CLIENT's trace id and the two statedumps
join into one tree.  One trace per compound chain: the chain's
outermost ``compound`` call is the root and every link is a child span.

The carrier is a :mod:`contextvars` ContextVar (the asyncio-idiomatic
``frame->root``): awaits and ``asyncio.gather`` fan-outs inherit it,
tasks copy it, and nothing in the fop signatures changes.  The io-stats
layer owns the operator knobs (``diagnostics.slow-fop-threshold``,
``diagnostics.span-ring-size``, and the master ``ENABLED`` gate rides
metrics-off bench runs / ``GFTPU_NO_OBSERVABILITY``).

A root span exceeding ``SLOW_FOP_THRESHOLD`` logs the full span tree —
a slow wire readv finally says WHERE the time went.
"""

from __future__ import annotations

import collections
import contextvars
import os
import time

from . import gflog
from .metrics import REGISTRY

log = gflog.get_logger("core.trace")

#: process darkening (GFTPU_NO_OBSERVABILITY / bench metrics-off):
#: while True, observability stays off no matter what volume options
#: say — io-stats' latency-measurement default must not re-arm the
#: histograms on a deliberately darkened process (the bench's off
#: pass mounts volumes whose io-stats init would otherwise undo it)
DARK = os.environ.get("GFTPU_NO_OBSERVABILITY", "") == "1"

#: master gate: False skips ALL span work in the fop hot path (set by
#: bench metrics-off passes and the GFTPU_NO_OBSERVABILITY env, which
#: brick subprocesses inherit so a whole served volume can run dark)
ENABLED = not DARK

#: root spans slower than this (seconds) log their full tree; 0 = off
#: (diagnostics.slow-fop-threshold)
SLOW_FOP_THRESHOLD = 0.0

_RING_DEFAULT = 4096

#: the bounded per-process span ring (circ-buff.c event-history analog);
#: span = (trace_id, depth, layer, op, start_ts, duration_s, err)
SPANS: collections.deque = collections.deque(maxlen=_RING_DEFAULT)

#: (trace_id, depth) of the span currently open in this context
CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "gftpu_trace", default=None)

#: per-(layer, op) slow-fop counts — the {layer,op} labels say WHICH
#: door and verb keeps blowing the threshold, not just that one did
SLOW_FOP_COUNTS: dict[tuple[str, str], int] = {}

REGISTRY.register(
    "gftpu_slow_fops_total", "counter",
    "root fops that exceeded diagnostics.slow-fop-threshold, "
    "by layer and op",
    lambda: [({"layer": l, "op": o}, v)
             for (l, o), v in sorted(SLOW_FOP_COUNTS.items())])


def set_ring_size(n: int) -> None:
    """Rebound the span ring (diagnostics.span-ring-size), keeping the
    newest entries."""
    global SPANS
    n = max(64, int(n))
    if SPANS.maxlen != n:
        SPANS = collections.deque(list(SPANS)[-n:], maxlen=n)


def new_trace_id() -> str:
    return os.urandom(8).hex()


def current_id() -> str | None:
    cur = CURRENT.get()
    return cur[0] if cur is not None else None


def arm(trace_id: str) -> None:
    """Adopt a wire-carried trace id for the rest of this context (the
    protocol/server re-arm: brick-graph spans join the client's trace
    instead of minting their own)."""
    CURRENT.set((str(trace_id), 0))


def enter(layer_name: str, op: str):
    """Open a span: mint a trace at the outermost call, else nest.
    Returns the token tuple ``exit_span`` needs."""
    cur = CURRENT.get()
    if cur is None:
        tid, depth, root = new_trace_id(), 0, True
    else:
        tid, depth, root = cur[0], cur[1] + 1, False
    tok = CURRENT.set((tid, depth))
    return (tid, depth, root, tok, layer_name, op, time.time())


def exit_span(span, duration: float, err: bool) -> None:
    tid, depth, root, tok, layer_name, op, start = span
    try:
        CURRENT.reset(tok)
    except ValueError:
        pass  # context migrated (sync facade thread hop): root-only
    SPANS.append((tid, depth, layer_name, op, start, duration, err))
    if not root:
        return
    if SLOW_FOP_THRESHOLD and duration >= SLOW_FOP_THRESHOLD:
        key = (layer_name, op)
        SLOW_FOP_COUNTS[key] = SLOW_FOP_COUNTS.get(key, 0) + 1
        tree = render_tree(tid)
        log.warning(7, "slow fop: %s.%s took %.1fms (threshold %.1fms) "
                    "trace %s\n%s", layer_name, op, duration * 1e3,
                    SLOW_FOP_THRESHOLD * 1e3, tid, tree)
        _flight().record("slow_fop", trace=tid, layer=layer_name, op=op,
                         ms=round(duration * 1e3, 3), tree=tree)
    elif err:
        # an error ROOT fop is flight-notable even when fast: its span
        # tree names which layer failed (the bundle's "what broke")
        _flight().record("error_fop", trace=tid, layer=layer_name,
                         op=op, ms=round(duration * 1e3, 3),
                         tree=render_tree(tid))


def _flight():
    """Late import: flight imports tracing at module top (for the span
    ring in its snapshot) — this side of the cycle resolves lazily."""
    from . import flight
    return flight


def spans_for(trace_id: str) -> list[tuple]:
    return [s for s in list(SPANS) if s[0] == trace_id]


def recent_spans(limit: int = 200) -> list[dict]:
    """Newest spans as dicts (statedump's trace_spans section)."""
    out = []
    for tid, depth, layer_name, op, start, dur, err in \
            list(SPANS)[-limit:]:
        out.append({"trace": tid, "depth": depth, "layer": layer_name,
                    "op": op, "start": round(start, 6),
                    "ms": round(dur * 1e3, 3), "err": err})
    return out


def render_tree(trace_id: str) -> str:
    """The trace's spans as an indented tree (slow-fop log format:
    one line per span, two spaces per depth, duration in ms)."""
    spans = sorted(spans_for(trace_id), key=lambda s: (s[4], s[1]))
    lines = []
    for _tid, depth, layer_name, op, _start, dur, err in spans:
        mark = " !!" if err else ""
        lines.append(f"{'  ' * depth}{layer_name}.{op} "
                     f"{dur * 1e3:.2f}ms{mark}")
    return "\n".join(lines)


__all__ = ["ENABLED", "SLOW_FOP_THRESHOLD", "SLOW_FOP_COUNTS", "SPANS",
           "CURRENT", "arm",
           "enter", "exit_span", "current_id", "new_trace_id",
           "recent_spans", "render_tree", "set_ring_size", "spans_for"]
