"""Layer engine: the translator (xlator) stack, TPU-build style.

The reference's xlator model (reference libglusterfs/src/xlator.c,
glusterfs/xlator.h:545,749) is a dlopen plugin tree where every fop is
propagated by continuation-passing ``STACK_WIND``/``STACK_UNWIND`` macros
(stack.h:283,346).  Here the same graph-of-layers architecture is expressed
idiomatically: a :class:`Layer` is a Python class registered by type name
("cluster/disperse", "storage/posix", ...); each fop is an async method;
winding is ``await child.fop(...)``; unwinding is the return value or a
raised :class:`FopError`.  The 59-fop default-passthrough boilerplate the
reference generates with generator.py:745 is installed by
``__init_subclass__``.

Lifecycle mirrors xlator_init/reconfigure/notify/fini (xlator.h:852-919):
graphs init bottom-up, events (CHILD_UP/DOWN...) propagate up by default.
Per-fop call/latency counters (xlator_t.stats, xlator.h:812-818) are kept
on every layer and exposed via statedump.
"""

from __future__ import annotations

import dataclasses
import enum
import errno as _errno
import time
from typing import Any, Callable, ClassVar

from .fops import Fop, FopError
from .iatt import Iatt
from .metrics import REGISTRY, LogHistogram
from .options import Option, validate_options
from . import gflog, tracing

log = gflog.get_logger("core")

# Per-fop latency histograms on every layer (io-stats
# `latency-measurement`, applied process-wide by IoStatsLayer
# init/reconfigure; GFTPU_NO_OBSERVABILITY pre-darkens subprocesses
# for the bench's metrics-off pair).  The count/avg/max accounting is
# NOT gated — it predates the histograms and `volume profile` always
# carried it.
import os as _os  # noqa: E402

HISTOGRAMS_ENABLED = _os.environ.get("GFTPU_NO_OBSERVABILITY", "") != "1"


class Event(enum.Enum):
    """Graph notifications (reference glusterfs.h GF_EVENT_*)."""

    PARENT_UP = "parent-up"
    PARENT_DOWN = "parent-down"
    CHILD_UP = "child-up"
    CHILD_DOWN = "child-down"
    CHILD_CONNECTING = "child-connecting"
    SOME_DESCENDENT_DOWN = "some-descendent-down"
    SOME_DESCENDENT_UP = "some-descendent-up"
    UPCALL = "upcall"
    TRANSLATOR_INFO = "translator-info"
    VOLFILE_MODIFIED = "volfile-modified"


@dataclasses.dataclass
class Loc:
    """A file location (reference loc_t): path plus resolved identity."""

    path: str
    gfid: bytes | None = None
    parent: bytes | None = None
    name: str | None = None

    def __post_init__(self):
        if self.name is None and self.path:
            self.name = self.path.rstrip("/").rsplit("/", 1)[-1] or "/"


class FdObj:
    """An open file handle flowing down the stack (reference fd_t): carries
    the inode identity plus per-layer private context slots."""

    __slots__ = ("gfid", "flags", "pid", "path", "anonymous", "_ctx")

    def __init__(self, gfid: bytes, flags: int = 0, pid: int = 0,
                 path: str = "", anonymous: bool = False):
        self.gfid = gfid
        self.flags = flags
        self.pid = pid
        self.path = path
        self.anonymous = anonymous
        self._ctx: dict[int, Any] = {}

    # per-layer ctx (reference fd_ctx_set/get keyed by xlator)
    def ctx_set(self, layer: "Layer", value: Any) -> None:
        self._ctx[id(layer)] = value

    def ctx_get(self, layer: "Layer", default: Any = None) -> Any:
        return self._ctx.get(id(layer), default)

    def ctx_del(self, layer: "Layer") -> Any:
        return self._ctx.pop(id(layer), None)


class _FopStats:
    __slots__ = ("count", "errors", "latency_sum", "latency_max", "hist")

    def __init__(self):
        self.count = 0
        self.errors = 0
        self.latency_sum = 0.0
        self.latency_max = 0.0
        # preallocated log2 buckets: the record path is two int ops and
        # a list increment — nothing allocates per fop
        self.hist = LogHistogram()

    def to_dict(self) -> dict:
        out = {
            "count": self.count, "errors": self.errors,
            "latency_avg": self.latency_sum / self.count if self.count else 0.0,
            "latency_max": self.latency_max,
        }
        if self.hist.total:
            # percentiles are DERIVED on read (profile/statedump/.meta
            # are cold paths); conservative bucket upper bounds
            out["latency_p50"] = self.hist.percentile(50)
            out["latency_p90"] = self.hist.percentile(90)
            out["latency_p99"] = self.hist.percentile(99)
        return out


def _timed(op_name: str, fn: Callable) -> Callable:
    """Wrap a fop coroutine with per-layer count/latency accounting."""

    async def wrapper(self, *args, **kwargs):
        st = self.stats.setdefault(op_name, _FopStats())
        # span bracket: the outermost timed call on a graph mints the
        # trace id, nested layers join it (core/tracing.py); one gate
        # check keeps the dark path at a single global read
        span = tracing.enter(self.name, op_name) if tracing.ENABLED \
            else None
        err = False
        t0 = time.perf_counter()
        try:
            return await fn(self, *args, **kwargs)
        except FopError:
            st.errors += 1
            err = True
            raise
        finally:
            dt = time.perf_counter() - t0
            st.count += 1
            st.latency_sum += dt
            if dt > st.latency_max:
                st.latency_max = dt
            if HISTOGRAMS_ENABLED:
                st.hist.record(dt)
            if span is not None:
                tracing.exit_span(span, dt, err)

    wrapper.__name__ = fn.__name__
    wrapper.__qualname__ = fn.__qualname__
    wrapper.__doc__ = fn.__doc__
    # keep the real signature reachable: inspect.signature follows
    # __wrapped__, and virtfs.extract_xdata needs the true parameter
    # list to find a caller's xdata (else identity-gated layers above
    # a timed fop silently skip their checks)
    wrapper.__wrapped__ = fn  # type: ignore[attr-defined]
    wrapper._gf_timed = True  # type: ignore[attr-defined]
    return wrapper


def _make_default(op_name: str) -> Callable:
    """Default fop: wind to the first child (reference defaults-tmpl.c)."""

    async def default(self, *args, **kwargs):
        if not self.children:
            raise FopError(_errno.EOPNOTSUPP,
                           f"{self.name}: no child to wind {op_name}")
        return await getattr(self.children[0], op_name)(*args, **kwargs)

    default.__name__ = op_name
    default.__doc__ = f"Default {op_name}: pass through to first child."
    # rpc/compound.py keys chain transparency off this mark: a layer
    # serving a fop with the generated default adds no behavior to it
    default._gf_default = True  # type: ignore[attr-defined]
    return default


def walk(root: "Layer"):
    """Yield ``root`` and every descendant exactly once (cycle-safe DFS)
    — the graph-traversal primitive behind hook injection (io-threads
    executor, upcall sink) and per-client cleanup."""
    stack, seen = [root], set()
    while stack:
        layer = stack.pop()
        if id(layer) in seen:
            continue
        seen.add(id(layer))
        yield layer
        stack.extend(layer.children)


# Live-layer fop accounting families (ISSUE 20): the per-layer
# count/error counters _timed already maintains, aggregated by
# (layer-name, op) across live instances — the SLO engine's error-ratio
# source (errors/total over a history window).  Aggregation collapses
# same-named layers from sibling graphs in one process (a test mounting
# three "c0" clients) into one monotonic series instead of three
# colliding label sets.
import weakref as _weakref  # noqa: E402 - after the class machinery above

_LIVE_LAYERS: "_weakref.WeakSet" = _weakref.WeakSet()


def _fop_samples(attr: str) -> list:
    agg: dict[tuple[str, str], int] = {}
    for layer in list(_LIVE_LAYERS):
        for op, st in list(layer.stats.items()):
            v = getattr(st, attr)
            if v:
                key = (layer.name, op)
                agg[key] = agg.get(key, 0) + v
    return [({"layer": ln, "op": op}, v)
            for (ln, op), v in sorted(agg.items())]


REGISTRY.register(
    "gftpu_fops_total", "counter",
    "fop dispatches per live layer instance (aggregated by name)",
    lambda: _fop_samples("count"))
REGISTRY.register(
    "gftpu_fop_errors_total", "counter",
    "fop failures (FopError) per live layer instance",
    lambda: _fop_samples("errors"))


# Registry of layer types: "cluster/disperse" -> class (the dlopen analog,
# reference xlator_dynload xlator.c:369).
_REGISTRY: dict[str, type["Layer"]] = {}


def register(type_name: str):
    def deco(cls):
        cls.type_name = type_name
        _REGISTRY[type_name] = cls
        return cls
    return deco


# type-name -> module path overrides (where the module name differs from
# the type suffix); everything else resolves by convention.
_TYPE_MODULES = {
    "cluster/disperse": "glusterfs_tpu.cluster.ec",
    "cluster/replicate": "glusterfs_tpu.cluster.afr",
    "cluster/distribute": "glusterfs_tpu.cluster.dht",
    "meta": "glusterfs_tpu.meta.meta",
}


def lookup_type(type_name: str) -> type["Layer"]:
    """Resolve a type name, importing its module on demand (the dlopen
    analog: the reference resolves 'cluster/disperse' to ec.so and dlsym's
    xlator_api; we import glusterfs_tpu.<category>.<name> and expect a
    @register decoration at module scope)."""
    if type_name not in _REGISTRY:
        import importlib

        mod = _TYPE_MODULES.get(type_name)
        if mod is None and "/" in type_name:
            category, _, leaf = type_name.partition("/")
            mod = f"glusterfs_tpu.{category}.{leaf.replace('-', '_')}"
        if mod is not None:
            try:
                importlib.import_module(mod)
            except ImportError:
                pass
    try:
        return _REGISTRY[type_name]
    except KeyError:
        raise ValueError(f"unknown layer type {type_name!r} "
                         f"(known: {sorted(_REGISTRY)})") from None


class Layer:
    """Base translator layer."""

    type_name: ClassVar[str] = "abstract"
    OPTIONS: ClassVar[tuple[Option, ...]] = ()

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        for fop in Fop:
            meth = getattr(cls, fop.value, None)
            if meth is None:
                setattr(cls, fop.value, _timed(fop.value,
                                               _make_default(fop.value)))
            elif not getattr(meth, "_gf_timed", False) and \
                    fop.value in cls.__dict__:
                setattr(cls, fop.value, _timed(fop.value, meth))

    def __init__(self, name: str, options: dict | None = None,
                 children: list["Layer"] | None = None, ctx: Any = None):
        self.name = name
        self.children: list[Layer] = children or []
        self.parents: list[Layer] = []
        for c in self.children:
            c.parents.append(self)
        self.ctx = ctx
        self.opts = validate_options(self.OPTIONS, options or {})
        self.stats: dict[str, _FopStats] = {}
        self.initialized = False
        _LIVE_LAYERS.add(self)

    # -- lifecycle ---------------------------------------------------------

    async def init(self) -> None:
        """Called bottom-up after construction (xlator init)."""
        self.initialized = True

    async def fini(self) -> None:
        """Called top-down at teardown (xlator fini)."""
        self.initialized = False

    def reconfigure(self, options: dict) -> None:
        """Apply new option values at runtime (xlator reconfigure)."""
        self.opts.update(validate_options(self.OPTIONS, options))

    def notify(self, event: Event, source: "Layer | None" = None,
               data: Any = None) -> None:
        """Default: propagate up to all parents (reference default_notify)."""
        for p in self.parents:
            p.notify(event, self, data)

    async def release(self, fd: "FdObj") -> None:
        """Close a file handle (not a wire fop in the reference either —
        fd_destroy cascades through the graph); default: pass down."""
        if self.children:
            rel = getattr(self.children[0], "release", None)
            if rel is not None:
                await rel(fd)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Compound fop (rpc/compound.py): forward the chain intact when
        this layer adds no behavior to any fop it contains, otherwise
        decompose — each link then runs through this layer's own fop
        methods, preserving its exact per-fop semantics.  Returns the
        per-link reply vector (never raises for link failures)."""
        from ..rpc import compound as _compound

        if self.children and _compound.transparent_for(type(self), links):
            return await self.children[0].compound(links, xdata)
        return await _compound.decompose(self, links, xdata)

    # -- introspection -----------------------------------------------------

    def dump_private(self) -> dict:
        """Layer-specific state for statedump (xlator dumpops priv)."""
        return {}

    def statedump(self) -> dict:
        return {
            "name": self.name,
            "type": self.type_name,
            "options": {k: (v.hex() if isinstance(v, bytes) else v)
                        for k, v in self.opts.items()},
            "stats": {op: st.to_dict() for op, st in self.stats.items()},
            "private": self.dump_private(),
            "subvolumes": [c.name for c in self.children],
        }


# Install timed defaults on the base class itself.
for _fop in Fop:
    if not hasattr(Layer, _fop.value):
        setattr(Layer, _fop.value, _timed(_fop.value, _make_default(_fop.value)))


__all__ = [
    "Layer", "Loc", "FdObj", "Event", "Fop", "FopError", "Iatt",
    "register", "lookup_type", "walk",
]
