"""Cluster event emission — the gf_event analog.

Reference: libglusterfs/src/events.c:27-31 (gf_event): any daemon fires
a fire-and-forget UDP datagram at the local glustereventsd, which fans
events out to registered webhooks (events/src/glustereventsd.py).

Here: JSON datagrams to the endpoint named by ``GFTPU_EVENTSD``
(host:port) or :func:`configure`; unset means events are disabled and
emission is a no-op.  Never raises, never blocks — losing an event must
not fail a fop.
"""

from __future__ import annotations

import json
import os
import socket
import time

_endpoint: tuple[str, int] | None = None
_sock: socket.socket | None = None

# emission accounting (absorbed by the unified registry the same way
# wire.blob_stats is): how many lifecycle events this process fired,
# dropped on a send error, or suppressed because no eventsd is
# configured — the event plane's own health must be observable too
emit_stats = {"sent": 0, "send_failed": 0, "unconfigured": 0}

from . import metrics as _metrics  # noqa: E402

_metrics.REGISTRY.register(
    "gftpu_events_emitted_total", "counter",
    "gf_event emissions by outcome (sent / send_failed / unconfigured)",
    lambda: _metrics.labeled(emit_stats))


def configure(endpoint: str | None) -> None:
    """'host:port' enables emission in this process; None disables."""
    global _endpoint, _sock
    if not endpoint:
        _endpoint = None
        return
    host, _, port = endpoint.rpartition(":")
    try:
        _endpoint = (host or "127.0.0.1", int(port))
    except ValueError:  # malformed endpoint disables, never raises
        _endpoint = None
        return
    if _sock is None:
        _sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        _sock.setblocking(False)


def _resolve() -> tuple[str, int] | None:
    if _endpoint is not None:
        return _endpoint
    env = os.environ.get("GFTPU_EVENTSD")
    if env:
        configure(env)
        return _endpoint
    return None


def gf_event(event: str, **fields) -> bool:
    """Emit one event; returns whether a datagram was sent.

    Every emission also lands in the process's flight-recorder ring,
    and a failure-class event (flight.FAILURE_EVENTS) auto-captures a
    local incident bundle — the black box records even when no eventsd
    is listening."""
    payload = {"event": event, "ts": time.time(), "pid": os.getpid()}
    payload.update(fields)
    try:
        from . import flight

        flight.note_event(event, payload)
    except Exception:  # noqa: BLE001 - the tap must not fail emission
        pass
    target = _resolve()
    if target is None:
        emit_stats["unconfigured"] += 1
        return False
    try:
        _sock.sendto(json.dumps(payload).encode(), target)
        emit_stats["sent"] += 1
        return True
    except OSError:
        emit_stats["send_failed"] += 1
        return False
