"""Cluster event emission — the gf_event analog.

Reference: libglusterfs/src/events.c:27-31 (gf_event): any daemon fires
a fire-and-forget UDP datagram at the local glustereventsd, which fans
events out to registered webhooks (events/src/glustereventsd.py).

Here: JSON datagrams to the endpoint named by ``GFTPU_EVENTSD``
(host:port) or :func:`configure`; unset means events are disabled and
emission is a no-op.  Never raises, never blocks — losing an event must
not fail a fop.
"""

from __future__ import annotations

import json
import os
import socket
import time

_endpoint: tuple[str, int] | None = None
_sock: socket.socket | None = None


def configure(endpoint: str | None) -> None:
    """'host:port' enables emission in this process; None disables."""
    global _endpoint, _sock
    if not endpoint:
        _endpoint = None
        return
    host, _, port = endpoint.rpartition(":")
    try:
        _endpoint = (host or "127.0.0.1", int(port))
    except ValueError:  # malformed endpoint disables, never raises
        _endpoint = None
        return
    if _sock is None:
        _sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        _sock.setblocking(False)


def _resolve() -> tuple[str, int] | None:
    if _endpoint is not None:
        return _endpoint
    env = os.environ.get("GFTPU_EVENTSD")
    if env:
        configure(env)
        return _endpoint
    return None


def gf_event(event: str, **fields) -> bool:
    """Emit one event; returns whether a datagram was sent."""
    target = _resolve()
    if target is None:
        return False
    payload = {"event": event, "ts": time.time(), "pid": os.getpid()}
    payload.update(fields)
    try:
        _sock.sendto(json.dumps(payload).encode(), target)
        return True
    except OSError:
        return False
