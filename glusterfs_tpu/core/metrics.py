"""Unified metrics registry — counters, gauges, log-bucket histograms.

The reference has no single metrics plane: io-stats keeps per-fop
count/avg/max, every xlator hand-rolls private counters, and the
``monitoring.c`` dump (glusterfsd/src/gf_proc_dump) walks them ad hoc.
This build had grown the same scatter — ``wire.blob_stats``,
``ec.read_fanout``, the gf256 program-LRU hit/miss, io-threads queue
depths, write-behind window bytes, codec probe state — each readable
only by whoever knew the module global.  This module is the one plane
they all report to:

* **Owned instruments**: :class:`Counter` / :class:`Gauge` created via
  the registry for new code.
* **Collectors**: a callback per family that reads EXISTING state at
  scrape time (the prometheus-client "custom collector" shape) — the
  scattered globals stay where their hot paths want them and cost
  nothing until someone looks.
* **Histograms**: :class:`LogHistogram`, fixed power-of-two log buckets
  (µs → minutes), zero-allocation record path; per-fop instances live
  in ``core.layer._FopStats`` and derive p50/p90/p99 on read.

Naming convention (docs/observability.md): ``gftpu_<area>_<name>``,
``_total`` suffix on counters, labels for sub-series (prometheus
conventions).  The registry renders the text exposition format
(``render()``) for the daemon's ``--metrics-port`` endpoint, the
``.meta/metrics`` file and ``gftpu volume metrics``, and a JSON-able
``snapshot()`` for the mgmt RPC path.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable

#: log2 bucket count: bucket i counts samples whose duration in µs has
#: bit_length i, i.e. [2^(i-1), 2^i) µs; bucket 0 is sub-µs and the
#: last bucket is open-ended (2^38 µs ≈ 4.6 min — far past any fop
#: deadline).  40 buckets * 8B = 320B per (layer, fop): cheap enough to
#: keep always-allocated, which is what makes the record path
#: allocation-free.
HIST_BUCKETS = 40


class LogHistogram:
    """Fixed power-of-two latency histogram (µs → minutes).

    ``record`` is the hot path: one int multiply, one ``bit_length``,
    one list increment — no allocation, no branching on configuration.
    Percentiles are derived on read by walking the cumulative counts
    and reporting the bucket's UPPER bound (conservative: the true
    quantile is never above the reported one by more than 2x)."""

    __slots__ = ("buckets", "total")

    def __init__(self):
        self.buckets = [0] * HIST_BUCKETS
        self.total = 0

    def record(self, seconds: float) -> None:
        idx = int(seconds * 1e6).bit_length()
        self.buckets[idx if idx < HIST_BUCKETS else HIST_BUCKETS - 1] += 1
        self.total += 1

    @staticmethod
    def bound(idx: int) -> float:
        """Upper bound of bucket ``idx`` in seconds."""
        return (1 << idx) * 1e-6

    def percentile(self, q: float) -> float:
        """q-th percentile (0-100) in seconds; 0.0 when empty."""
        if not self.total:
            return 0.0
        rank = q / 100.0 * self.total
        seen = 0
        for i, c in enumerate(self.buckets):
            seen += c
            if seen >= rank and c:
                return self.bound(i)
        return self.bound(HIST_BUCKETS - 1)

    def merge(self, other: "LogHistogram") -> None:
        for i, c in enumerate(other.buckets):
            self.buckets[i] += c
        self.total += other.total

    def to_dict(self) -> dict:
        return {"total": self.total,
                "p50": self.percentile(50),
                "p90": self.percentile(90),
                "p99": self.percentile(99)}


class Counter:
    """Monotonic counter (owned instrument)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Point-in-time value (owned instrument)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


def _fmt_labels(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricsRegistry:
    """Process-global family registry.

    A family is ``(type, help, collect)`` where ``collect()`` yields
    ``(labels_dict, value)`` samples at scrape time.  Registration is
    idempotent by name (module reloads in tests must not error)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, tuple[str, str, Callable[[], Iterable]]] \
            = {}

    # -- registration ------------------------------------------------------

    def register(self, name: str, mtype: str, help_text: str,
                 collect: Callable[[], Iterable]) -> None:
        with self._lock:
            self._families[name] = (mtype, help_text, collect)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def register_objects(self, name: str, mtype: str, help_text: str,
                         samples_of: Callable[[Any], Iterable],
                         live: Any = None):
        """Register a family scraped from a weakly-tracked set of live
        objects (the per-layer-instance pattern: ec read-fanout,
        io-threads queues, write-behind occupancy).  ``samples_of(obj)``
        yields that object's ``(labels, value)`` samples.  Returns the
        WeakSet — constructors add instances to it; pass an existing
        ``live`` set to hang several families off one population."""
        import weakref

        if live is None:
            live = weakref.WeakSet()
        self.register(name, mtype, help_text,
                      lambda: [s for obj in list(live)
                               for s in samples_of(obj)])
        return live

    def counter(self, name: str, help_text: str = "") -> Counter:
        c = Counter()
        self.register(name, "counter", help_text,
                      lambda: [({}, c.value)])
        return c

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        g = Gauge()
        self.register(name, "gauge", help_text, lambda: [({}, g.value)])
        return g

    # -- scraping ----------------------------------------------------------

    def collect(self) -> dict[str, dict]:
        """name -> {type, help, samples: [[labels, value], ...]} — a
        collector that raises loses only its own family (a dead layer's
        stale callback must not take the whole scrape down)."""
        _ensure_default_families()
        with self._lock:
            fams = dict(self._families)
        out: dict[str, dict] = {}
        for name, (mtype, help_text, fn) in sorted(fams.items()):
            try:
                samples = [[dict(labels), value]
                           for labels, value in fn()]
            except Exception:  # noqa: BLE001 - scrape isolation
                continue
            out[name] = {"type": mtype, "help": help_text,
                         "samples": samples}
        return out

    def snapshot(self) -> dict:
        """JSON/wire-able scrape (the mgmt RPC + .meta shape)."""
        return self.collect()

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        return render_families(self.collect())


def render_families(families: dict) -> str:
    """Text exposition of a ``collect()``-shaped family dict — shared
    by the registry's own ``render()`` and aggregators that merge
    OTHER processes' snapshots (the gateway worker-pool supervisor)."""
    lines: list[str] = []
    for name, fam in families.items():
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for labels, value in fam["samples"]:
            if isinstance(value, float) and value == int(value):
                value = int(value)
            lines.append(f"{name}{_fmt_labels(labels)} {value}")
    return "\n".join(lines) + "\n"


#: THE process-global registry (the prometheus default-registry shape);
#: modules register their families at import / instance-construction
#: time and every exposure surface reads this one object.
REGISTRY = MetricsRegistry()


# Families whose owning modules may not be loaded yet in a given role
# (a plain distribute brick never touches the codec): imported once on
# the FIRST scrape so every dump carries the full family set — the
# acceptance contract is "the families are present", not "present iff
# the right import happened first".  Scraping is a cold path; these
# imports are cheap (numpy is already resident in any serving process).
_DEFAULT_SOURCES = ("glusterfs_tpu.rpc.wire", "glusterfs_tpu.ops.gf256",
                    "glusterfs_tpu.ops.codec")
_ensured = False


def _ensure_default_families() -> None:
    global _ensured
    if _ensured:
        return
    _ensured = True
    import importlib

    for mod in _DEFAULT_SOURCES:
        try:
            importlib.import_module(mod)
        except Exception:  # noqa: BLE001 - a missing optional dep
            pass           # loses that family, never the scrape


def labeled(samples: dict, **fixed) -> list:
    """Helper: a flat ``{key: value}`` dict -> labeled samples, with
    ``fixed`` labels merged in (the one-line collector for the absorbed
    module-global counter dicts)."""
    return [({**fixed, "counter": k}, v) for k, v in samples.items()]


def register_build_info(role: str) -> None:
    """Register the standard ``gftpu_build_info{version,op_version,
    role}`` info-gauge (value always 1, the prometheus build-info
    idiom): every daemon role calls this at startup so merged bundles
    and history rings are attributable to the code + op-version that
    produced them.  Idempotent by registry contract (last registration
    wins — one role per process)."""
    from .. import OP_VERSION, __version__

    REGISTRY.register(
        "gftpu_build_info", "gauge",
        "build/version identity of this process (value is always 1)",
        lambda: [({"version": __version__,
                   "op_version": str(OP_VERSION),
                   "role": str(role)}, 1)])


def history_ring():
    """The per-process :class:`core.history.HistoryRing` (lazy import:
    metrics is imported by everything; history pulls in tracing/flight
    and must not become a base-layer import cost)."""
    from . import history

    return history.HISTORY


__all__ = ["REGISTRY", "MetricsRegistry", "Counter", "Gauge",
           "LogHistogram", "HIST_BUCKETS", "labeled",
           "register_build_info", "history_ring"]
