"""Per-process incident flight recorder — the always-on black box.

The reference keeps a per-xlator ``circ-buff.c`` event history that
only a manual statedump can read; by the time an operator asks, the
interesting window has usually scrolled away.  This module is the
process's flight recorder: a bounded ring of NOTABLE records (error
fops with their span trees, slow fops, lifecycle events, circuit/QoS/
shm transitions, worker respawns) that costs nothing while healthy,
plus :func:`snapshot` which packs the record ring, the span ring
(:mod:`core.tracing`), the full metrics registry and any registered
per-process sections (brick client accounting, gateway dump) into one
JSON-able bundle.

Capture is the other half: :func:`maybe_capture` writes that bundle
into ``diagnostics.incident-dir`` when a failure-class event fires
(:data:`FAILURE_EVENTS`, tapped from :func:`core.events.gf_event`),
rate-limited to one bundle per ``diagnostics.incident-min-interval``
seconds and pruned oldest-first so the directory never exceeds
``diagnostics.incident-max-bytes`` — a crash loop fills a quota, not a
disk.  Service daemons with no inbound RPC surface (shd, rebalanced)
arm :func:`arm_signal_capture` instead: SIGUSR2 writes a snapshot to a
well-known path, which glusterd's ``volume incident capture`` fan-out
collects (the statedump-SIGUSR1 precedent, daemon._dump_state).

Everything here honours the :mod:`core.tracing` DARK gate: a process
darkened by ``GFTPU_NO_OBSERVABILITY`` records nothing and captures
nothing.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable

from . import gflog, tracing
from .metrics import REGISTRY

log = gflog.get_logger("core.flight")

#: rides the same master gate as the span ring: a darkened process
#: (bench metrics-off) must not pay for — or leak state through — the
#: flight ring either
ENABLED = tracing.ENABLED

_RING_DEFAULT = 512

#: the bounded record ring; record = {"ts", "kind", ...fields}
RING: collections.deque = collections.deque(maxlen=_RING_DEFAULT)

#: gf_event names that auto-capture a local incident bundle (the
#: failure CLASS: connectivity loss, quorum loss, containment firing,
#: storage health, pool self-healing — not routine lifecycle)
FAILURE_EVENTS = frozenset((
    "BRICK_DISCONNECTED", "CLIENT_CIRCUIT_OPEN", "EC_MIN_BRICKS_NOT_UP",
    "AFR_QUORUM_FAIL", "POSIX_HEALTH_CHECK_FAILED", "SERVER_QUORUM_LOST",
    "GATEWAY_WORKER_RESPAWN", "ALERT_RAISED",
))

# -- capture configuration (diagnostics.* v18 keys / --incident-dir) ------
#: directory for auto-captured bundles; "" disables capture (recording
#: into the ring is always on — capture is the part that touches disk)
INCIDENT_DIR = ""
#: total bytes the incident dir may hold; oldest bundles pruned first
INCIDENT_MAX_BYTES = 64 * 1024 * 1024
#: min seconds between auto-captures (one incident, one bundle — not
#: one bundle per breaker flap during the same outage)
INCIDENT_MIN_INTERVAL = 60.0

#: what this process calls itself in bundles ("brick", "gateway-worker",
#: "shd", ...) — set once at daemon startup, purely descriptive
ROLE = ""

#: diagnostics.access-log: the gateway's structured per-request access
#: line (method, path, status, bytes, ms, trace).  Owned here because
#: io-stats pushes the diagnostics.* keys process-wide and the gateway
#: only reads the resulting flag — same shape as tracing.ENABLED
ACCESS_LOG = False

_lock = threading.Lock()
_record_counts: dict[str, int] = {}
_capture_counts = {"written": 0, "rate_limited": 0, "error": 0}
_pruned = 0
_last_capture = 0.0
_capturing = False  # reentrancy guard: a capture must not capture
_sections: dict[str, Callable[[], Any]] = {}

REGISTRY.register(
    "gftpu_flight_records_total", "counter",
    "flight-recorder ring appends by record kind",
    lambda: [({"kind": k}, v) for k, v in sorted(_record_counts.items())])
REGISTRY.register(
    "gftpu_incident_captures_total", "counter",
    "incident bundle auto-capture attempts by outcome",
    lambda: [({"outcome": k}, v)
             for k, v in sorted(_capture_counts.items())])
REGISTRY.register(
    "gftpu_incident_pruned_total", "counter",
    "incident bundles deleted by the size-bound pruner",
    lambda: [({}, _pruned)])


def set_ring_size(n: int) -> None:
    """Rebound the record ring, keeping the newest entries."""
    global RING
    n = max(16, int(n))
    if RING.maxlen != n:
        RING = collections.deque(list(RING)[-n:], maxlen=n)


def set_role(role: str) -> None:
    global ROLE
    ROLE = str(role)


def set_access_log(on: bool) -> None:
    global ACCESS_LOG
    ACCESS_LOG = bool(on) and ENABLED


def configure_capture(incident_dir: str | None = None,
                      max_bytes: int | None = None,
                      min_interval: float | None = None) -> None:
    """Arm/tune auto-capture (io-stats option push or daemon argv)."""
    global INCIDENT_DIR, INCIDENT_MAX_BYTES, INCIDENT_MIN_INTERVAL
    if incident_dir is not None:
        INCIDENT_DIR = str(incident_dir)
    if max_bytes is not None:
        INCIDENT_MAX_BYTES = max(0, int(max_bytes))
    if min_interval is not None:
        INCIDENT_MIN_INTERVAL = max(0.0, float(min_interval))


def record(kind: str, /, **fields) -> None:
    """Append one notable record to the ring (cheap, never raises).
    ``kind`` is positional-only so a field literally named "kind"
    (e.g. an alert's rule kind) cannot raise a TypeError; the ring's
    taxonomy key always wins the collision."""
    if not ENABLED:
        return
    try:
        rec = {"ts": round(time.time(), 6)}
        rec.update(fields)
        rec["kind"] = str(kind)
        RING.append(rec)
        _record_counts[kind] = _record_counts.get(kind, 0) + 1
    except Exception:  # noqa: BLE001 - the recorder must never hurt a fop
        pass


def note_event(event: str, payload: dict) -> None:
    """The gf_event tap: every emission lands in the ring; a
    failure-class event additionally triggers a local auto-capture."""
    if not ENABLED:
        return
    record("event", event=event,
           **{("event_kind" if k == "kind" else k): v
              for k, v in payload.items()
              if k not in ("event", "ts", "pid")})
    if event in FAILURE_EVENTS:
        maybe_capture(event)


def add_section(name: str, fn: Callable[[], Any]) -> None:
    """Register a per-process extra for :func:`snapshot` (the brick
    registers its per-client accounting, the gateway its dump)."""
    _sections[str(name)] = fn


def snapshot(spans: int = 500, records: int = 0,
             metrics: bool = True) -> dict:
    """The bundle: record ring + span ring + metrics registry + every
    registered section, one JSON-able dict.  ``metrics=False`` skips
    the registry scrape for carriers that already ship it beside the
    bundle (the gateway worker control channel)."""
    out: dict[str, Any] = {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "role": ROLE,
        "enabled": ENABLED,
        "records": list(RING)[-records:] if records else list(RING),
        "spans": tracing.recent_spans(spans),
    }
    if metrics:
        out["metrics"] = REGISTRY.snapshot()
    for name, fn in list(_sections.items()):
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 - scrape isolation
            out[name] = {"error": repr(e)[:200]}
    return out


def _jsonable_dumps(bundle: dict) -> str:
    return json.dumps(bundle, default=repr, separators=(",", ":"),
                      sort_keys=True)


def write_snapshot(path: str, reason: str = "") -> None:
    """Atomically write one bundle to ``path`` (tmp + rename)."""
    bundle = snapshot()
    if reason:
        bundle["reason"] = reason
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(_jsonable_dumps(bundle))
    os.replace(tmp, path)


def prune_dir(incident_dir: str, max_bytes: int) -> int:
    """Delete oldest bundles until the dir fits ``max_bytes``; returns
    how many were pruned (shared by capture and the chaos leak audit)."""
    global _pruned
    try:
        entries = []
        for name in os.listdir(incident_dir):
            if not name.startswith("incident-") \
                    or not name.endswith(".json"):
                continue
            p = os.path.join(incident_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
    except OSError:
        return 0
    entries.sort()  # oldest first
    total = sum(e[1] for e in entries)
    n = 0
    while entries and total > max_bytes:
        mtime, size, p = entries.pop(0)
        try:
            os.unlink(p)
        except OSError:
            continue
        total -= size
        n += 1
    if n:
        _pruned += n
        log.info(1, "pruned %d incident bundle(s) from %s "
                 "(size bound %d bytes)", n, incident_dir, max_bytes)
    return n


def maybe_capture(reason: str, force: bool = False) -> str | None:
    """Write an incident bundle if capture is armed and the rate limit
    allows; returns the bundle path or None.  ``force`` (the operator's
    explicit ``incident capture``) skips the rate limit, never the
    size bound."""
    global _last_capture, _capturing
    if not ENABLED or not INCIDENT_DIR:
        return None
    with _lock:
        if _capturing:
            return None
        now = time.monotonic()
        if not force and _last_capture \
                and now - _last_capture < INCIDENT_MIN_INTERVAL:
            _capture_counts["rate_limited"] += 1
            return None
        _last_capture = now
        _capturing = True
    try:
        os.makedirs(INCIDENT_DIR, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))[:48] or "manual"
        path = os.path.join(
            INCIDENT_DIR,
            f"incident-{time.time_ns()}-{os.getpid()}-{safe}.json")
        write_snapshot(path, reason=str(reason))
        _capture_counts["written"] += 1
        record("incident_captured", reason=str(reason), path=path)
        log.warning(2, "incident bundle captured: %s (%s)", path, reason)
        prune_dir(INCIDENT_DIR, INCIDENT_MAX_BYTES)
        return path
    except Exception as e:  # noqa: BLE001 - capture must never cascade
        _capture_counts["error"] += 1
        log.warning(3, "incident capture failed: %r", e)
        return None
    finally:
        _capturing = False


def arm_signal_capture(path: str, signum: int | None = None) -> None:
    """SIGUSR2 (default) writes a snapshot bundle to ``path`` — the
    capture door for daemons with no inbound RPC surface (shd,
    rebalanced); glusterd signals, polls for the file, and merges it."""
    import signal

    sig = signal.SIGUSR2 if signum is None else signum

    def _cap():
        try:
            write_snapshot(path, reason="signal")
        except Exception as e:  # noqa: BLE001 - a capture door only
            log.warning(4, "signal capture to %s failed: %r", path, e)

    try:
        import asyncio

        asyncio.get_running_loop().add_signal_handler(sig, _cap)
    except (RuntimeError, NotImplementedError):
        signal.signal(sig, lambda *_: _cap())


__all__ = ["ENABLED", "ACCESS_LOG", "RING", "FAILURE_EVENTS",
           "record", "note_event", "set_access_log",
           "add_section", "snapshot", "write_snapshot", "maybe_capture",
           "prune_dir", "configure_capture", "arm_signal_capture",
           "set_ring_size", "set_role"]
