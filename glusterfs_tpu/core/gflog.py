"""Structured logging with stable message IDs.

The reference logs through ``gf_msg(component, level, errno, msgid, fmt)``
with per-component message-ID catalogs (reference
libglusterfs/src/logging.c, glfs-message-id.h); message IDs are stable
across releases so operators can grep/alert on them.  Here: a thin wrapper
over :mod:`logging` that prefixes records with ``[MSGID: N]`` and keeps a
per-process in-memory ring of recent messages for statedump (the
event-history analog, reference circ-buff.c).
"""

from __future__ import annotations

import collections
import logging
import threading

_RING_SIZE = 1024
_ring: collections.deque[str] = collections.deque(maxlen=_RING_SIZE)
_ring_lock = threading.Lock()

# Message-ID bases per component (glfs-message-id.h segments a global space)
COMP_BASE = {
    "core": 100000,
    "ec": 110000,
    "afr": 120000,
    "dht": 130000,
    "posix": 140000,
    "protocol": 150000,
    "mgmt": 160000,
    "heal": 170000,
    "perf": 180000,
    "features": 190000,
}


def get_logger(component: str) -> "GfLogger":
    return GfLogger(component)


class GfLogger:
    def __init__(self, component: str):
        self._log = logging.getLogger(f"glusterfs_tpu.{component}")
        self._base = COMP_BASE.get(component.split(".")[0], 0)

    def _emit(self, level: int, msgid: int, msg: str, *args,
              err: int | None = None):
        mid = self._base + msgid
        text = msg % args if args else msg
        if err is not None:
            text = f"{text} [errno={err}]"
        line = f"[MSGID: {mid}] {text}"
        self._log.log(level, line)
        with _ring_lock:
            _ring.append(f"{logging.getLevelName(level)} {self._log.name} {line}")

    def debug(self, msgid: int, msg: str, *args, **kw):
        self._emit(logging.DEBUG, msgid, msg, *args, **kw)

    def info(self, msgid: int, msg: str, *args, **kw):
        self._emit(logging.INFO, msgid, msg, *args, **kw)

    def warning(self, msgid: int, msg: str, *args, **kw):
        self._emit(logging.WARNING, msgid, msg, *args, **kw)

    def error(self, msgid: int, msg: str, *args, **kw):
        self._emit(logging.ERROR, msgid, msg, *args, **kw)

    def critical(self, msgid: int, msg: str, *args, **kw):
        self._emit(logging.CRITICAL, msgid, msg, *args, **kw)


def recent_messages(limit: int = 100) -> list[str]:
    """Most recent log lines (for statedump / tests)."""
    with _ring_lock:
        items = list(_ring)
    return items[-limit:]
