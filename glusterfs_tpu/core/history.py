"""Per-process metrics HISTORY — the time dimension of the registry.

PRs 4/5/19 made every subsystem's state *scrapeable*; this module makes
it *replayable*: a background sampler captures the unified registry at
a fixed cadence into a bounded ring of **delta-compressed** samples
(only the keys whose value changed since the previous tick are stored,
with a full baseline at ring start), so "what did p99 / the error rate
look like over the last ten minutes?" is answerable from any daemon —
no Prometheus server required.  The reference's closest analog is
``volume profile`` interval mode, which keeps exactly ONE interval of
state and loses it on read.

Three consumers:

* ``/metrics/history.json`` on every daemon metrics endpoint (and the
  gateway supervisor, which merges per-worker rings via
  :func:`merge_series` the same way it merges snapshots);
* the SLO engine (:mod:`core.slo`), whose rules evaluate windowed
  rates/ratios against the local ring on every sampler tick;
* incident bundles — :func:`arm` registers the ring's tail as a flight
  section, so a captured bundle shows the minutes *before* the
  failure, not just the instant of it.

Armed like the flight recorder: :func:`arm` at daemon startup, the
``GFTPU_NO_OBSERVABILITY`` master gate darkens it entirely, and the
``diagnostics.history-{interval,retention}`` keys (op-version 19,
pushed process-wide by debug/io-stats) tune it live.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from typing import Callable, Iterable

from . import gflog, tracing
from .metrics import REGISTRY, LogHistogram, _fmt_labels

log = gflog.get_logger("core.history")

#: rides the tracing master gate: a darkened process (bench
#: metrics-off) must not pay a sampler thread either
ENABLED = tracing.ENABLED

DEFAULT_INTERVAL = 10.0
DEFAULT_RETENTION = 600.0
#: hard sample-count bound regardless of retention/interval (a
#: misconfigured 0.1s interval with a day of retention must cost a
#: bounded ring, not the heap)
MAX_SAMPLES = 4096

_sample_counts = {"sampled": 0, "error": 0}

REGISTRY.register(
    "gftpu_history_samples_total", "counter",
    "history-ring sampler ticks by outcome",
    lambda: [({"outcome": k}, v) for k, v in sorted(_sample_counts.items())])


def flatten(snapshot: dict) -> tuple[dict[str, float], dict[str, str]]:
    """A ``REGISTRY.snapshot()`` -> (``key -> value``, ``key -> type``)
    with prometheus-shaped keys (``family{a="b"}``) — the ring's
    storage unit.  Non-numeric samples (repr'd state strings) are
    dropped: history is for values that can ramp."""
    flat: dict[str, float] = {}
    types: dict[str, str] = {}
    for name, fam in snapshot.items():
        mtype = fam.get("type", "gauge")
        for labels, value in fam.get("samples", ()):
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            key = name + _fmt_labels(labels)
            flat[key] = value
            types[key] = mtype
    return flat, types


_KEY_RE = re.compile(r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)'
                     r'(?:\{(?P<labels>.*)\})?$')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def key_family(key: str) -> str:
    m = _KEY_RE.match(key)
    return m.group("name") if m else key


def key_labels(key: str) -> dict[str, str]:
    m = _KEY_RE.match(key)
    if not m or not m.group("labels"):
        return {}
    return dict(_LABEL_RE.findall(m.group("labels")))


class HistoryRing:
    """Bounded ring of delta-compressed registry samples.

    Each entry is ``(ts, {key: value})`` holding only the keys that
    changed since the previous entry (the first entry after a reset is
    a full baseline); reconstruction walks forward carrying values.
    Thread-safe: the sampler thread appends while scrape/SLO paths
    read."""

    def __init__(self, interval: float = DEFAULT_INTERVAL,
                 retention: float = DEFAULT_RETENTION):
        self.interval = float(interval)
        self.retention = float(retention)
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque()
        self._last: dict[str, float] = {}
        self._types: dict[str, str] = {}

    def configure(self, interval: float | None = None,
                  retention: float | None = None) -> None:
        with self._lock:
            if interval is not None:
                self.interval = max(0.05, float(interval))
            if retention is not None:
                self.retention = max(1.0, float(retention))
            self._trim_locked(time.time())

    def _trim_locked(self, now: float) -> None:
        # retention + hard count bound; trimming the baseline away is
        # fine — the next-oldest delta simply becomes authoritative
        # only for the keys it carries, and reconstruction tolerates
        # keys appearing mid-ring (a late-registered family does the
        # same thing)
        while self._samples and (
                now - self._samples[0][0] > self.retention
                or len(self._samples) > MAX_SAMPLES):
            self._samples.popleft()

    def sample(self, snapshot: dict | None = None,
               now: float | None = None) -> None:
        """Capture one delta sample (the sampler tick; tests feed
        synthetic snapshots directly)."""
        try:
            if snapshot is None:
                snapshot = REGISTRY.snapshot()
            flat, types = flatten(snapshot)
        except Exception:  # noqa: BLE001 - a scrape must not kill the thread
            _sample_counts["error"] += 1
            return
        now = time.time() if now is None else float(now)
        with self._lock:
            delta = {k: v for k, v in flat.items()
                     if self._last.get(k) != v}
            self._samples.append((now, delta))
            self._last = flat
            self._types.update(types)
            self._trim_locked(now)
        _sample_counts["sampled"] += 1

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._last = {}
            self._types = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    # -- reconstruction ----------------------------------------------------

    def series(self, prefix: str = "", window: float | None = None,
               now: float | None = None) -> dict[str, list]:
        """``key -> [[ts, value], ...]`` reconstructed with carry-
        forward (an unchanged value still gets a point per tick — the
        consumer sees a dense series, the ring stores one delta)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            samples = list(self._samples)
        out: dict[str, list] = {}
        current: dict[str, float] = {}
        for ts, delta in samples:
            current.update(delta)
            if window is not None and now - ts > window:
                continue
            for k, v in current.items():
                if prefix and not k.startswith(prefix):
                    continue
                out.setdefault(k, []).append([ts, v])
        return out

    def dump(self, window: float | None = None, prefix: str = "",
             max_samples: int | None = None) -> dict:
        """The JSON-able ring view ``/metrics/history.json`` serves and
        incident bundles embed (``max_samples`` bounds the tail for
        bundle embedding)."""
        with self._lock:
            n = len(self._samples)
            first = self._samples[0][0] if n else 0.0
            last = self._samples[-1][0] if n else 0.0
            types = dict(self._types)
        if max_samples is not None and n:
            window = min(window if window is not None else float("inf"),
                         time.time() - last
                         + self.interval * max_samples)
        series = self.series(prefix=prefix, window=window)
        rates = {}
        for k, pts in series.items():
            if types.get(k) == "counter" and len(pts) >= 2:
                rates[k] = round(rate(pts), 6)
        return {"interval": self.interval, "retention": self.retention,
                "samples": n, "first_ts": first, "last_ts": last,
                "series": series, "rates": rates}


# -- series math (shared by the SLO engine and the trajectory surface) ----

def increase(points: Iterable, t0: float | None = None,
             t1: float | None = None) -> float:
    """Counter increase over ``[t0, t1]``: the sum of positive deltas
    between consecutive points — a value DROP is a counter reset
    (daemon respawn), after which the post-reset absolute value counts
    as new increase (the prometheus ``increase()`` contract)."""
    total = 0.0
    prev = None
    for ts, v in points:
        if t0 is not None and ts < t0:
            prev = v
            continue
        if t1 is not None and ts > t1:
            break
        if prev is None:
            prev = v
            continue
        total += (v - prev) if v >= prev else v
        prev = v
    return total


def rate(points: list, window: float | None = None) -> float:
    """Per-second increase over the last ``window`` seconds (or the
    whole series); 0.0 when fewer than two points span the window."""
    if not points:
        return 0.0
    t1 = points[-1][0]
    t0 = t1 - window if window is not None else points[0][0]
    span = [p for p in points if p[0] >= t0]
    if len(span) < 2:
        return 0.0
    dt = span[-1][0] - span[0][0]
    if dt <= 0:
        return 0.0
    return increase(span) / dt


def percentile_trajectory(bucket_series: dict[int, list], q: float,
                          window: float) -> list:
    """``[[ts, seconds], ...]`` — the q-th percentile derived per tick
    from windowed increments of log-histogram *bucket counters*
    (bucket index -> cumulative-count series, the
    :class:`core.metrics.LogHistogram` bucket convention).  Points with
    an empty window (sampler gap, no traffic) report 0.0 — a gap is
    visible as a flat zero, never interpolated away."""
    grid = sorted({ts for pts in bucket_series.values()
                   for ts, _ in pts})
    out = []
    for ts in grid:
        counts: list[tuple[int, float]] = []
        for idx, pts in sorted(bucket_series.items()):
            inc = increase(pts, ts - window, ts)
            if inc > 0:
                counts.append((idx, inc))
        total = sum(c for _, c in counts)
        if not total:
            out.append([ts, 0.0])
            continue
        need = q / 100.0 * total
        seen = 0.0
        val = LogHistogram.bound(counts[-1][0])
        for idx, c in counts:
            seen += c
            if seen >= need:
                val = LogHistogram.bound(idx)
                break
        out.append([ts, val])
    return out


def merge_series(dumps: list[dict]) -> dict:
    """Merge several per-process ring dumps (the gateway supervisor's
    per-worker aggregation, same semantics as its snapshot merge):
    counters and plain gauges SUM across workers, quantile-labeled
    gauges take the MAX (summing a p99 is meaningless).  The merged
    grid is the union of every worker's tick timestamps; a worker
    contributes its carried-forward value once it has one."""
    grid = sorted({ts for d in dumps
                   for pts in d.get("series", {}).values()
                   for ts, _ in pts})[-MAX_SAMPLES:]
    keys = sorted({k for d in dumps for k in d.get("series", {})})
    merged: dict[str, list] = {}
    for k in keys:
        use_max = 'quantile="' in k
        per_worker = [d.get("series", {}).get(k, []) for d in dumps]
        pts_out = []
        cursors = [0] * len(per_worker)
        carried: list[float | None] = [None] * len(per_worker)
        for ts in grid:
            for i, pts in enumerate(per_worker):
                while cursors[i] < len(pts) and pts[cursors[i]][0] <= ts:
                    carried[i] = pts[cursors[i]][1]
                    cursors[i] += 1
            vals = [c for c in carried if c is not None]
            if not vals:
                continue
            pts_out.append([ts, max(vals) if use_max else sum(vals)])
        if pts_out:
            merged[k] = pts_out
    return {"series": merged, "samples": len(grid),
            "first_ts": grid[0] if grid else 0.0,
            "last_ts": grid[-1] if grid else 0.0,
            "workers": len(dumps)}


# -- the background sampler (one thread per process, armed at startup) ----

#: THE process ring — every consumer (endpoint, SLO engine, incident
#: section) reads this one object
HISTORY = HistoryRing()

_tick_hooks: list[Callable[[], None]] = []
_thread: threading.Thread | None = None
_wake = threading.Event()
_stop = False
_lock = threading.Lock()


def add_tick_hook(fn: Callable[[], None]) -> None:
    """Run ``fn`` after every sampler tick (the SLO engine's eval
    cadence — one scrape feeds both the ring and the rules)."""
    if fn not in _tick_hooks:
        _tick_hooks.append(fn)


def _sampler_loop() -> None:
    while True:
        _wake.wait(HISTORY.interval)
        _wake.clear()
        if _stop:
            return
        HISTORY.sample()
        for fn in list(_tick_hooks):
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - hook isolation
                log.warning(1, "history tick hook failed: %r", e)


def arm() -> bool:
    """Start the background sampler (idempotent; no-op when darkened).
    Also registers the ring tail as an incident-bundle section — a
    captured bundle carries the minutes before the failure."""
    global _thread, _stop
    if not ENABLED:
        return False
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        from . import flight

        flight.add_section(
            "history",
            lambda: HISTORY.dump(max_samples=60))
        _stop = False
        _wake.clear()
        _thread = threading.Thread(target=_sampler_loop,
                                   name="gftpu-history-sampler",
                                   daemon=True)
        _thread.start()
    return True


def disarm() -> None:
    """Stop the sampler (tests; daemons just exit — the thread is a
    daemon thread)."""
    global _thread, _stop
    with _lock:
        if _thread is None:
            return
        _stop = True
        _wake.set()
        t = _thread
        _thread = None
    t.join(timeout=2.0)


def configure(interval: float | None = None,
              retention: float | None = None) -> None:
    """The diagnostics.history-* option push (io-stats, both graph
    ends) and the gateway's argv arm: retune the ring live and kick
    the sampler so a shorter interval takes effect now, not after the
    old sleep."""
    HISTORY.configure(interval=interval, retention=retention)
    _wake.set()


__all__ = ["ENABLED", "HISTORY", "HistoryRing", "flatten",
           "key_family", "key_labels", "increase", "rate",
           "percentile_trajectory", "merge_series",
           "arm", "disarm", "configure", "add_tick_hook",
           "DEFAULT_INTERVAL", "DEFAULT_RETENTION", "MAX_SAMPLES"]
