"""Shared machinery for virtual-namespace layers (meta, snapview,
gfid-access) and xdata-carrying wrappers (utime, namespace).

Virtual trees need stable synthetic gfids and iatts; read-only trees
need BOTH the path-addressed and the fd-carried mutation surface
rejected (an fd opened on a virtual object must never fall through to
the live graph with a foreign gfid).  Fop wrappers that tag xdata must
find it wherever the caller put it — layers forward xdata positionally
as often as by keyword."""

from __future__ import annotations

import errno
import hashlib
import inspect
import stat as stat_mod
import time

from .fops import FopError
from .iatt import IAType, Iatt
from .layer import FdObj, Loc


def virtual_gfid(ns: str, path: str) -> bytes:
    return hashlib.md5(f"{ns}:{path}".encode(
        "utf-8", "surrogateescape")).digest()


def virtual_dir_iatt(gfid: bytes) -> Iatt:
    ia = Iatt(gfid=gfid, ia_type=IAType.DIR)
    ia.mode = stat_mod.S_IFDIR | 0o555
    ia.nlink = 2
    ia.atime = ia.mtime = ia.ctime = time.time()
    return ia


def virtual_file_iatt(gfid: bytes, size: int) -> Iatt:
    ia = Iatt(gfid=gfid, ia_type=IAType.REG)
    ia.mode = stat_mod.S_IFREG | 0o444
    ia.size = size
    ia.nlink = 1
    ia.atime = ia.mtime = ia.ctime = time.time()
    return ia


# path- and fd-carried mutation fops a read-only virtual tree rejects
LOC_MUTATIONS = ("unlink", "rmdir", "mkdir", "mknod", "create",
                 "rename", "link", "symlink", "truncate", "setattr",
                 "setxattr", "removexattr")
FD_MUTATIONS = ("writev", "ftruncate", "fsetattr", "fsetxattr",
                "fremovexattr", "fallocate", "discard", "zerofill")


def install_readonly_guards(cls, is_virtual_loc: str,
                            is_virtual_fd: str, msg: str) -> None:
    """Give cls EROFS guards over the whole mutation surface.
    is_virtual_loc/is_virtual_fd name predicate methods on cls taking a
    Loc / FdObj.  Methods the class defines itself are left alone."""

    def loc_guard(op_name):
        async def impl(self, *args, **kwargs):
            pred = getattr(self, is_virtual_loc)
            for a in args[:2]:
                if isinstance(a, Loc) and pred(a):
                    raise FopError(errno.EROFS, msg)
            return await getattr(self.children[0], op_name)(*args,
                                                            **kwargs)
        impl.__name__ = op_name
        return impl

    def fd_guard(op_name):
        async def impl(self, fd, *args, **kwargs):
            if getattr(self, is_virtual_fd)(fd):
                raise FopError(errno.EROFS, msg)
            return await getattr(self.children[0], op_name)(fd, *args,
                                                            **kwargs)
        impl.__name__ = op_name
        return impl

    for op in LOC_MUTATIONS:
        if op not in cls.__dict__:
            setattr(cls, op, loc_guard(op))
    for op in FD_MUTATIONS:
        if op not in cls.__dict__:
            setattr(cls, op, fd_guard(op))


_SIG_CACHE: dict = {}


def _fop_signature(child, op_name: str):
    """Best available signature for a fop: the child's own if it names
    its parameters, else the CANONICAL one from the posix storage layer
    — many mid-graph layers define fops as ``(self, *args, **kwargs)``
    passthroughs, and binding against those would hide every named
    argument (an identity gate above such a layer must still find
    xdata)."""
    key = (type(child), op_name)
    sig = _SIG_CACHE.get(key)
    if sig is not None:
        return sig
    sig = inspect.signature(getattr(child, op_name))
    if all(p.kind in (inspect.Parameter.VAR_POSITIONAL,
                      inspect.Parameter.VAR_KEYWORD)
           for p in sig.parameters.values()):
        from ..storage.posix import PosixLayer

        canon = getattr(PosixLayer, op_name, None)
        if canon is not None:
            csig = inspect.signature(canon)
            # drop `self`: we bind call-site args of a bound method
            params = [p for n, p in csig.parameters.items()
                      if n != "self"]
            sig = csig.replace(parameters=params)
    _SIG_CACHE[key] = sig
    return sig


def _bound_arg(child, op_name: str, args: tuple, kwargs: dict,
               param: str):
    sig = _fop_signature(child, op_name)
    if param not in sig.parameters:
        return kwargs.get(param)
    try:
        ba = sig.bind(*args, **kwargs)
    except TypeError:
        return kwargs.get(param)
    return ba.arguments.get(param)


def extract_xdata(child, op_name: str, args: tuple,
                  kwargs: dict) -> dict | None:
    """Read the xdata argument wherever the caller put it, without
    disturbing the call."""
    xd = _bound_arg(child, op_name, args, kwargs, "xdata")
    return xd if isinstance(xd, dict) else None


def extract_arg(child, op_name: str, args: tuple, kwargs: dict,
                param: str):
    """Read any named fop argument wherever the caller put it
    (positional or keyword), resolving var-arg passthrough layers to
    the canonical fop signature."""
    return _bound_arg(child, op_name, args, kwargs, param)


def call_with_xdata(child, op_name: str, args: tuple, kwargs: dict,
                    update: dict):
    """Invoke child.op(*args, **kwargs) with `update` merged into its
    xdata parameter wherever the caller put it (positional or keyword
    or absent).  Returns the awaitable.  Existing keys win over the
    update (setdefault semantics)."""
    fn = getattr(child, op_name)
    sig = _fop_signature(child, op_name)
    if "xdata" not in sig.parameters:
        return fn(*args, **kwargs)
    try:
        ba = sig.bind(*args, **kwargs)
    except TypeError:
        return fn(*args, **kwargs)  # let the real call raise precisely
    xd = ba.arguments.get("xdata")
    if not isinstance(xd, dict):
        xd = {}
    merged = {**update, **xd}
    ba.arguments["xdata"] = merged
    return fn(*ba.args, **ba.kwargs)
