"""Graph engine: volfile DSL -> layer tree -> init/activate/statedump.

The reference parses volfiles with a flex/bison grammar
(``volume/type/option/subvolumes/end-volume``, reference
libglusterfs/src/graph.y:52-71), builds the xlator tree
(graph.c:980 ``glusterfs_graph_construct``), initializes it bottom-up
(graph.c:456 ``glusterfs_graph_init``) and sends parent-up
(graph.c:568).  The same DSL is kept here (judgeable parity; volgen emits
it) with a hand-written parser.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from . import gflog
from .layer import Event, Layer, lookup_type

log = gflog.get_logger("core")


class VolfileError(ValueError):
    pass


@dataclasses.dataclass
class VolumeSpec:
    name: str
    type_name: str
    options: dict[str, str]
    subvolumes: list[str]


def parse_volfile(text: str) -> list[VolumeSpec]:
    """Parse the volume/type/option/subvolumes/end-volume DSL."""
    specs: list[VolumeSpec] = []
    cur: VolumeSpec | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        words = line.split()
        kw = words[0]
        if kw == "volume":
            if cur is not None:
                raise VolfileError(f"line {lineno}: nested volume")
            if len(words) != 2:
                raise VolfileError(f"line {lineno}: volume needs a name")
            cur = VolumeSpec(words[1], "", {}, [])
        elif kw == "end-volume":
            if cur is None:
                raise VolfileError(f"line {lineno}: end-volume without volume")
            if not cur.type_name:
                raise VolfileError(f"volume {cur.name}: missing type")
            specs.append(cur)
            cur = None
        elif cur is None:
            raise VolfileError(f"line {lineno}: {kw!r} outside volume block")
        elif kw == "type":
            if len(words) != 2:
                raise VolfileError(f"line {lineno}: type needs one value")
            cur.type_name = words[1]
        elif kw == "option":
            if len(words) < 3:
                raise VolfileError(f"line {lineno}: option needs key + value")
            cur.options[words[1]] = " ".join(words[2:])
        elif kw == "subvolumes":
            if len(words) < 2:
                raise VolfileError(f"line {lineno}: subvolumes needs names")
            cur.subvolumes = words[1:]
        else:
            raise VolfileError(f"line {lineno}: unknown keyword {kw!r}")
    if cur is not None:
        raise VolfileError(f"volume {cur.name}: missing end-volume")
    if not specs:
        raise VolfileError("empty volfile")
    return specs


def emit_volfile(specs: list[VolumeSpec]) -> str:
    """Serialize specs back to the DSL (volgen uses this)."""
    out = []
    for s in specs:
        out.append(f"volume {s.name}")
        out.append(f"    type {s.type_name}")
        for k, v in s.options.items():
            out.append(f"    option {k} {v}")
        if s.subvolumes:
            out.append(f"    subvolumes {' '.join(s.subvolumes)}")
        out.append("end-volume")
        out.append("")
    return "\n".join(out)


class Graph:
    """A constructed layer tree."""

    def __init__(self, top: Layer, by_name: dict[str, Layer],
                 volfile_text: str = ""):
        self.top = top
        self.by_name = by_name
        self.volfile_text = volfile_text
        self.active = False

    @classmethod
    def construct(cls, volfile: str | list[VolumeSpec],
                  top_name: str | None = None, ctx: Any = None) -> "Graph":
        """Build the tree (glusterfs_graph_construct + prepare analog)."""
        text = volfile if isinstance(volfile, str) else emit_volfile(volfile)
        specs = parse_volfile(text) if isinstance(volfile, str) else volfile
        by_name: dict[str, Layer] = {}
        for spec in specs:  # bottom-up: subvolumes must already exist
            children = []
            for sub in spec.subvolumes:
                if sub not in by_name:
                    raise VolfileError(
                        f"volume {spec.name}: unknown subvolume {sub!r}")
                children.append(by_name[sub])
            if spec.name in by_name:
                raise VolfileError(f"duplicate volume {spec.name!r}")
            klass = lookup_type(spec.type_name)
            by_name[spec.name] = klass(spec.name, dict(spec.options),
                                       children, ctx=ctx)
        if top_name is not None:
            if top_name not in by_name:
                raise VolfileError(f"no volume named {top_name!r}")
            top = by_name[top_name]
        else:
            # default top: the layer nobody references (last defined wins)
            referenced = {c.name for l in by_name.values() for c in l.children}
            tops = [l for l in by_name.values() if l.name not in referenced]
            top = tops[-1]
        return cls(top, by_name, text)

    def _topo_order(self) -> list[Layer]:
        """Children before parents (bottom-up init order)."""
        seen: set[int] = set()
        order: list[Layer] = []

        def visit(l: Layer):
            if id(l) in seen:
                return
            seen.add(id(l))
            for c in l.children:
                visit(c)
            order.append(l)

        visit(self.top)
        return order

    async def init(self) -> None:
        """Bottom-up init (glusterfs_graph_init)."""
        for layer in self._topo_order():
            await layer.init()

    async def activate(self) -> None:
        """init + parent-up (glusterfs_graph_activate)."""
        await self.init()
        self.top.notify(Event.PARENT_UP)
        self.active = True

    async def fini(self) -> None:
        for layer in reversed(self._topo_order()):
            await layer.fini()
        self.active = False

    def same_shape(self, specs: list[VolumeSpec]) -> bool:
        """True when specs describe this graph's exact topology (names,
        types, subvolume wiring) — the precondition for in-place
        reconfigure (reference glusterfs_graph_reconfigure vs the full
        graph switch, graph.c:980-1089)."""
        if {s.name for s in specs} != set(self.by_name):
            return False
        for s in specs:
            layer = self.by_name[s.name]
            if layer.type_name != s.type_name:
                return False
            if [c.name for c in layer.children] != s.subvolumes:
                return False
        return True

    def apply_volfile(self, text: str) -> bool:
        """Live option reconfigure: same topology -> push each spec's
        options through ``layer.reconfigure`` (validated, defaults
        restored for dropped keys) and return True; topology change ->
        False, the caller must swap graphs."""
        specs = parse_volfile(text)
        if not self.same_shape(specs):
            return False
        for s in specs:
            self.by_name[s.name].reconfigure(s.options)
        self.volfile_text = text
        return True

    def statedump(self) -> dict:
        """Full-graph introspection (the SIGUSR1 statedump / .meta analog,
        reference statedump.c:831; tests read this like volume.rc parses
        statedumps)."""
        from . import tracing

        return {
            "top": self.top.name,
            "layers": {name: l.statedump() for name, l in self.by_name.items()},
            "recent_logs": gflog.recent_messages(50),
            # newest spans from the per-process ring: over the wire a
            # brick's __statedump__ shows the same trace ids the client
            # minted (protocol/server re-arms them), so the two dumps
            # join into one per-request tree
            "trace_spans": tracing.recent_spans(200),
        }
