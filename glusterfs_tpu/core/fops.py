"""File-operation model: the framework's fop vocabulary.

The reference defines 59 fops as an enum (reference
libglusterfs/src/glusterfs/glusterfs-fops.h:17-76) and every xlator
implements a subset of them via a fops vtable (xlator.h:545).  Here the
vocabulary is the same, but the mechanism is idiomatic Python: each fop is
an async method on :class:`glusterfs_tpu.core.layer.Layer`, winding is an
``await`` into the child, unwinding is the return (or raised
:class:`FopError`).
"""

from __future__ import annotations

import enum
import errno as _errno


class Fop(enum.Enum):
    """Fop vocabulary (reference glusterfs-fops.h:17-76, same set minus
    the getspec RPC-internal entry).  COMPOUND (the reference's
    GF_FOP_COMPOUND fused-chain carrier) is a real member here: its
    argument is an ordered link chain executed brick-side in one round
    trip (rpc/compound.py defines the envelope and the graph
    semantics)."""

    STAT = "stat"
    READLINK = "readlink"
    MKNOD = "mknod"
    MKDIR = "mkdir"
    UNLINK = "unlink"
    RMDIR = "rmdir"
    SYMLINK = "symlink"
    RENAME = "rename"
    LINK = "link"
    TRUNCATE = "truncate"
    OPEN = "open"
    READV = "readv"
    WRITEV = "writev"
    STATFS = "statfs"
    FLUSH = "flush"
    FSYNC = "fsync"
    SETXATTR = "setxattr"
    GETXATTR = "getxattr"
    REMOVEXATTR = "removexattr"
    OPENDIR = "opendir"
    FSYNCDIR = "fsyncdir"
    ACCESS = "access"
    CREATE = "create"
    FTRUNCATE = "ftruncate"
    FSTAT = "fstat"
    LK = "lk"
    LOOKUP = "lookup"
    READDIR = "readdir"
    INODELK = "inodelk"
    FINODELK = "finodelk"
    ENTRYLK = "entrylk"
    FENTRYLK = "fentrylk"
    XATTROP = "xattrop"
    FXATTROP = "fxattrop"
    FGETXATTR = "fgetxattr"
    FSETXATTR = "fsetxattr"
    RCHECKSUM = "rchecksum"
    SETATTR = "setattr"
    FSETATTR = "fsetattr"
    READDIRP = "readdirp"
    FREMOVEXATTR = "fremovexattr"
    FALLOCATE = "fallocate"
    DISCARD = "discard"
    ZEROFILL = "zerofill"
    IPC = "ipc"
    SEEK = "seek"
    LEASE = "lease"
    GETACTIVELK = "getactivelk"
    SETACTIVELK = "setactivelk"
    PUT = "put"
    ICREATE = "icreate"
    NAMELINK = "namelink"
    COPY_FILE_RANGE = "copy_file_range"
    COMPOUND = "compound"
    # parity-delta write plane (ISSUE 10): read-xor-write at a fragment
    # offset, served by storage/posix in one journal-batched pass.  A
    # write-class fop that must NEVER be blindly retried: XOR is an
    # involution, so a double-applied delta self-cancels (the client's
    # idempotent-retry allowlist is read-class only and excludes it).
    XORV = "xorv"


#: Fops that modify data or metadata (drive version/dirty accounting in the
#: EC/AFR transaction engines; reference ec-common.h fop classification).
WRITE_FOPS = frozenset({
    Fop.MKNOD, Fop.MKDIR, Fop.UNLINK, Fop.RMDIR, Fop.SYMLINK, Fop.RENAME,
    Fop.LINK, Fop.TRUNCATE, Fop.WRITEV, Fop.SETXATTR, Fop.REMOVEXATTR,
    Fop.CREATE, Fop.FTRUNCATE, Fop.XATTROP, Fop.FXATTROP, Fop.FSETXATTR,
    Fop.SETATTR, Fop.FSETATTR, Fop.FREMOVEXATTR, Fop.FALLOCATE, Fop.DISCARD,
    Fop.ZEROFILL, Fop.PUT, Fop.ICREATE, Fop.NAMELINK, Fop.COPY_FILE_RANGE,
    Fop.XORV,
})


class FopError(OSError):
    """A fop failure carrying a POSIX errno (the reference's op_errno;
    unwinding with op_ret=-1 maps to raising this).

    ``xdata`` is the error-path reply dict (the reference unwinds
    op_errno WITH an xdata dict — e.g. the lock-revocation notice of
    features/locks rides the EAGAIN it causes).  Optional: most errors
    carry none, and both wire codecs keep the two-field shape for
    those."""

    def __init__(self, err: int, msg: str = "", xdata: dict | None = None):
        super().__init__(err, msg or _errno.errorcode.get(err, str(err)))
        self.err = err
        self.xdata = xdata

    def __repr__(self) -> str:  # pragma: no cover
        return f"FopError({_errno.errorcode.get(self.err, self.err)})"
