"""Typed per-layer option framework.

The reference gives every xlator a ``volume_option_t`` table with typed
validation (int/bool/percent/size/time/string-enum, min/max, defaults) and
runtime ``reconfigure`` (reference libglusterfs/src/options.c:20-326,
glusterfs/options.h ``GF_OPTION_INIT``/``GF_OPTION_RECONF``).  Same model
here: each Layer class declares ``OPTIONS``; values are validated at graph
build and on reconfigure.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

_SIZE_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([KMGTP]?)I?B?$", re.IGNORECASE)
_TIME_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*(s|sec|min|h|hr|d|w|ms)?$")

_SIZE_MULT = {"": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30,
              "T": 1 << 40, "P": 1 << 50}
_TIME_MULT = {None: 1.0, "s": 1.0, "sec": 1.0, "min": 60.0, "h": 3600.0,
              "hr": 3600.0, "d": 86400.0, "w": 604800.0, "ms": 0.001}

_BOOL_TRUE = {"1", "on", "yes", "true", "enable", "enabled"}
_BOOL_FALSE = {"0", "off", "no", "false", "disable", "disabled"}


class OptionError(ValueError):
    pass


def parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in _BOOL_TRUE:
        return True
    if s in _BOOL_FALSE:
        return False
    raise OptionError(f"not a boolean: {v!r}")


def parse_size(v: Any) -> int:
    """'64KB', '1M', '512' -> bytes (reference gf_string2bytesize)."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    m = _SIZE_RE.match(str(v).strip())
    if not m:
        raise OptionError(f"not a size: {v!r}")
    return int(float(m.group(1)) * _SIZE_MULT[m.group(2).upper()])


def parse_time(v: Any) -> float:
    """'10', '500ms', '2min' -> seconds (reference gf_string2time)."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    m = _TIME_RE.match(str(v).strip().lower())
    if not m:
        raise OptionError(f"not a time: {v!r}")
    return float(m.group(1)) * _TIME_MULT[m.group(2)]


@dataclasses.dataclass(frozen=True)
class Option:
    """One typed option (volume_option_t analog)."""

    name: str
    otype: str = "str"  # str | int | bool | size | time | percent | enum | path
    default: Any = None
    min: float | None = None
    max: float | None = None
    values: tuple[str, ...] | None = None  # for enum
    description: str = ""
    validate_fn: Callable[[Any], Any] | None = None

    def parse(self, value: Any) -> Any:
        try:
            if self.otype == "int":
                out: Any = int(value)
            elif self.otype == "bool":
                out = parse_bool(value)
            elif self.otype == "size":
                out = parse_size(value)
            elif self.otype == "time":
                out = parse_time(value)
            elif self.otype == "percent":
                s = str(value).rstrip("%")
                out = float(s)
            elif self.otype == "enum":
                out = str(value)
                if self.values and out not in self.values:
                    raise OptionError(
                        f"{self.name}: {out!r} not in {self.values}")
            else:
                out = str(value) if not isinstance(value, str) else value
        except (TypeError, ValueError) as e:
            raise OptionError(f"option {self.name}: {e}") from e
        if self.min is not None and out < self.min:
            raise OptionError(f"option {self.name}={out} below min {self.min}")
        if self.max is not None and out > self.max:
            raise OptionError(f"option {self.name}={out} above max {self.max}")
        if self.validate_fn is not None:
            out = self.validate_fn(out)
        return out


def validate_options(table: tuple[Option, ...], raw: dict[str, Any],
                     *, strict: bool = False) -> dict[str, Any]:
    """Parse raw option strings against a table; unknown keys pass through
    untyped unless strict (the reference warns on unknown options)."""
    byname = {o.name: o for o in table}
    out: dict[str, Any] = {o.name: o.parse(o.default)
                           for o in table if o.default is not None}
    for key, val in raw.items():
        opt = byname.get(key)
        if opt is None:
            if strict:
                raise OptionError(f"unknown option {key!r}")
            out[key] = val
        else:
            out[key] = opt.parse(val)
    return out
