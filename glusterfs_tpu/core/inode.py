"""Inode & dentry table: GFID-keyed identity cache with LRU.

Reference: libglusterfs/src/inode.c (inode_table_new/inode_link,
inode.c:983,1098,1564-1605) — a per-graph table mapping GFID -> inode with
a dentry hash ((parent gfid, basename) -> inode) and an LRU of unreferenced
inodes.  Per-layer inode ctx slots mirror inode_ctx_set/get.
"""

from __future__ import annotations

import collections
import threading
from typing import Any

from .iatt import IAType, Iatt, ROOT_GFID


class Inode:
    __slots__ = ("gfid", "ia_type", "table", "nlookup", "_ctx", "iatt")

    def __init__(self, gfid: bytes, ia_type: IAType, table: "InodeTable"):
        self.gfid = gfid
        self.ia_type = ia_type
        self.table = table
        self.nlookup = 0
        self.iatt: Iatt | None = None
        self._ctx: dict[int, Any] = {}

    def ctx_set(self, layer, value: Any) -> None:
        self._ctx[id(layer)] = value

    def ctx_get(self, layer, default: Any = None) -> Any:
        return self._ctx.get(id(layer), default)

    def ctx_del(self, layer) -> Any:
        return self._ctx.pop(id(layer), None)

    def is_dir(self) -> bool:
        return self.ia_type is IAType.DIR


class InodeTable:
    def __init__(self, lru_limit: int = 16384):
        self._lock = threading.RLock()
        self._by_gfid: dict[bytes, Inode] = {}
        self._dentries: dict[tuple[bytes, str], bytes] = {}
        self._rdentries: dict[bytes, set[tuple[bytes, str]]] = {}
        self._lru: collections.OrderedDict[bytes, None] = collections.OrderedDict()
        self.lru_limit = lru_limit
        self.root = self._new(ROOT_GFID, IAType.DIR)
        self.root.nlookup = 1  # root is pinned

    def _new(self, gfid: bytes, ia_type: IAType) -> Inode:
        ino = Inode(gfid, ia_type, self)
        self._by_gfid[gfid] = ino
        return ino

    def get(self, gfid: bytes) -> Inode | None:
        with self._lock:
            ino = self._by_gfid.get(gfid)
            if ino is not None and gfid in self._lru:
                self._lru.move_to_end(gfid)
            return ino

    def find_dentry(self, parent: bytes, name: str) -> Inode | None:
        with self._lock:
            gfid = self._dentries.get((parent, name))
            return self._by_gfid.get(gfid) if gfid else None

    def link(self, parent: bytes, name: str, gfid: bytes,
             ia_type: IAType, iatt: Iatt | None = None) -> Inode:
        """Record identity + dentry after a successful lookup/create
        (reference __inode_link, inode.c:983)."""
        with self._lock:
            ino = self._by_gfid.get(gfid)
            if ino is None:
                ino = self._new(gfid, ia_type)
            ino.nlookup += 1
            if iatt is not None:
                ino.iatt = iatt
            key = (parent, name)
            old = self._dentries.get(key)
            if old is not None and old != gfid:
                self._rdentries.get(old, set()).discard(key)
            self._dentries[key] = gfid
            self._rdentries.setdefault(gfid, set()).add(key)
            self._lru.pop(gfid, None)
            return ino

    def unlink(self, parent: bytes, name: str) -> None:
        with self._lock:
            key = (parent, name)
            gfid = self._dentries.pop(key, None)
            if gfid is not None:
                self._rdentries.get(gfid, set()).discard(key)

    def forget(self, gfid: bytes, nlookup: int = 1) -> None:
        """Drop lookups; unreferenced inodes go to the LRU (inode.c lru)."""
        with self._lock:
            ino = self._by_gfid.get(gfid)
            if ino is None or gfid == ROOT_GFID:
                return
            ino.nlookup = max(0, ino.nlookup - nlookup)
            if ino.nlookup == 0:
                self._lru[gfid] = None
                self._lru.move_to_end(gfid)
                while len(self._lru) > self.lru_limit:
                    evict, _ = self._lru.popitem(last=False)
                    self._purge(evict)

    def _purge(self, gfid: bytes) -> None:
        self._by_gfid.pop(gfid, None)
        for key in self._rdentries.pop(gfid, set()):
            self._dentries.pop(key, None)

    def invalidate(self, gfid: bytes) -> None:
        """Forcibly drop an inode + its dentries (upcall invalidation)."""
        with self._lock:
            self._lru.pop(gfid, None)
            if gfid != ROOT_GFID:
                self._purge(gfid)

    def dump(self) -> dict:
        with self._lock:
            # active = referenced (nlookup > 0) inodes; everything in
            # the LRU is by construction unreferenced (the reference's
            # itable dump splits active/lru the same way, inode.c
            # inode_table_dump)
            return {
                "inodes": len(self._by_gfid),
                "active": len(self._by_gfid) - len(self._lru),
                "dentries": len(self._dentries),
                "lru": len(self._lru),
                "lru_limit": self.lru_limit,
            }
