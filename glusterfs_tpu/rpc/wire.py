"""Wire codec + framing — the XDR analog.

Reference: rpc/xdr/src/*.x define the wire schema; rpc-lib frames records
over the socket.  Here: a small tagged binary codec for the value tree a
fop carries (ints, bytes, strings, lists, dicts, Iatt, Loc, fd handles,
errors) and length-prefixed frames.  No pickle — only the types below can
cross the wire (same property XDR gives the reference).

Frame: 4-byte big-endian length, then the record.
Record: 8-byte header (u32 xid, u8 mtype, u8 flags, 2 reserved) + body.

Bulk payloads (the iobref analog): the reference never XDR-encodes file
data — write payloads ride beside the header as raw iobufs
(rpc-lib/src/rpc-clnt.c iobref submit; socket.c's vectored writev).
Here the same: a :class:`Blob` in the value tree is encoded as a tiny
reference (tag + offset/length) and its bytes are shipped verbatim
AFTER the body (``FL_BLOBS`` record layout: header, u32 body length,
body, then the concatenated blob bytes).  ``pack_frames`` returns the
prefix plus the original buffer objects so the transport can
``writelines`` them with zero payload copies; ``unpack`` hands blobs
back as memoryviews into the received frame, so the receive side also
adds no copy beyond the socket read itself.
"""

from __future__ import annotations

import os
import struct
from typing import Any

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt
from ..core.layer import Loc

MT_CALL = 1
MT_REPLY = 2
MT_ERROR = 3
MT_EVENT = 4  # server -> client notifications (upcall channel analog)
# on-wire compression (the cdc/compress xlator analog): an MT_ZLIB
# record's body is the zlib deflate of a complete inner record
MT_ZLIB = 5

# The RPC peer identity of the request currently being dispatched
# (set per-call by protocol/server, read by brick-side layers that need
# to know WHO is asking — features/upcall's client registry; the
# reference threads this through frame->root->client).
import contextvars as _contextvars  # noqa: E402

CURRENT_CLIENT: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "gftpu_current_client", default=None)

# The absolute (local event-loop clock) deadline of the request being
# dispatched, armed per-call by protocol/server from the client's
# propagated budget (network.deadline-propagation).  Brick-side queue
# layers (io-threads) read it to DROP work whose client has already
# timed the call out; None = no budget known.
CURRENT_DEADLINE: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "gftpu_current_deadline", default=None)

# The io-threads priority lane of the request being dispatched, set
# per-call by protocol/server from the QoS engine's verdict
# (features/qos): "least" demotes the request to the least-priority
# class (rebalance-origin traffic, currently-shaped clients); "" keeps
# the per-fop priority table.
CURRENT_LANE: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "gftpu_current_lane", default="")

_HDR = struct.Struct(">IBBxx")

# record flags (byte 5 of the header; 0 in pre-blob frames)
FL_BLOBS = 1
# blob payloads ride a shared-memory arena (rpc/shm): the record
# carries a (seq, offset, length) descriptor table instead of bytes
FL_SHM = 2

# value tags
_T_NONE, _T_TRUE, _T_FALSE = 0, 1, 2
_T_INT, _T_NEGINT, _T_FLOAT = 3, 4, 5
_T_BYTES, _T_STR = 6, 7
_T_LIST, _T_DICT = 8, 9
_T_IATT, _T_LOC, _T_FD, _T_ERR = 10, 11, 12, 13
_T_BLOBREF = 14

# observability: how many payload bytes rode the zero-copy lane vs were
# inlined through the tagged codec (bench asserts the lane is actually
# taken; the reference counts iobref hits the same way in io-stats).
# rx_* mirror the receive side: blob bytes decoded as views into the
# frame (the read pipeline's zero-copy proof counter).
blob_stats = {"tx_blobs": 0, "tx_bytes": 0, "inline_bytes": 0,
              "rx_frames": 0, "rx_bytes": 0}

# absorbed into the unified registry (core/metrics.py): the dict stays
# the hot-path counter store, the registry reads it at scrape time
from ..core import metrics as _metrics  # noqa: E402

_metrics.REGISTRY.register(
    "gftpu_wire_blob_stats", "counter",
    "payload bytes/frames by wire lane (blob vs inline, tx vs rx)",
    lambda: _metrics.labeled(blob_stats))


class Blob:
    """A bulk payload shipped out-of-band (iobuf analog).

    Wrap file data in a Blob before handing the value tree to
    ``pack_frames`` and the bytes never pass through the codec; without
    a collector (plain ``pack`` / compressed frames) it degrades to an
    inline _T_BYTES, so every path stays correct."""

    __slots__ = ("view",)

    def __init__(self, data):
        self.view = data if isinstance(data, memoryview) \
            else memoryview(data)

    def __len__(self):
        return len(self.view)


class WireError(Exception):
    pass


class ShmDecodeError(WireError):
    """An FL_SHM record that cannot be served from a local arena (lane
    not armed, malformed descriptor, mapping gone).  Transports answer
    this with EOPNOTSUPP + an ``shm-unsupported`` xdata notice so the
    peer downgrades to inline frames, instead of dropping the
    connection over a recoverable capability mismatch."""


#: wire spelling of a scatter-gather payload: a one-key dict whose value
#: is the ordered segment list.  A plain dict (not a new value tag) so
#: both codecs — and any recorded frame — stay format-compatible.
SG_KEY = "__sg__"


class SGBuf:
    """A scatter-gather payload: an ordered vector of buffer segments
    (the iovec/iobref-list analog).  Produced by layers that already
    hold the reply as several buffers — cached pages, per-link chain
    replies, EC fragment windows — so the bytes are never joined just
    to cross the wire: each segment rides the blob lane as its own
    trailing buffer (``pack_frames`` + ``writelines`` = one gathered
    send) and decodes back into segment memoryviews on the far side.

    Joining happens exactly once, at a boundary that demands plain
    bytes (``bytes(sg)``: the glfs API edge); ``os.writev`` consumers
    (the fuse bridge) hand the segments straight to the kernel."""

    __slots__ = ("segments",)

    def __init__(self, segments):
        self.segments = [s if isinstance(s, memoryview) else memoryview(s)
                         for s in segments]

    def __len__(self) -> int:
        return sum(len(s) for s in self.segments)

    def __bytes__(self) -> bytes:
        return b"".join(self.segments)

    def tobytes(self) -> bytes:
        return b"".join(self.segments)

    def __eq__(self, other) -> bool:
        if isinstance(other, SGBuf):
            return self.tobytes() == other.tobytes()
        if isinstance(other, (bytes, bytearray, memoryview)):
            return self.tobytes() == bytes(other)
        return NotImplemented

    __hash__ = None  # eq without hash: segments are mutable

    def __repr__(self):  # pragma: no cover
        return f"SGBuf({len(self.segments)} segs, {len(self)}B)"


def as_single_buffer(data):
    """A buffer-protocol view of any readv result shape (bytes,
    memoryview, SGBuf) — what np.frombuffer / os.pwrite consumers call
    before touching payload bytes.  Single-segment SGBufs stay
    zero-copy; multi-segment ones pay their one join here."""
    if isinstance(data, SGBuf):
        if len(data.segments) == 1:
            return data.segments[0]
        return data.tobytes()
    return data


def serve_pages(pages, offset: int, end: int, psz: int):
    """Assemble [offset, end) from a page map as zero-copy views — the
    shared serve loop of the page-granular read caches (io-cache,
    read-ahead).  Pages are immutable bytes keyed by index; a missing
    or short page is EOF.  Returns b'' / a single bytes-or-view / an
    SGBuf, never joining multi-page answers (small single-page answers
    come back as owned bytes: the view wrapper costs more than it
    saves)."""
    segs = []
    pos = offset
    while pos < end:
        idx = pos // psz
        page = pages.get(idx)
        if page is None:
            break  # EOF
        start = pos - idx * psz
        if start >= len(page):
            break  # EOF inside this page
        take = memoryview(page)[start: min(len(page),
                                           start + (end - pos))]
        segs.append(take)
        if len(page) < psz:  # short page = EOF
            break
        pos += len(take)
    if not segs:
        return b""
    if len(segs) == 1:
        return bytes(segs[0]) if len(segs[0]) < 4096 else segs[0]
    return SGBuf(segs)


class FdHandle:
    """A remote fd reference (server-side fd table slot) carrying the fd
    identity so the client can reconstruct a local FdObj."""

    __slots__ = ("fdid", "gfid", "path")

    def __init__(self, fdid: int, gfid: bytes = b"", path: str = ""):
        self.fdid = fdid
        self.gfid = gfid
        self.path = path

    def __repr__(self):  # pragma: no cover
        return f"FdHandle({self.fdid})"


def _enc_uint(out: bytearray, n: int) -> None:
    # LEB128-ish varint
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _dec_uint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def encode_value(v: Any, out: bytearray,
                 blobs: list | None = None) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        if v >= 0:
            out.append(_T_INT)
            _enc_uint(out, v)
        else:
            out.append(_T_NEGINT)
            _enc_uint(out, -v)
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += struct.pack(">d", v)
    elif isinstance(v, Blob):
        if blobs is None:  # no out-of-band lane: inline (compressed path)
            out.append(_T_BYTES)
            _enc_uint(out, len(v.view))
            out += v.view
            blob_stats["inline_bytes"] += len(v.view)
        else:
            out.append(_T_BLOBREF)
            _enc_uint(out, len(v.view))
            blobs.append(v.view)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(_T_BYTES)
        b = bytes(v)
        _enc_uint(out, len(b))
        out += b
    elif isinstance(v, str):
        out.append(_T_STR)
        # surrogateescape: filenames come off the kernel/disk as raw
        # bytes; non-UTF-8 names must round-trip the wire losslessly
        b = v.encode("utf-8", "surrogateescape")
        _enc_uint(out, len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _enc_uint(out, len(v))
        for item in v:
            encode_value(item, out, blobs)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _enc_uint(out, len(v))
        for k, val in v.items():
            encode_value(k, out, blobs)
            encode_value(val, out, blobs)
    elif isinstance(v, Iatt):
        out.append(_T_IATT)
        encode_value([v.gfid, v.ia_type.value, v.mode, v.nlink, v.uid,
                      v.gid, v.size, v.blocks, v.atime, v.mtime, v.ctime,
                      v.rdev, v.blksize], out)
    elif isinstance(v, Loc):
        out.append(_T_LOC)
        encode_value([v.path, v.gfid, v.parent, v.name], out)
    elif isinstance(v, FdHandle):
        out.append(_T_FD)
        encode_value([v.fdid, v.gfid, v.path], out)
    elif isinstance(v, FopError):
        out.append(_T_ERR)
        msg = str(v.args[1]) if len(v.args) > 1 else ""
        xd = getattr(v, "xdata", None)
        # two-field shape unless an error xdata rides along (the
        # lock-revocation notice): a third element old decoders ignore
        encode_value([v.err, msg, xd] if xd else [v.err, msg], out)
    else:
        raise WireError(f"unencodable type {type(v).__name__}")


def decode_value(buf: memoryview, pos: int,
                 blobs: list | None = None) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        return _dec_uint(buf, pos)
    if tag == _T_NEGINT:
        n, pos = _dec_uint(buf, pos)
        return -n, pos
    if tag == _T_FLOAT:
        return struct.unpack_from(">d", buf, pos)[0], pos + 8
    if tag == _T_BYTES:
        n, pos = _dec_uint(buf, pos)
        return bytes(buf[pos:pos + n]), pos + n
    if tag == _T_BLOBREF:
        n, pos = _dec_uint(buf, pos)
        if blobs is None:
            raise WireError("blob reference outside a FL_BLOBS record")
        region, off = blobs
        if isinstance(region, list):
            # FL_SHM record: refs resolve by INDEX into the arena views
            # the descriptor table named (``off`` counts refs here).
            # Lengths must agree — a mismatch means the table and the
            # body disagree about the frame's shape
            if off >= len(region) or len(region[off]) != n:
                raise ShmDecodeError("shm descriptor/blobref mismatch")
            blobs[1] = off + 1
            return region[off], pos
        if off + n > len(region):
            raise WireError("blob reference beyond record")
        blobs[1] = off + n
        # a memoryview INTO the received frame: the payload is never
        # copied again on this side (posix pwrite / np.frombuffer both
        # take buffer views)
        return region[off:off + n], pos
    if tag == _T_STR:
        n, pos = _dec_uint(buf, pos)
        return bytes(buf[pos:pos + n]).decode("utf-8", "surrogateescape"), \
            pos + n
    if tag == _T_LIST:
        n, pos = _dec_uint(buf, pos)
        out = []
        for _ in range(n):
            item, pos = decode_value(buf, pos, blobs)
            out.append(item)
        return out, pos
    if tag == _T_DICT:
        n, pos = _dec_uint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = decode_value(buf, pos, blobs)
            v, pos = decode_value(buf, pos, blobs)
            d[k] = v
        return d, pos
    if tag == _T_IATT:
        vals, pos = decode_value(buf, pos)
        ia = Iatt(gfid=vals[0], ia_type=IAType(vals[1]), mode=vals[2],
                  nlink=vals[3], uid=vals[4], gid=vals[5], size=vals[6],
                  blocks=vals[7], atime=vals[8], mtime=vals[9],
                  ctime=vals[10], rdev=vals[11], blksize=vals[12])
        return ia, pos
    if tag == _T_LOC:
        vals, pos = decode_value(buf, pos)
        return Loc(vals[0], gfid=vals[1], parent=vals[2], name=vals[3]), pos
    if tag == _T_FD:
        vals, pos = decode_value(buf, pos)
        return FdHandle(vals[0], vals[1], vals[2]), pos
    if tag == _T_ERR:
        vals, pos = decode_value(buf, pos)
        return FopError(vals[0], vals[1],
                        vals[2] if len(vals) > 2 else None), pos
    raise WireError(f"bad tag {tag}")


# ---------------------------------------------------------------------
# C codec (native/src/wirec.c — the XDR-is-generated-C analog): same
# format bit-for-bit, built on demand; this module is the fallback.
# Disable with GFTPU_NO_WIREC=1.
# ---------------------------------------------------------------------

_wirec = None
if not os.environ.get("GFTPU_NO_WIREC"):
    try:
        from glusterfs_tpu import native as _native

        _wirec = _native.wirec_module()
        _wirec.register(
            Iatt, Loc, FdHandle, FopError, Blob,
            lambda v: Iatt(gfid=v[0], ia_type=IAType(v[1]), mode=v[2],
                           nlink=v[3], uid=v[4], gid=v[5], size=v[6],
                           blocks=v[7], atime=v[8], mtime=v[9],
                           ctime=v[10], rdev=v[11], blksize=v[12]),
            lambda v: Loc(v[0], gfid=v[1], parent=v[2], name=v[3]),
            lambda v: FdHandle(v[0], v[1], v[2]),
            lambda v: FopError(v[0], v[1], v[2] if len(v) > 2 else None),
            WireError, blob_stats)
    except Exception:  # no toolchain: pure-Python codec serves
        _wirec = None


def _encode_body(payload: Any, blobs: list | None) -> bytes:
    if _wirec is not None:
        return _wirec.encode(payload, blobs if blobs is not None
                             else None)
    body = bytearray()
    encode_value(payload, body, blobs)
    return bytes(body)


def _decode_body(buf, pos: int, blobs: list | None = None):
    if _wirec is not None and \
            (blobs is None or isinstance(blobs[0], memoryview)):
        return _wirec.decode(buf, pos, blobs)
    return decode_value(buf, pos, blobs)


def pack(xid: int, mtype: int, payload: Any) -> bytes:
    rec = _HDR.pack(xid, mtype, 0) + _encode_body(payload, None)
    return struct.pack(">I", len(rec)) + rec


def pack_frames(xid: int, mtype: int, payload: Any,
                shm_tx=None) -> list:
    """Frame a record with payload blobs out-of-band.

    Returns a list of buffers for ``StreamWriter.writelines``: one
    prefix (length, header, body-length, body) followed by the blob
    buffers THEMSELVES — file data crosses into the transport without
    ever being copied into the frame.

    With an armed ``shm_tx`` arena (rpc/shm), the blobs are written
    once into shared memory instead and the record carries only their
    descriptor table (FL_SHM) — zero payload bytes on the socket.  An
    arena that can't hold this frame right now returns the frame to
    the FL_BLOBS path: fallback is per-frame, never a mode switch."""
    blobs: list = []
    body = _encode_body(payload, blobs)
    if not blobs:
        rec = _HDR.pack(xid, mtype, 0) + body
        return [struct.pack(">I", len(rec)) + rec]
    if shm_tx is not None:
        descs = shm_tx.put_blobs(blobs)
        if descs is not None:
            table = b"".join(descs)
            rec_len = _HDR.size + 4 + len(body) + len(table)
            return [struct.pack(">I", rec_len)
                    + _HDR.pack(xid, mtype, FL_SHM)
                    + struct.pack(">I", len(body)) + body + table]
    blob_len = sum(len(b) for b in blobs)
    rec_len = _HDR.size + 4 + len(body) + blob_len
    prefix = (struct.pack(">I", rec_len)
              + _HDR.pack(xid, mtype, FL_BLOBS)
              + struct.pack(">I", len(body)) + body)
    blob_stats["tx_blobs"] += len(blobs)
    blob_stats["tx_bytes"] += blob_len
    return [prefix, *blobs]


# inflation cap: a few-KB zlib bomb must not materialize gigabytes
# pre-auth (zlib ratios reach ~1000:1)
_MAX_INFLATED = 256 << 20


def peek_xid(rec: bytes) -> int:
    """The xid of a framed record, without decoding it — how a
    transport answers a frame whose BODY failed to decode (an FL_SHM
    record on an unarmed lane must still be ANSWERED, or the peer's
    call hangs out its whole deadline)."""
    return _HDR.unpack_from(rec, 0)[0]


def unpack(rec: bytes, shm_rx=None) -> tuple[int, int, Any]:
    xid, mtype, flags = _HDR.unpack_from(rec, 0)
    if mtype == MT_ZLIB:
        import zlib

        d = zlib.decompressobj()
        inner = d.decompress(rec[_HDR.size:], _MAX_INFLATED)
        if d.unconsumed_tail:
            raise WireError("compressed frame exceeds inflation cap")
        if len(inner) >= 4 + _HDR.size and \
                _HDR.unpack_from(inner, 4)[1] == MT_ZLIB:
            raise WireError("nested compression refused")
        return unpack(inner[4:])  # strip the inner length prefix
    mv = memoryview(rec)
    if flags & FL_SHM:
        # shared-memory record: the frame carries body + descriptor
        # table only; payload bytes live in the peer-shared arena
        if shm_rx is None:
            raise ShmDecodeError("shm record without an armed lane")
        (body_len,) = struct.unpack_from(">I", rec, _HDR.size)
        start = _HDR.size + 4
        if start + body_len > len(rec):
            raise WireError("shm record body overruns frame")
        views = shm_rx.views_for(mv[start + body_len:])
        # a list region routes _T_BLOBREF decoding by index — and
        # keeps the decode on the pure-Python codec (the C codec only
        # understands contiguous FL_BLOBS regions)
        payload, _ = decode_value(mv[:start + body_len], start,
                                  [views, 0])
        return xid, mtype, payload
    if flags & FL_BLOBS:
        (body_len,) = struct.unpack_from(">I", rec, _HDR.size)
        start = _HDR.size + 4
        if start + body_len > len(rec):
            raise WireError("blob record body overruns frame")
        blobs = [mv[start + body_len:], 0]
        blob_stats["rx_frames"] += 1
        blob_stats["rx_bytes"] += len(blobs[0])
        payload, _ = _decode_body(mv[:start + body_len], start, blobs)
        return xid, mtype, payload
    payload, _ = _decode_body(mv, _HDR.size)
    return xid, mtype, payload


def pack_z(xid: int, mtype: int, payload: Any,
           min_size: int = 512, level: int = 1) -> bytes:
    """Compressed pack: deflate the whole record when it is worth it
    (small frames ship plain — zlib would grow them).  ``level`` is the
    cdc xlator's compression-level (-1 = zlib default)."""
    import zlib

    plain = pack(xid, mtype, payload)
    if len(plain) < min_size:
        return plain
    body = zlib.compress(plain, level)
    rec = _HDR.pack(xid, MT_ZLIB, 0) + body
    return struct.pack(">I", len(rec)) + rec


async def read_frame(reader) -> bytes:
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    if length > (1 << 30):
        raise WireError(f"frame too large: {length}")
    return await reader.readexactly(length)
