"""Wire codec + framing — the XDR analog.

Reference: rpc/xdr/src/*.x define the wire schema; rpc-lib frames records
over the socket.  Here: a small tagged binary codec for the value tree a
fop carries (ints, bytes, strings, lists, dicts, Iatt, Loc, fd handles,
errors) and length-prefixed frames.  No pickle — only the types below can
cross the wire (same property XDR gives the reference).

Frame: 4-byte big-endian length, then the record.
Record: 8-byte header (u32 xid, u8 mtype, 3 reserved) + body.
"""

from __future__ import annotations

import struct
from typing import Any

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt
from ..core.layer import Loc

MT_CALL = 1
MT_REPLY = 2
MT_ERROR = 3
MT_EVENT = 4  # server -> client notifications (upcall channel analog)
# on-wire compression (the cdc/compress xlator analog): an MT_ZLIB
# record's body is the zlib deflate of a complete inner record
MT_ZLIB = 5

# The RPC peer identity of the request currently being dispatched
# (set per-call by protocol/server, read by brick-side layers that need
# to know WHO is asking — features/upcall's client registry; the
# reference threads this through frame->root->client).
import contextvars as _contextvars  # noqa: E402

CURRENT_CLIENT: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "gftpu_current_client", default=None)

_HDR = struct.Struct(">IBxxx")

# value tags
_T_NONE, _T_TRUE, _T_FALSE = 0, 1, 2
_T_INT, _T_NEGINT, _T_FLOAT = 3, 4, 5
_T_BYTES, _T_STR = 6, 7
_T_LIST, _T_DICT = 8, 9
_T_IATT, _T_LOC, _T_FD, _T_ERR = 10, 11, 12, 13


class WireError(Exception):
    pass


class FdHandle:
    """A remote fd reference (server-side fd table slot) carrying the fd
    identity so the client can reconstruct a local FdObj."""

    __slots__ = ("fdid", "gfid", "path")

    def __init__(self, fdid: int, gfid: bytes = b"", path: str = ""):
        self.fdid = fdid
        self.gfid = gfid
        self.path = path

    def __repr__(self):  # pragma: no cover
        return f"FdHandle({self.fdid})"


def _enc_uint(out: bytearray, n: int) -> None:
    # LEB128-ish varint
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _dec_uint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def encode_value(v: Any, out: bytearray) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        if v >= 0:
            out.append(_T_INT)
            _enc_uint(out, v)
        else:
            out.append(_T_NEGINT)
            _enc_uint(out, -v)
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += struct.pack(">d", v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(_T_BYTES)
        b = bytes(v)
        _enc_uint(out, len(b))
        out += b
    elif isinstance(v, str):
        out.append(_T_STR)
        # surrogateescape: filenames come off the kernel/disk as raw
        # bytes; non-UTF-8 names must round-trip the wire losslessly
        b = v.encode("utf-8", "surrogateescape")
        _enc_uint(out, len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _enc_uint(out, len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _enc_uint(out, len(v))
        for k, val in v.items():
            encode_value(k, out)
            encode_value(val, out)
    elif isinstance(v, Iatt):
        out.append(_T_IATT)
        encode_value([v.gfid, v.ia_type.value, v.mode, v.nlink, v.uid,
                      v.gid, v.size, v.blocks, v.atime, v.mtime, v.ctime,
                      v.rdev, v.blksize], out)
    elif isinstance(v, Loc):
        out.append(_T_LOC)
        encode_value([v.path, v.gfid, v.parent, v.name], out)
    elif isinstance(v, FdHandle):
        out.append(_T_FD)
        encode_value([v.fdid, v.gfid, v.path], out)
    elif isinstance(v, FopError):
        out.append(_T_ERR)
        encode_value([v.err, str(v.args[1]) if len(v.args) > 1 else ""], out)
    else:
        raise WireError(f"unencodable type {type(v).__name__}")


def decode_value(buf: memoryview, pos: int) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        return _dec_uint(buf, pos)
    if tag == _T_NEGINT:
        n, pos = _dec_uint(buf, pos)
        return -n, pos
    if tag == _T_FLOAT:
        return struct.unpack_from(">d", buf, pos)[0], pos + 8
    if tag == _T_BYTES:
        n, pos = _dec_uint(buf, pos)
        return bytes(buf[pos:pos + n]), pos + n
    if tag == _T_STR:
        n, pos = _dec_uint(buf, pos)
        return bytes(buf[pos:pos + n]).decode("utf-8", "surrogateescape"), \
            pos + n
    if tag == _T_LIST:
        n, pos = _dec_uint(buf, pos)
        out = []
        for _ in range(n):
            item, pos = decode_value(buf, pos)
            out.append(item)
        return out, pos
    if tag == _T_DICT:
        n, pos = _dec_uint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = decode_value(buf, pos)
            v, pos = decode_value(buf, pos)
            d[k] = v
        return d, pos
    if tag == _T_IATT:
        vals, pos = decode_value(buf, pos)
        ia = Iatt(gfid=vals[0], ia_type=IAType(vals[1]), mode=vals[2],
                  nlink=vals[3], uid=vals[4], gid=vals[5], size=vals[6],
                  blocks=vals[7], atime=vals[8], mtime=vals[9],
                  ctime=vals[10], rdev=vals[11], blksize=vals[12])
        return ia, pos
    if tag == _T_LOC:
        vals, pos = decode_value(buf, pos)
        return Loc(vals[0], gfid=vals[1], parent=vals[2], name=vals[3]), pos
    if tag == _T_FD:
        vals, pos = decode_value(buf, pos)
        return FdHandle(vals[0], vals[1], vals[2]), pos
    if tag == _T_ERR:
        vals, pos = decode_value(buf, pos)
        return FopError(vals[0], vals[1]), pos
    raise WireError(f"bad tag {tag}")


def pack(xid: int, mtype: int, payload: Any) -> bytes:
    body = bytearray()
    encode_value(payload, body)
    rec = _HDR.pack(xid, mtype) + bytes(body)
    return struct.pack(">I", len(rec)) + rec


# inflation cap: a few-KB zlib bomb must not materialize gigabytes
# pre-auth (zlib ratios reach ~1000:1)
_MAX_INFLATED = 256 << 20


def unpack(rec: bytes) -> tuple[int, int, Any]:
    xid, mtype = _HDR.unpack_from(rec, 0)
    if mtype == MT_ZLIB:
        import zlib

        d = zlib.decompressobj()
        inner = d.decompress(rec[_HDR.size:], _MAX_INFLATED)
        if d.unconsumed_tail:
            raise WireError("compressed frame exceeds inflation cap")
        if len(inner) >= 4 + _HDR.size and \
                _HDR.unpack_from(inner, 4)[1] == MT_ZLIB:
            raise WireError("nested compression refused")
        return unpack(inner[4:])  # strip the inner length prefix
    payload, _ = decode_value(memoryview(rec), _HDR.size)
    return xid, mtype, payload


def pack_z(xid: int, mtype: int, payload: Any,
           min_size: int = 512) -> bytes:
    """Compressed pack: deflate the whole record when it is worth it
    (small frames ship plain — zlib would grow them)."""
    import zlib

    plain = pack(xid, mtype, payload)
    if len(plain) < min_size:
        return plain
    body = zlib.compress(plain, 1)
    rec = _HDR.pack(xid, MT_ZLIB) + body
    return struct.pack(">I", len(rec)) + rec


async def read_frame(reader) -> bytes:
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    if length > (1 << 30):
        raise WireError(f"frame too large: {length}")
    return await reader.readexactly(length)
