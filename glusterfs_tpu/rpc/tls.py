"""Shared TLS context construction for brick transports (the socket.c
ssl_setup_connection analog).  One policy, used by protocol/client,
glusterd's mgmt brick calls, and bitd — so a TLS change lands once."""

from __future__ import annotations

import ssl


def client_context(ca: str = "", cert: str = "",
                   key: str = "") -> ssl.SSLContext:
    """TLS toward a brick: verify against ca when given (bricks are
    addressed by IP, so hostname checks are off), present cert/key when
    the brick requires mutual auth."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    if ca:
        ctx.load_verify_locations(ca)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if cert:
        ctx.load_cert_chain(cert, key or None)
    return ctx


def server_context(cert: str, key: str = "",
                   ca: str = "") -> ssl.SSLContext:
    """TLS listener for a brick; a ca makes client certs mandatory
    (ssl-ca-list semantics)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key or None)
    if ca:
        ctx.load_verify_locations(ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
