"""Same-host shared-memory bulk lane (the RDMA-transport analog).

Reference: rpc/rpc-transport/rdma registers buffers once and ships
only descriptors over the wire while socket.c stays the fallback.
Here the registered buffer is a pair of memfd arenas (one per
direction) exchanged over an AF_UNIX side-channel at SETVOLUME via
SCM_RIGHTS — "the fd mapped" is the same-host proof.  Bulk payload
bytes (readv replies, writev/xorv request data, compound chains,
SGBuf segments) are written ONCE by the producer into its TX arena
and handed to the consumer as memoryviews into the shared mapping;
only a small (seq, offset, length) descriptor table rides the socket
(``wire.FL_SHM`` records).  Control frames, ordering, deadlines, QoS
admission and trace propagation all stay on the existing wire — the
lane substitutes only where blob bytes travel.

Reclamation is an ack watermark realized IN shared memory: the
consumer writes the highest contiguously-released descriptor seq into
its RX arena header; the producer reads it before every allocation
and frees every slot at or below it.  Zero extra wire bytes, zero
extra round trips, and peer death reclaims everything through plain
fd-close semantics (each side's mmap dies with its process).

Fallback is per-frame and total: an arena that cannot hold a frame's
blobs right now (or a dead/corrupt lane) makes THAT frame ship inline
exactly as today — no mode flag, no renegotiation.

Arena layout (both directions identical)::

    [0:4)   magic b"GSHM"
    [4:8)   reserved (zeros)
    [8:16)  u64 BE consumer ack watermark (written by the RECEIVER)
    [16:)   ring data

Descriptors (``DESC``, 20 bytes each): seq u64, absolute arena offset
u64, length u32 — appended to the FL_SHM record in blob order.
"""

from __future__ import annotations

import ctypes
import errno
import mmap
import os
import socket
import struct
import sys
import threading
import weakref
from collections import deque

from ..core import metrics as _metrics
from .wire import ShmDecodeError

MAGIC = b"GSHM"
HDR_SIZE = 16
_WM = struct.Struct(">Q")       # watermark field at offset 8
DESC = struct.Struct(">QQI")    # seq, absolute offset, length
DEFAULT_ARENA = 16 * 1024 * 1024

# hot-path counter store; the unified registry reads it at scrape time
shm_stats = {"tx_bytes": 0, "rx_bytes": 0,
             "tx_frames": 0, "rx_frames": 0}
# why frames/connections fell back to the inline wire, by reason
fallback_stats: dict[str, int] = {}

# every live arena (tx and rx, both ends), for the occupancy gauge and
# the leak audit.  WeakSet: a torn-down lane's arenas age out with GC.
_LIVE_ARENAS: "weakref.WeakSet" = weakref.WeakSet()


def count_fallback(reason: str) -> None:
    fallback_stats[reason] = fallback_stats.get(reason, 0) + 1


def supported() -> bool:
    """Can this build arm the lane at all?  Linux memfd + SCM_RIGHTS
    fd passing (socket.send_fds/recv_fds, py3.9+) are required; any
    miss means the peer simply never advertises / never arms."""
    return (sys.platform.startswith("linux")
            and hasattr(os, "memfd_create")
            and hasattr(socket, "send_fds")
            and hasattr(socket, "recv_fds"))


_boot_id: str | None = None


def boot_id() -> str:
    """This host's boot identity, for the cheap cross-host screen
    (the fd exchange is the real proof; this avoids dialing a
    side-channel that cannot exist on another machine)."""
    global _boot_id
    if _boot_id is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _boot_id = f.read().strip()
        except OSError:
            _boot_id = socket.gethostname()
    return _boot_id


def _create_mm(size: int) -> tuple[mmap.mmap, int]:
    """Mint one arena: anonymous memfd, sized, mapped, header stamped.
    Returns (mapping, fd) — the fd is the capability handed to the
    peer; the creator keeps only the mapping."""
    fd = os.memfd_create("gftpu-shm-arena", os.MFD_CLOEXEC)
    try:
        os.ftruncate(fd, size)
        mm = mmap.mmap(fd, size)
    except BaseException:
        os.close(fd)
        raise
    mm[0:4] = MAGIC
    return mm, fd


def _attach_mm(fd: int) -> tuple[mmap.mmap, int]:
    """Map a received arena fd; the magic check is the handshake's
    integrity screen (a wrong fd must not become a silent data lane)."""
    size = os.fstat(fd).st_size
    if size <= HDR_SIZE:
        raise OSError(errno.EINVAL, "shm arena too small")
    mm = mmap.mmap(fd, size)
    if bytes(mm[0:4]) != MAGIC:
        mm.close()
        raise OSError(errno.EINVAL, "shm arena magic mismatch")
    return mm, size


class ShmTx:
    """Producer half: a contiguous-slot ring allocator over the data
    area.  Slots are freed by the consumer's ack watermark (read from
    the shared header before every allocation); a frame whose blobs
    don't fit RIGHT NOW returns None and ships inline — the ring never
    blocks the wire."""

    role = "tx"

    def __init__(self, mm: mmap.mmap, size: int):
        self.mm = mm
        self.size = size
        self.cap = size - HDR_SIZE
        self.dead = False
        # allocation state, guarded: pack_frames runs on event-pool
        # threads and the loop concurrently
        self._lock = threading.Lock()
        self._allocs: deque = deque()  # (seq, start, end) data-relative
        self._head = 0
        self._used = 0
        self._seq = 0  # last descriptor seq issued
        _LIVE_ARENAS.add(self)

    @classmethod
    def create(cls, size: int) -> tuple["ShmTx", int]:
        mm, fd = _create_mm(size)
        return cls(mm, size), fd

    @classmethod
    def attach(cls, fd: int) -> "ShmTx":
        mm, size = _attach_mm(fd)
        return cls(mm, size)

    def used(self) -> int:
        return self._used

    def _reclaim_locked(self) -> None:
        wm = _WM.unpack_from(self.mm, 8)[0]
        if wm > self._seq:
            # a watermark past anything we issued is corruption (torn
            # write, hostile peer): disarm — inline forever after
            self.dead = True
            count_fallback("corrupt")
            return
        while self._allocs and self._allocs[0][0] <= wm:
            _, s, e = self._allocs.popleft()
            self._used -= e - s

    def _alloc_locked(self, n: int) -> int | None:
        """Contiguous ring allocation (data-relative start), or None.
        Frees happen strictly in seq order (the watermark is
        contiguous), so the oldest allocation's start is the tail."""
        if n > self.cap:
            return None
        if not self._allocs:
            start = self._head = 0
        else:
            tail = self._allocs[0][1]
            head = self._head
            if head >= tail:
                if n <= self.cap - head:
                    start = head
                elif n < tail:
                    start = 0  # wrap; the skipped gap frees with tail
                else:
                    return None
            elif n < tail - head:
                start = head
            else:
                return None
        self._head = start + n
        self._used += n
        return start

    def put_blobs(self, views: list) -> list | None:
        """Copy a frame's blobs into the arena.  Returns the packed
        descriptors (bytes, frame order) or None when the ring cannot
        hold them right now — the caller ships that frame inline (the
        per-frame fallback; nothing is renegotiated)."""
        if self.dead:
            return None
        descs: list = []
        total = 0
        with self._lock:
            self._reclaim_locked()
            if self.dead:
                return None
            head0, used0, seq0 = self._head, self._used, self._seq
            taken = 0
            for v in views:
                n = len(v)
                start = self._alloc_locked(n)
                if start is None:
                    # roll back the whole frame: the seqs were never
                    # shipped, so reusing them keeps the watermark
                    # contiguous
                    for _ in range(taken):
                        self._allocs.pop()
                    self._head, self._used, self._seq = head0, used0, seq0
                    count_fallback("arena-full")
                    return None
                self._seq += 1
                self._allocs.append((self._seq, start, start + n))
                taken += 1
                off = HDR_SIZE + start
                if n:
                    self.mm[off:off + n] = v
                descs.append(DESC.pack(self._seq, off, n))
                total += n
        shm_stats["tx_bytes"] += total
        shm_stats["tx_frames"] += 1
        return descs

    def close(self) -> None:
        self.dead = True
        try:
            self.mm.close()
        except (BufferError, ValueError):  # pragma: no cover
            pass


class ShmRx:
    """Consumer half: resolves descriptor tables into memoryviews that
    point INTO the shared mapping (zero consumer-side copies).  Each
    view's death — GC of the last reference, from any thread — marks
    its seq released; the highest contiguously-released seq is written
    back into the arena header as the producer's ack watermark."""

    role = "rx"

    def __init__(self, mm: mmap.mmap, size: int):
        self.mm = mm
        self.size = size
        self.cap = size - HDR_SIZE
        self._lock = threading.Lock()
        self._released: set = set()
        self._lens: dict[int, int] = {}  # outstanding seq -> length
        self._wm = 0
        self._out_bytes = 0
        self._closed = False
        self._close_pending = False
        _LIVE_ARENAS.add(self)

    @classmethod
    def create(cls, size: int) -> tuple["ShmRx", int]:
        mm, fd = _create_mm(size)
        return cls(mm, size), fd

    @classmethod
    def attach(cls, fd: int) -> "ShmRx":
        mm, size = _attach_mm(fd)
        return cls(mm, size)

    def used(self) -> int:
        return self._out_bytes

    def views_for(self, table) -> list:
        """Resolve one FL_SHM descriptor table.  Raises ShmDecodeError
        on any malformed descriptor — the transport answers that with
        EOPNOTSUPP so the peer downgrades, instead of serving bytes
        from the wrong offset."""
        if len(table) == 0 or len(table) % DESC.size:
            raise ShmDecodeError("malformed shm descriptor table")
        out: list = []
        total = 0
        for i in range(0, len(table), DESC.size):
            seq, off, n = DESC.unpack_from(table, i)
            if off < HDR_SIZE or off + n > self.size:
                raise ShmDecodeError("shm descriptor out of bounds")
            try:
                arr = (ctypes.c_char * n).from_buffer(self.mm, off)
            except (ValueError, BufferError) as e:
                raise ShmDecodeError(f"shm arena unavailable: {e}") \
                    from None
            # release rides GC: fires only when every derived
            # memoryview is gone (the view below, plus anything the
            # fop pipeline sliced from it)
            weakref.finalize(arr, self._release, seq)
            with self._lock:
                self._lens[seq] = n
                self._out_bytes += n
            out.append(memoryview(arr).cast("B"))
            total += n
        shm_stats["rx_bytes"] += total
        shm_stats["rx_frames"] += 1
        return out

    def _release(self, seq: int) -> None:
        # runs on whatever thread dropped the last reference
        with self._lock:
            self._out_bytes -= self._lens.pop(seq, 0)
            self._released.add(seq)
            wm = self._wm
            while wm + 1 in self._released:
                wm += 1
                self._released.discard(wm)
            if wm != self._wm:
                self._wm = wm
                if not self._closed:
                    _WM.pack_into(self.mm, 8, wm)
            if self._close_pending and not self._lens:
                self._close_locked()

    def close(self) -> None:
        """Tear down; deferred while consumer views are still alive
        (closing the mmap under them would be a BufferError — the last
        release completes the close instead)."""
        with self._lock:
            if self._closed:
                return
            if self._lens:
                self._close_pending = True
                return
            self._close_locked()

    def _close_locked(self) -> None:
        self._closed = True
        self._close_pending = False
        try:
            self.mm.close()
        except (BufferError, ValueError):  # pragma: no cover
            pass


def live_mappings() -> int:
    """Arenas whose mapping is still open — the leak audit's measure
    (peer death / teardown must drive this back to the survivor's own
    count; a wedged view would pin an rx arena here forever)."""
    n = 0
    for a in list(_LIVE_ARENAS):
        mm = getattr(a, "mm", None)
        if mm is not None and not mm.closed:
            n += 1
    return n


# -- side-channel (SCM_RIGHTS fd exchange) ------------------------------

def fetch_fds(addr: str, token: str, timeout: float = 5.0) -> list[int]:
    """Client half of the fd exchange: dial the brick's AF_UNIX
    side-channel (abstract namespace when ``addr`` starts with '@'),
    present the one-shot token from the SETVOLUME advert, and receive
    the two arena memfds via SCM_RIGHTS as [c2s_fd, s2c_fd].  Blocking
    — call via asyncio.to_thread."""
    raw: str | bytes = addr
    if addr.startswith("@"):
        raw = b"\0" + addr[1:].encode()
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.settimeout(timeout)
        s.connect(raw)
        s.sendall(token.encode() + b"\n")
        msg, fds, _flags, _addr = socket.recv_fds(s, 16, 2)
        if len(fds) != 2 or not msg.startswith(b"ok"):
            for fd in fds:
                os.close(fd)
            raise OSError(errno.EPROTO, "shm side-channel refused")
        return list(fds)
    finally:
        s.close()


# -- unified registry families ------------------------------------------

def _arena_samples():
    totals: dict[tuple, int] = {}
    for a in list(_LIVE_ARENAS):
        mm = getattr(a, "mm", None)
        if mm is None or mm.closed:
            continue
        used = a.used()
        for state, v in (("used", used), ("free", a.cap - used)):
            totals[(a.role, state)] = totals.get((a.role, state), 0) + v
    return [({"role": r, "state": st}, v)
            for (r, st), v in sorted(totals.items())]


_metrics.REGISTRY.register(
    "gftpu_shm_tx_bytes_total", "counter",
    "payload bytes written into shared-memory arenas by this process",
    lambda: [({}, shm_stats["tx_bytes"])])
_metrics.REGISTRY.register(
    "gftpu_shm_rx_bytes_total", "counter",
    "payload bytes consumed from shared-memory arenas by this process",
    lambda: [({}, shm_stats["rx_bytes"])])
_metrics.REGISTRY.register(
    "gftpu_shm_fallback_total", "counter",
    "frames/connections that fell back to the inline wire, by reason",
    lambda: [({"reason": r}, v)
             for r, v in sorted(fallback_stats.items())])
_metrics.REGISTRY.register(
    "gftpu_shm_arena_bytes", "gauge",
    "shared-memory arena occupancy by role and state",
    _arena_samples)
