"""Transport socket tuning — the socket.c option surface
(rpc/rpc-transport/socket/src/socket.c: keepalive, user-timeout, window
size).  Shared by protocol/client (outbound) and protocol/server
(accepted connections); a 0 value leaves the kernel default alone."""

from __future__ import annotations

import socket


def tune_socket(sock, *, keepalive_time: float = 0,
                keepalive_interval: float = 0, keepalive_count: int = 0,
                user_timeout: float = 0, window_size: int = 0) -> None:
    if sock is None:
        return
    try:
        if keepalive_time > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            if hasattr(socket, "TCP_KEEPIDLE"):
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPIDLE,
                                max(1, int(keepalive_time)))
        if keepalive_interval > 0 and hasattr(socket, "TCP_KEEPINTVL"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPINTVL,
                            max(1, int(keepalive_interval)))
        if keepalive_count > 0 and hasattr(socket, "TCP_KEEPCNT"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPCNT,
                            int(keepalive_count))
        if user_timeout > 0 and hasattr(socket, "TCP_USER_TIMEOUT"):
            # milliseconds (tcp(7)); bounds how long sent-but-unacked
            # data may linger before the connection is declared dead
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_USER_TIMEOUT,
                            int(user_timeout * 1000))
        if window_size > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            int(window_size))
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                            int(window_size))
    except OSError:
        # tuning is advisory: an unsupported knob must never kill the
        # transport (socket.c logs and continues the same way)
        pass
