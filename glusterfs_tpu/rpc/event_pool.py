"""Concurrent event plane: keyed frame-turning worker pools.

Reference: libglusterfs/src/gf-event (event-epoll.c multithreaded epoll,
``server.event-threads`` / ``client.event-threads``) plus the
own-thread transport mode — N threads turn socket events concurrently,
with each socket's events handled by one thread at a time so a
connection's requests are never reordered.

Here the transports are asyncio streams, so the pool does not own the
sockets; it owns the CPU work of *turning a frame*: decode of an
arrived record (``wire.unpack``), encode of an outgoing reply
(``wire.pack_frames`` / ``pack_z``), and whatever payload handling
rides them (zlib inflate/deflate releases the GIL; the native wirec
codec and checksum paths do for their bulk sections).  The asyncio
loops keep doing what they are good at — socket readiness and fop
scheduling — while frame turning overlaps across connections on the
pool.

Ordering invariant (the own-thread analog): jobs submitted under the
same **key** (one key per connection) execute FIFO and never
concurrently; distinct keys proceed in parallel across the workers.
The brick's per-connection read loop additionally awaits each decode
before reading the next frame, so a connection's fops are *dispatched*
in arrival order no matter how many workers exist.

Observability: ``gftpu_event_threads`` / ``gftpu_event_threads_busy``
gauges and a per-worker ``gftpu_event_frames_total`` counter family on
the unified registry (docs/event_threads.md).

Sizing is live: ``resize()`` grows by spawning workers and shrinks by
retiring them as they come off a job — in-flight and queued work is
never dropped (the reconfigure contract the tests pin).
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import queue
import threading
import time
from typing import Any, Callable

from ..core import gflog
from ..core import metrics as _metrics

log = gflog.get_logger("rpc.event")

#: frames below this turn inline on the event loop: a lookup record is
#: ~100 bytes and the thread handoff costs more than the decode (the
#: reference's epoll threads have no such floor because they own the
#: whole socket; here the handoff is explicit, so it must pay for
#: itself).
TURN_MIN = 4096

#: live pools, scraped by the unified registry (weakref: a stopped
#: server's pool ages out with the GC)
_LIVE_POOLS = _metrics.REGISTRY.register_objects(
    "gftpu_event_threads", "gauge",
    "configured frame-turning workers per event pool "
    "(server.event-threads / client.event-threads)",
    lambda p: [({"pool": p.name}, p.size)])
_metrics.REGISTRY.register_objects(
    "gftpu_event_threads_busy", "gauge",
    "event-pool workers currently turning a frame",
    lambda p: [({"pool": p.name}, p.busy)], live=_LIVE_POOLS)
_metrics.REGISTRY.register_objects(
    "gftpu_event_frames_total", "counter",
    "frames turned per event-pool worker",
    lambda p: [({"pool": p.name, "worker": w}, n)
               for w, n in sorted(p.frames_turned.items())],
    live=_LIVE_POOLS)


class _Job:
    __slots__ = ("fn", "args", "loop", "future")

    def __init__(self, fn, args, loop, future):
        self.fn = fn
        self.args = args
        self.loop = loop
        self.future = future


class EventPool:
    """N worker threads turning jobs with per-key FIFO serialization.

    A key (one per connection) owns at most one running job at a time;
    its backlog drains in submission order.  Distinct keys spread over
    the workers.  This is the scheduling shape of the reference's
    own-thread socket dispatch expressed over a shared pool."""

    def __init__(self, threads: int, name: str = "event"):
        self.name = name
        self._lock = threading.Lock()
        # key-id -> deque of queued jobs; a key present in the dict is
        # either running or queued on _runq (never both idle and listed)
        self._keyed: dict[int, collections.deque] = {}
        self._runq: "queue.SimpleQueue" = queue.SimpleQueue()
        self._workers: list[threading.Thread] = []
        self._target = 0
        self._seq = itertools.count(1)
        self.busy = 0
        self.frames_turned: dict[str, int] = {}
        self._shutdown = False
        self.resize(threads)
        _LIVE_POOLS.add(self)

    # -- sizing -----------------------------------------------------------

    @property
    def size(self) -> int:
        return self._target

    @property
    def closed(self) -> bool:
        return self._shutdown

    def resize(self, threads: int) -> None:
        """Grow by spawning, shrink by retiring workers as they come
        off a job — queued/in-flight work is never dropped."""
        threads = max(0, int(threads))
        with self._lock:
            if self._shutdown:
                return
            self._target = threads
            self._workers = [w for w in self._workers if w.is_alive()]
            while len(self._workers) < threads:
                wname = f"{self.name}-evt-{next(self._seq)}"
                t = threading.Thread(target=self._worker_main,
                                     args=(wname,), name=wname,
                                     daemon=True)
                self._workers.append(t)
                t.start()
            excess = len(self._workers) - threads
        # wake exactly the excess workers so they can notice retirement
        # (a worker blocked on an empty queue would otherwise linger)
        for _ in range(max(0, excess)):
            self._runq.put(None)

    def ensure(self, threads: int) -> None:
        """Cheap per-use reconcile: resize only when the configured
        value changed (one int compare on the hot path)."""
        if threads != self._target:
            self.resize(threads)

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._target = 0
            n = len(self._workers)
        for _ in range(n):
            self._runq.put(None)

    # -- submission -------------------------------------------------------

    def submit(self, key: Any, fn: Callable, *args) -> "asyncio.Future":
        """Schedule ``fn(*args)`` on the pool, serialized FIFO against
        other jobs with the same key.  Returns an asyncio Future
        resolved on the submitting loop."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        job = _Job(fn, args, loop, fut)
        kid = id(key)
        with self._lock:
            if self._shutdown or self._target <= 0:
                pending = None  # turn inline below, outside the lock
            else:
                pending = self._keyed.get(kid)
                if pending is None:
                    # key idle: claim it and enqueue for a worker
                    self._keyed[kid] = collections.deque()
                    self._runq.put((kid, job))
                else:
                    pending.append(job)
                return fut
        # inline fallback (pool disabled mid-flight): still answer
        try:
            fut.set_result(fn(*args))
        except BaseException as e:  # noqa: BLE001 - mirrored to caller
            fut.set_exception(e)
        return fut

    async def turn(self, key: Any, fn: Callable, *args) -> Any:
        """Await ``fn(*args)`` turned on the pool (or inline when the
        pool is sized to zero — the event-threads=0 escape hatch)."""
        if self._target <= 0:
            return fn(*args)
        return await self.submit(key, fn, *args)

    # -- worker -----------------------------------------------------------

    def _worker_main(self, wname: str) -> None:
        self.frames_turned.setdefault(wname, 0)
        while True:
            item = self._runq.get()
            if item is None:
                with self._lock:
                    alive = [w for w in self._workers if w.is_alive()]
                    over = self._shutdown or len(alive) > self._target
                    # a retirement that would leave queued keyed work
                    # with NO surviving worker (target 0 / shutdown)
                    # must drain first: an orphaned job's future would
                    # never resolve and its connection would wedge.
                    # target > 0 always leaves >= 1 survivor (resize
                    # posts exactly `excess` tokens).
                    drain_first = over and self._target <= 0 \
                        and bool(self._keyed)
                    if over and not drain_first:
                        me = threading.current_thread()
                        self._workers = [w for w in alive if w is not me]
                        return
                if drain_first:
                    # repost the token and serve the backlog first
                    self._runq.put(None)
                    time.sleep(0.001)
                continue  # spurious wake (resize race): keep serving
            kid, job = item
            with self._lock:
                self.busy += 1
            try:
                result, exc = job.fn(*job.args), None
            except BaseException as e:  # noqa: BLE001 - mirrored
                result, exc = None, e
            finally:
                with self._lock:
                    self.busy -= 1
                    self.frames_turned[wname] = \
                        self.frames_turned.get(wname, 0) + 1
            self._resolve(job, result, exc)
            # hand the key's NEXT job back through the run queue (not
            # drained inline): FIFO per key — the per-connection
            # ordering invariant — with round-robin fairness across
            # keys.  At most one job per key is ever on the run queue,
            # which is what makes same-key jobs mutually exclusive.
            with self._lock:
                backlog = self._keyed.get(kid)
                if backlog:
                    self._runq.put((kid, backlog.popleft()))
                else:
                    self._keyed.pop(kid, None)

    @staticmethod
    def _resolve(job: _Job, result, exc) -> None:
        def done():
            if job.future.cancelled():
                return
            if exc is not None:
                job.future.set_exception(exc)
            else:
                job.future.set_result(result)
        try:
            job.loop.call_soon_threadsafe(done)
        except RuntimeError:
            pass  # submitting loop closed mid-turn (teardown race)

    def dump(self) -> dict:
        return {"threads": self._target,
                "busy": self.busy,
                "keys_active": len(self._keyed),
                "frames_turned": dict(self.frames_turned)}


# -- the shared client-side pool (client.event-threads) -------------------
#
# The reference sizes event threads PER PROCESS (one gf-event pool per
# glusterfs process, every transport shares it); protocol/client mirrors
# that: all client layers in a process share one pool, created on first
# use and sized to the largest request seen (reconfigure resizes it
# directly — the operator's latest `volume set` wins, as it does for the
# reference's process-wide knob).

_client_pool: EventPool | None = None
_client_pool_lock = threading.Lock()


def client_pool(threads: int) -> EventPool | None:
    """The process-wide reply-turning pool, grown to ``threads`` (never
    implicitly shrunk — several graphs share it).  ``threads <= 0``
    returns the existing pool for INTROSPECTION only (callers wanting
    inline decode must gate on their own option, not on this return —
    a 0-configured layer must not ride a pool another graph grew).
    Lock-free when the pool is already big enough: this runs per large
    frame on every connection's receive path."""
    global _client_pool
    pool = _client_pool
    if threads <= 0:
        return pool if pool is not None and pool.size > 0 else None
    if pool is not None and pool.size >= threads:
        return pool  # hot path: no lock, no resize
    with _client_pool_lock:
        if _client_pool is None:
            _client_pool = EventPool(threads, name="client")
        elif _client_pool.size < threads:
            _client_pool.resize(threads)
    return _client_pool


def client_pool_resize(threads: int) -> None:
    """Explicit resize (live reconfigure of client.event-threads):
    unlike the connect-time max-wins growth, the operator's latest
    value applies exactly — including shrink."""
    global _client_pool
    with _client_pool_lock:
        if _client_pool is None:
            if threads > 0:
                _client_pool = EventPool(threads, name="client")
        else:
            _client_pool.resize(threads)


__all__ = ["EventPool", "TURN_MIN", "client_pool", "client_pool_resize"]
