"""Compound fops: fused request chains on the wire.

Reference: the GF_FOP_COMPOUND machinery (glusterfs-fops.h compound
entries; afr/ec used it to fuse xattrop+writev waves before it was
retired upstream in favor of xdata piggybacks).  This build keeps the
xdata piggybacks (lock-on-create, pre-xattrop) AND revives the general
mechanism, because the smallfile budget is dominated by serialized RPC
waves: a create+writev+flush+release of one 4 KiB file costs ~4 round
trips as singles and exactly one as a chain.

A chain is an ordered list of links ``(fop_name, args, kwargs)``.
Links may reference the fd produced by an EARLIER link through
:class:`FdRef` (create->writev fd plumbing); on the wire the reference
travels as ``{"__fd_link__": index}``.  Execution is strictly in order
with short-circuit-on-first-error; the result is a REPLY VECTOR that
maps 1:1 onto the links:

    ["ok",   value]   — link executed, value is its return
    ["err",  FopError]— link failed; every later link is skipped
    ["skip", None]    — not executed (an earlier link failed)

The vector never raises by itself — callers that want plain values use
:func:`unwrap`, which raises the first error.  Two invariants keep fd
lifecycle airtight:

* a failed chain releases every fd it created itself (no orphan
  fd-table entries or OS handles from half-applied chains), and the
  surviving "ok" entries are stripped of those fds;
* a ``release`` link may only target an :class:`FdRef` (an fd created
  by this same chain) — releasing a caller-owned fd mid-chain would
  race the caller's own view of it.

Graph semantics: :meth:`Layer.compound` forwards a chain INTACT to its
first child only when the layer overrides none of the chain's fops
(checked against the generated default-passthrough methods), and
otherwise DECOMPOSES it — each link runs through the layer's own fop
methods, so gating/caching/transaction layers keep their exact
semantics at the cost of fusion from that point down.  Layers whose
per-fop behavior is cheap to replay (io-stats accounting, md-cache
invalidation, write-behind draining) override ``compound`` to forward
the chain and replay that behavior around it, which is what carries a
chain from the mount entry points all the way onto one wire frame.
"""

from __future__ import annotations

import errno
from typing import Any

from ..core.fops import Fop, FopError
from ..core.layer import FdObj

#: abuse bound: one frame must not smuggle an unbounded work queue past
#: the server's outstanding-rpc accounting (a chain occupies ONE slot)
MAX_LINKS = 64

#: wire spelling of an FdRef (survives the tagged codec as a dict)
FD_LINK_KEY = "__fd_link__"

#: links whose results can carry a brand-new fd (create returns
#: (fd, iatt); open/opendir return the fd itself)
FD_PRODUCERS = ("create", "open", "opendir")

_FOP_NAMES = {f.value for f in Fop}
#: release is not a wire fop but is legal as a chain tail so a one-shot
#: create+writev+flush+release never registers a client-visible fd
ALLOWED = _FOP_NAMES | {"release"}


class FdRef:
    """Placeholder for the fd produced by link ``index`` of this chain."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = int(index)

    def __repr__(self) -> str:  # pragma: no cover
        return f"FdRef({self.index})"


class ChainError(FopError):
    """A malformed chain (caller bug, not a storage condition)."""

    def __init__(self, msg: str):
        super().__init__(errno.EINVAL, msg)


def _is_ref(v: Any) -> FdRef | None:
    if isinstance(v, FdRef):
        return v
    if isinstance(v, dict) and len(v) == 1 and FD_LINK_KEY in v:
        return FdRef(v[FD_LINK_KEY])
    return None


def validate(links: Any) -> list[tuple[str, tuple, dict]]:
    """Normalize + validate a chain: wire-form links (lists, dict fd
    markers) become ``(fop, args, kwargs)`` tuples with FdRef objects;
    forward references, unknown fops, nested compounds and non-FdRef
    release targets are refused up front."""
    if not isinstance(links, (list, tuple)) or not links:
        raise ChainError("compound chain must be a non-empty list")
    if len(links) > MAX_LINKS:
        raise ChainError(f"compound chain exceeds {MAX_LINKS} links")
    out: list[tuple[str, tuple, dict]] = []
    for i, raw in enumerate(links):
        if not isinstance(raw, (list, tuple)) or len(raw) < 2:
            raise ChainError(f"link {i}: not a [fop, args, kwargs] triple")
        fop = raw[0]
        args = tuple(raw[1])
        kwargs = dict(raw[2] or {}) if len(raw) > 2 and raw[2] else {}
        if fop not in ALLOWED:
            raise ChainError(f"link {i}: unknown fop {fop!r}")
        if fop == Fop.COMPOUND.value:
            raise ChainError("nested compound chains are refused")
        for v in list(args) + list(kwargs.values()):
            ref = _is_ref(v)
            if ref is not None and not 0 <= ref.index < i:
                raise ChainError(
                    f"link {i}: fd reference to link {ref.index} is not "
                    f"an earlier link")
        if fop == "release" and _is_ref(args[0] if args else None) is None:
            raise ChainError(
                f"link {i}: release may only target an in-chain FdRef")
        out.append((fop, args, kwargs))
    return out


def fd_of(result: Any) -> FdObj | None:
    """The fd carried by a link result ((fd, iatt) from create, the fd
    itself from open/opendir)."""
    if isinstance(result, FdObj):
        return result
    if isinstance(result, (tuple, list)):
        for item in result:
            if isinstance(item, FdObj):
                return item
    return None


def _subst(value: Any, results: list) -> Any:
    ref = _is_ref(value)
    if ref is not None:
        fd = fd_of(results[ref.index]) if ref.index < len(results) else None
        if fd is None:
            raise ChainError(
                f"fd reference to link {ref.index}, which produced no fd")
        return fd
    if isinstance(value, list):
        return [_subst(v, results) for v in value]
    if isinstance(value, dict) and FD_LINK_KEY not in value:
        return {k: _subst(v, results) for k, v in value.items()}
    return value


def _strip_fds(value: Any, dead: set[int]) -> Any:
    """Replace released/cleaned-up FdObjs in a reply value with None —
    a handle the chain already closed must never reach the caller."""
    if isinstance(value, FdObj) and id(value) in dead:
        return None
    if isinstance(value, (tuple, list)):
        return [_strip_fds(v, dead) for v in value]
    return value


def first_error(replies: list) -> FopError | None:
    for entry in replies:
        if entry[0] == "err":
            return entry[1]
    return None


def unwrap(replies: list) -> list:
    """Reply vector -> plain per-link values, raising the first error."""
    err = first_error(replies)
    if err is not None:
        raise err
    return [entry[1] for entry in replies]


async def decompose(layer, links, xdata: dict | None = None) -> list:
    """Execute a chain link-by-link through ``layer``'s own fop methods
    (the always-correct path: every layer from here down sees ordinary
    fops).  Returns the reply vector; never raises for per-link
    failures.  On a mid-chain error, fds created by earlier links are
    released through the layer so the short-circuit leaves no orphan
    handle anywhere below.

    ``xdata`` is CHAIN-scoped: it rides the frame to wherever the
    chain executes (the client ships it, the server hands it to the
    brick graph) but is never merged into the links — per-link xdata
    belongs in each link's own kwargs.  It exists for chain-level
    piggybacks (the reference's compound dict_t)."""
    links = validate(links)
    results: list = []
    replies: list = []
    error: FopError | None = None
    chain_fds: list[FdObj] = []   # fds this chain itself created
    dead: set[int] = set()        # ids of fds already released
    for fop, args, kwargs in links:
        if error is not None:
            replies.append(["skip", None])
            continue
        try:
            rargs = tuple(_subst(a, results) for a in args)
            rkw = {k: _subst(v, results) for k, v in kwargs.items()}
            if fop == "release":
                fd = rargs[0]
                await layer.release(fd)
                dead.add(id(fd))
                results.append(None)
                replies.append(["ok", None])
                continue
            ret = await getattr(layer, fop)(*rargs, **rkw)
            if fop in FD_PRODUCERS:
                fd = fd_of(ret)
                if fd is not None:
                    chain_fds.append(fd)
            results.append(ret)
            replies.append(["ok", ret])
        except FopError as e:
            error = e
            results.append(None)
            replies.append(["err", e])
        except Exception as e:  # noqa: BLE001 - keep the vector shape
            error = FopError(errno.EIO, f"compound link {fop}: {e!r}")
            results.append(None)
            replies.append(["err", error])
    if error is not None:
        # short-circuit cleanup: close every fd the chain minted
        for fd in chain_fds:
            if id(fd) in dead:
                continue
            dead.add(id(fd))
            try:
                await layer.release(fd)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
    if dead:
        replies = [[st, _strip_fds(val, dead)] if st == "ok" else [st, val]
                   for st, val in replies]
    return replies


#: the write-class links whose forwarded execution must still run a
#: caching layer's invalidation (shared by quick-read/io-cache replay)
WRITE_INVALIDATING = ("writev", "ftruncate", "truncate", "discard",
                      "zerofill", "fallocate")


def replay_write_invalidation(links, replies, invalidate) -> None:
    """Run ``invalidate(gfid)`` for every object a forwarded write link
    touched — the per-fop override logic the intact chain skipped.
    One shared copy so the fop list cannot drift between layers."""
    for (fop, args, _kw), (st, val) in zip(links, replies):
        if fop not in WRITE_INVALIDATING:
            continue
        for a in args:
            if isinstance(a, FdObj) and a.gfid:
                invalidate(a.gfid)
        if st == "ok" and hasattr(val, "gfid"):
            invalidate(val.gfid)


def is_default_fop(cls: type, name: str) -> bool:
    """True when ``cls`` serves ``name`` with the generated default
    passthrough (it neither defines nor inherits a real override)."""
    meth = getattr(cls, name, None)
    if meth is None:
        return False
    inner = getattr(meth, "__wrapped__", meth)
    return bool(getattr(inner, "_gf_default", False))


def transparent_for(cls: type, links) -> bool:
    """A layer may forward a chain intact iff it adds no behavior to any
    fop the chain contains.  ``release`` links are exempt: they only
    ever target fds the chain itself created BELOW this layer, which
    therefore never acquired per-layer context here."""
    for raw in links:
        fop = raw[0]
        if fop == "release":
            continue
        if not is_default_fop(cls, fop):
            return False
    return True
