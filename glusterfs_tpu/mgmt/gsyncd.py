"""Geo-replication monitor + per-brick workers — the gsyncd analog.

Reference: geo-replication/syncdaemon (monitor.py:63-85 Monitor spawns
and supervises one gsyncd worker PER BRICK, respawning on death;
monitor.py:299 distribute() maps bricks to workers with Active/Passive
election inside each replica set; primary.py:90-135 crawl/changelog
consumption; resource.py rsync/tar transport).

TPU-build shape: one monitor process per node per (primary volume ->
secondary volume) link.  The monitor runs one worker per LOCAL brick
of the primary volume; each worker tails ITS brick's journal segments
by (segment, offset) cursor (features/changelog.py) with its own
persisted state, coalesces the batch (one data-sync per path — the
copy reads the CURRENT primary state through the mounted client, so
intermediate writes are free), replays entry ops in order, and
persists cursors only after a fully-applied batch — replay is
idempotent, so re-applying after a crash converges.

Supervision model (monitor.py:63-85): a worker that dies is respawned
with exponential backoff and its status surfaces per worker — one
wedged brick's worker never stalls the other bricks' replication.
Election (monitor.py:299): replica/disperse bricks journal the same
logical ops, so only ONE worker per subvolume group is Active; the
monitor polls brick liveness through glusterd and fails over to a
peer brick's worker when the active brick dies.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import sys

from ..core.fops import FopError
from ..core import gflog

log = gflog.get_logger("gsyncd")

COPY_WINDOW = 1 << 20


class GeoRepWorker:
    def __init__(self, primary, secondary, changelog_dirs: list[str],
                 state_path: str, interval: float = 5.0,
                 floor=None):
        self.primary = primary      # mounted Client on the primary vol
        self.secondary = secondary  # mounted Client on the secondary vol
        self.dirs = changelog_dirs
        self.state_path = state_path
        self.interval = interval
        # failover fast-forward: records at or before the session's
        # synced_through AT PROMOTION TIME were already replayed by a
        # peer brick's worker (the reference tracks the equivalent
        # stime xattr) — skip them instead of re-replaying a whole
        # journal history.  Snapshotted ONCE: a live floor would race
        # the idle-tick synced_through stamp against records whose
        # journal line lands after the scan that stamped it, silently
        # dropping them from the active worker's own stream.
        self._floor_ts = float(floor() if callable(floor) else 0.0)
        self.state = self._load_state()
        self.synced = 0
        self.batches = 0
        self._task: asyncio.Task | None = None
        # supervised workers (under GeoRepMonitor) die on persistent
        # failure and get respawned with backoff; the legacy standalone
        # worker has NO supervisor, so it must retry forever instead
        self.supervised = False

    # -- checkpoint ---------------------------------------------------------

    def _load_state(self) -> dict:
        try:
            with open(self.state_path) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return {"cursors": {}, "last_ts": 0.0}

    def _save_state(self) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
        os.replace(tmp, self.state_path)

    # -- journal tailing ----------------------------------------------------

    def _collect_new(self) -> tuple[list[dict], dict]:
        """Read records past each brick's (segment, offset) cursor.
        Returns (records, advanced-cursor-proposal) WITHOUT touching
        self.state — cursors move only after the batch fully applies,
        so a failed replay is re-read next tick (replay is idempotent)."""
        out: list[dict] = []
        proposal = {d: dict(c)
                    for d, c in self.state["cursors"].items()}
        for d in self.dirs:
            cur = proposal.setdefault(d, {})
            try:
                segs = sorted(int(n.rsplit(".", 1)[1])
                              for n in os.listdir(d)
                              if n.startswith("CHANGELOG."))
            except OSError:
                continue
            for seq in segs:
                if seq < cur.get("segment", 0):
                    continue
                off = cur.get("offset", 0) \
                    if seq == cur.get("segment", 0) else 0
                path = os.path.join(d, f"CHANGELOG.{seq}")
                try:
                    with open(path) as f:
                        f.seek(off)
                        data = f.read()
                except OSError:
                    continue
                # consume only complete lines (a record may be mid-write)
                complete = data.rfind("\n") + 1
                floor_ts = self._floor_ts
                for line in data[:complete].splitlines():
                    try:
                        r = json.loads(line)
                    except ValueError:
                        continue
                    if r.get("ts", 0) > floor_ts:
                        out.append(r)
                cur["segment"] = seq
                cur["offset"] = off + complete
        out.sort(key=lambda r: r.get("ts", 0))
        return out, proposal

    # -- replay -------------------------------------------------------------

    async def _copy_file(self, path: str, strict: bool = False) -> bool:
        """Sync the CURRENT primary state of path to the secondary.

        ``strict`` is the initial-crawl mode: pre-session data has no
        journal records, so a transient primary-side failure (ENOTCONN,
        EIO) must re-raise and retry the walk — only a genuinely
        vanished entry may be skipped.  Journal replay passes False:
        there a vanished source is benign because a later record
        covers the final state."""
        _gone = (errno.ENOENT, errno.ESTALE)
        try:
            ia = await self.primary.stat(path)
        except FopError as e:
            if strict and e.err not in _gone:
                raise
            return False  # vanished since the record; a later E handles it
        try:
            f_in = await self.primary.open(path)
        except FopError as e:
            if strict and e.err not in _gone:
                raise
            return False  # vanished on primary: benign
        try:
            try:
                f_out = await self.secondary.create(path)
            except FopError as e:
                if e.err != errno.EEXIST:
                    raise  # secondary trouble is a REAL failure: retry batch
                f_out = await self.secondary.open(path, os.O_RDWR)
            try:
                off = 0
                while off < ia.size:
                    chunk = await f_in.read(
                        min(COPY_WINDOW, ia.size - off), off)
                    if not chunk:
                        break
                    await f_out.write(chunk, off)
                    off += len(chunk)
                await self.secondary.truncate(path, ia.size)
            finally:
                await f_out.close()
        finally:
            await f_in.close()
        return True

    async def _ensure_parents(self, path: str) -> None:
        parts = [p for p in path.strip("/").split("/")[:-1] if p]
        cur = ""
        for p in parts:
            cur += "/" + p
            try:
                await self.secondary.mkdir(cur)
            except FopError:
                pass

    async def _replay(self, rec: dict) -> bool:
        """Apply one record to the secondary; False = hard failure (the
        caller must NOT advance the cursors; the batch re-applies next
        tick)."""
        op, path = rec.get("op", ""), rec.get("path", "")
        if not path:
            return True
        try:
            if op in ("unlink",):
                try:
                    await self.secondary.unlink(path)
                except FopError as e:
                    if e.err != errno.ENOENT:
                        raise
            elif op == "rmdir":
                try:
                    await self.secondary.rmdir(path)
                except FopError as e:
                    if e.err not in (errno.ENOENT, errno.ENOTEMPTY):
                        raise
            elif op == "mkdir":
                await self._ensure_parents(path)
                try:
                    await self.secondary.mkdir(path)
                except FopError as e:
                    if e.err != errno.EEXIST:
                        raise
            elif op == "rename":
                dst = rec.get("path2", "")
                if dst:
                    await self._ensure_parents(dst)
                    try:
                        await self.secondary.rename(path, dst)
                    except FopError:
                        # source absent on secondary: materialize dst
                        await self._copy_file(dst)
                    try:
                        await self.secondary.unlink(path)
                    except FopError:
                        pass
            elif op == "link":
                dst = rec.get("path2", "")
                if dst:
                    await self._ensure_parents(dst)
                    try:
                        await self.secondary.link(path, dst)
                    except FopError:
                        # source missing on secondary: materialize dst
                        await self._copy_file(dst)
            elif op == "symlink":
                try:
                    target = await self.primary.readlink(path)
                    await self._ensure_parents(path)
                    await self.secondary.symlink(target, path)
                except FopError:
                    pass
            elif rec.get("type") in ("D", "E"):
                # create/write/truncate/...: sync current file state
                await self._ensure_parents(path)
                if await self._copy_file(path):
                    self.synced += 1
            elif rec.get("type") == "M":
                try:
                    ia = await self.primary.stat(path)
                    await self.secondary.setattr(
                        path, {"mode": ia.mode & 0o7777})
                except FopError:
                    pass
        except FopError as e:
            log.warning(1, "replay %s %s failed: %s", op, path, e)
            return False
        return True

    _SYNC_OPS = {"create", "icreate", "put"}

    @classmethod
    def _is_sync(cls, r: dict) -> bool:
        """Records whose replay is 'copy current file state'."""
        return r.get("type") == "D" or r.get("op") in cls._SYNC_OPS

    @classmethod
    def _coalesce(cls, recs: list[dict]) -> list[dict]:
        """One data-sync per path per batch: create + N writev records
        collapse to the LAST such record (the copy reads the current
        primary state anyway)."""
        last: dict[str, int] = {}
        for i, r in enumerate(recs):
            if cls._is_sync(r):
                last[r.get("path", "")] = i
        return [r for i, r in enumerate(recs)
                if not cls._is_sync(r) or last.get(r.get("path", "")) == i]

    async def process_once(self) -> int:
        import time as _t

        # stamp BEFORE the scan: a record journaled between scan and
        # stamp must not fall inside a "synced through" window
        scan_started = _t.time()
        recs, proposal = self._collect_new()
        if not recs:
            # caught up THROUGH the scan start: checkpoint completion
            # must not wait for new traffic on an idle session
            # (gsyncdstatus checkpoint semantics)
            self.state["synced_through"] = scan_started
            self._save_state()
            return 0
        batch = self._coalesce(recs)
        ok = True
        for rec in batch:
            ok = await self._replay(rec) and ok
        if not ok:
            # leave the cursors where they were: the whole batch is
            # re-read and re-applied (idempotently) next tick
            return 0
        self.state["cursors"] = proposal
        self.state["last_ts"] = recs[-1].get("ts", 0)
        self.state["synced_through"] = self.state["last_ts"]
        self.batches += 1
        self._save_state()
        self._prune_consumed()
        return len(batch)

    def _prune_consumed(self) -> None:
        """Delete journal segments fully behind the persisted cursor —
        the consumed changelog would otherwise grow without bound (the
        reference archives processed changelogs the same way)."""
        for d, cur in self.state["cursors"].items():
            current = cur.get("segment", 0)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                if not n.startswith("CHANGELOG."):
                    continue
                try:
                    seq = int(n.rsplit(".", 1)[1])
                except ValueError:
                    continue
                if seq < current:
                    try:
                        os.unlink(os.path.join(d, n))
                    except OSError:
                        pass

    async def initial_crawl(self) -> int:
        """Hybrid/xsync crawl (reference primary.py XCrawlMixin): data
        written BEFORE the session existed has no journal records —
        walk the primary namespace once and materialize everything on
        the secondary, then hand over to changelog tailing.  Runs
        before the first journal batch; idempotent (copy reads current
        primary state), so a crash mid-crawl just re-walks."""
        from ..core.iatt import IAType

        synced = 0

        # pre-session data has NO journal records, so a transiently
        # failing secondary op here (ENOTCONN, EIO) loses the entry
        # forever if swallowed — only the benign races (entry already
        # there / vanished under live churn) may pass; everything else
        # re-raises so run() retries the walk, same as the listdir path.
        _benign = (errno.EEXIST, errno.ENOENT, errno.ESTALE)

        async def meta(child: str, ia) -> None:
            # pre-session data has no 'M' journal records: carry
            # mode/ownership in the crawl itself
            try:
                await self.secondary.setattr(
                    child, {"mode": ia.mode & 0o7777,
                            "uid": ia.uid, "gid": ia.gid})
            except FopError as e:
                if e.err not in _benign:
                    raise

        async def walk(path: str) -> int:
            n = 0
            try:
                entries = await self.primary.listdir_with_stat(path)
            except FopError as e:
                if e.err in (errno.ENOENT, errno.ESTALE):
                    # directory vanished mid-crawl (live churn): its
                    # removal IS journaled, so skipping is safe
                    return 0
                # transient trouble (ENOTCONN, EIO): pre-session data
                # has NO journal records — finishing the crawl now
                # would mark initial_done with this subtree missing
                # forever; re-raise so run() retries the whole walk
                raise
            for name, ia in entries:
                child = path.rstrip("/") + "/" + name
                if ia is not None and ia.is_dir():
                    try:
                        await self.secondary.mkdir(child)
                    except FopError as e:
                        if e.err not in _benign:
                            raise
                    await meta(child, ia)
                    n += await walk(child)
                elif ia is not None and ia.ia_type is IAType.LNK:
                    # symlinks must stay symlinks (journal replay's
                    # op=='symlink' path does the same)
                    try:
                        target = await self.primary.readlink(child)
                        await self.secondary.symlink(target, child)
                        n += 1
                    except FopError as e:
                        if e.err not in _benign:
                            raise
                else:
                    if await self._copy_file(child, strict=True):
                        if ia is not None:
                            await meta(child, ia)
                        n += 1
            return n

        synced = await walk("/")
        self.state["initial_done"] = True
        self._save_state()
        log.info(3, "initial crawl synced %d files", synced)
        return synced

    async def run(self) -> None:
        failures = 0
        while not self.state.get("initial_done"):
            try:
                await self.initial_crawl()
            except Exception as e:
                log.error(4, "initial crawl failed (will retry): %r", e)
                await asyncio.sleep(self.interval)
        while True:
            try:
                await self.process_once()
                failures = 0
            except Exception as e:  # a bad batch must not kill the link
                log.error(2, "gsyncd batch failed: %r", e)
                failures += 1
                if self.supervised and failures >= 3:
                    # persistently failing worker: die and let the
                    # monitor respawn it with backoff (the reference
                    # worker exits on persistent faults the same way,
                    # monitor.py respawn loop); unsupervised legacy
                    # workers have nobody to respawn them — retry on
                    raise
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def status(self) -> dict:
        return {"batches": self.batches, "files_synced": self.synced,
                "last_ts": self.state.get("last_ts", 0)}


class GeoRepMonitor:
    """Per-brick worker supervision + Active/Passive election
    (monitor.py:63-85 spawn/respawn, monitor.py:299 distribute()).

    One worker per local brick of the primary volume, each with its own
    journal cursors and state file.  Replica/disperse bricks journal
    the same logical ops, so per subvolume group exactly one brick's
    worker is ACTIVE (lowest-indexed brick that is online, cluster
    wide); the rest stay Passive.  The monitor polls brick liveness
    through glusterd every tick — when the active brick dies, the next
    online brick's worker takes over, fast-forwarded past everything
    the session already replayed (``floor``).  A worker that exits is
    respawned with exponential backoff and reported Faulty meanwhile.
    """

    BACKOFF0 = 1.0
    BACKOFF_MAX = 30.0

    def __init__(self, primary, secondary, *, glusterd: tuple[str, int],
                 volume: str, bricks: list[dict], group_size: int,
                 state_dir: str, session_state: str,
                 interval: float = 5.0, statusfile: str = ""):
        self.primary = primary
        self.secondary = secondary
        self.glusterd = glusterd
        self.volume = volume
        self.bricks = bricks  # [{name, path, index}] local bricks
        self.group_size = max(1, group_size)
        self.state_dir = state_dir
        self.session_state = session_state
        self.interval = interval
        self.statusfile = statusfile
        self.workers: dict[str, GeoRepWorker] = {}
        self._tasks: dict[str, asyncio.Task] = {}
        self._backoff: dict[str, float] = {}
        self._down_until: dict[str, float] = {}
        self.status: dict[str, dict] = {
            b["name"]: {"state": "Initializing", "restarts": 0}
            for b in bricks}
        self.state = self._load_session()

    # -- session-level state (the gsync-<vol>.state file the status op
    # reads): initial_done + aggregated synced_through ------------------

    def _load_session(self) -> dict:
        try:
            with open(self.session_state) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return {"initial_done": False, "synced_through": 0.0,
                    "last_ts": 0.0}

    def _save_session(self) -> None:
        tmp = self.session_state + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
        os.replace(tmp, self.session_state)

    def floor(self) -> float:
        return self.state.get("synced_through", 0.0)

    # -- liveness -------------------------------------------------------

    async def _volume_bricks(self) -> list[tuple[str, bool]] | None:
        """EVERY brick of the primary volume in index order with its
        online flag, or None when glusterd is unreachable (keep the
        current election).  The election must run over the full
        cluster-wide brick list: a replica group spanning nodes has ONE
        active worker total, not one per node."""
        from .glusterd import MgmtClient

        try:
            async with MgmtClient(*self.glusterd) as c:
                st = await asyncio.wait_for(
                    c.call("volume-status", name=self.volume), 5)
            return [(b["name"], bool(b.get("online")))
                    for b in st.get("bricks", ())]
        except Exception:
            return None

    def _elect(self, allbricks: list[tuple[str, bool]]) -> set[str]:
        """Active brick names cluster-wide: per subvolume group, the
        lowest-indexed ONLINE brick (monitor.py:299 distribute; the
        reference breaks ties by node-uuid — volume brick order is
        already total here).  This monitor then starts only the
        winners that are LOCAL; peers' monitors reach the same answer
        from the same volume-status."""
        active: set[str] = set()
        for g0 in range(0, len(allbricks), self.group_size):
            group = allbricks[g0:g0 + self.group_size]
            alive = [name for name, online in group if online]
            if alive:
                active.add(alive[0])
        return active

    # -- worker lifecycle ----------------------------------------------

    def _worker_for(self, brick: dict) -> GeoRepWorker:
        w = self.workers.get(brick["name"])
        if w is None:
            d = os.path.join(brick["path"], ".glusterfs_tpu",
                             "changelog")
            sp = os.path.join(self.state_dir,
                              f"worker-{brick['name']}.state")
            w = GeoRepWorker(self.primary, self.secondary, [d], sp,
                             self.interval, floor=self.floor)
            w.supervised = True  # monitor respawns on death
            # the monitor ran (or will run) the volume-level initial
            # crawl; per-brick workers only tail journals
            w.state["initial_done"] = True
            self.workers[brick["name"]] = w
        return w

    def _start(self, brick: dict) -> None:
        name = brick["name"]
        t = self._tasks.get(name)
        if t is not None and not t.done():
            return
        now = asyncio.get_running_loop().time()
        if now < self._down_until.get(name, 0):
            return  # still backing off
        w = self._worker_for(brick)
        task = asyncio.get_running_loop().create_task(w.run())

        def died(t: asyncio.Task, _name=name) -> None:
            if t.cancelled():
                return
            st = self.status[_name]
            st["state"] = "Faulty"
            st["restarts"] += 1
            back = min(self._backoff.get(_name, self.BACKOFF0) * 2,
                       self.BACKOFF_MAX)
            self._backoff[_name] = back
            self._down_until[_name] = \
                asyncio.get_running_loop().time() + back
            exc = t.exception()
            log.error(5, "worker %s died (%r); respawn in %.1fs",
                      _name, exc, back)

        task.add_done_callback(died)
        self._tasks[name] = task
        self.status[name]["state"] = "Active"

    async def _stop(self, name: str, state: str) -> None:
        t = self._tasks.pop(name, None)
        if t is not None and not t.done():
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        if self.status[name]["state"] != "Faulty" or t is None:
            self.status[name]["state"] = state

    # -- aggregation ----------------------------------------------------

    def _aggregate(self, active: set[str]) -> None:
        """Session synced_through = the slowest ACTIVE worker (every
        group's changes up to that instant are on the secondary)."""
        vals = []
        for name in active:
            w = self.workers.get(name)
            t = self._tasks.get(name)
            if w is None or t is None or t.done():
                return  # a group has no live active worker: no claim
            vals.append(w.state.get("synced_through", 0.0))
        if vals:
            agg = min(vals)
            if agg > self.state.get("synced_through", 0.0):
                self.state["synced_through"] = agg
                self.state["last_ts"] = max(
                    w.state.get("last_ts", 0.0)
                    for w in self.workers.values())
                self._save_session()

    def _write_status(self) -> None:
        if not self.statusfile:
            return
        body = {"pid": os.getpid(),
                "workers": {n: dict(s)
                            for n, s in self.status.items()},
                "synced_through": self.state.get("synced_through", 0.0)}
        tmp = self.statusfile + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f)
        os.replace(tmp, self.statusfile)

    async def run(self) -> None:
        # volume-level initial crawl once per session (pre-session data
        # has no journal records anywhere)
        while not self.state.get("initial_done"):
            crawler = GeoRepWorker(self.primary, self.secondary, [],
                                   os.path.join(self.state_dir,
                                                "crawl.state"),
                                   self.interval)
            try:
                await crawler.initial_crawl()
                self.state["initial_done"] = True
                self._save_session()
            except Exception as e:
                log.error(4, "initial crawl failed (will retry): %r", e)
                await asyncio.sleep(self.interval)
        allbricks = [(b["name"], True) for b in self.bricks]
        while True:
            got = await self._volume_bricks()
            if got is not None:
                allbricks = got
            active = self._elect(allbricks)
            online = {n for n, up in allbricks if up}
            for b in self.bricks:
                if b["name"] in active:
                    self._start(b)
                else:
                    await self._stop(
                        b["name"],
                        "Passive" if b["name"] in online else "Offline")
            self._aggregate(active & {b["name"] for b in self.bricks})
            self._write_status()
            await asyncio.sleep(min(self.interval, 1.0))

    async def stop(self) -> None:
        for name in list(self._tasks):
            await self._stop(name, "Stopped")


def _parse_endpoint(spec: str) -> tuple[str, int, str]:
    host, port, vol = spec.rsplit(":", 2)
    return host, int(port), vol


async def _amain(args) -> None:
    from .glusterd import mount_volume

    # fail FAST on malformed endpoints: a retry loop cannot fix a typo,
    # and in broker mode it would respawn a doomed agent forever
    ph, pp, pv = _parse_endpoint(args.primary)
    _parse_endpoint(args.secondary)
    primary = secondary = None
    broker = args.transport == "broker"
    while primary is None or secondary is None:
        try:
            if primary is None:
                primary = await mount_volume(ph, pp, pv)
            if secondary is None:
                if broker:
                    # the "geo" in geo-rep: the secondary site is only
                    # reachable through a spawned agent (repce/ssh
                    # analog) — THIS process holds no secondary client
                    from .repce import RepceClient

                    secondary = RepceClient(args.secondary)
                    await secondary._call("__ping__")  # spawn + mount
                else:
                    sh, sp, sv = _parse_endpoint(args.secondary)
                    secondary = await mount_volume(sh, sp, sv)
        except Exception as e:
            log.warning(3, "gsyncd mount retry: %r", e)
            if broker and secondary is not None:
                await secondary.close()
                secondary = None
            await asyncio.sleep(1.0)
    if args.bricks:
        bricks = []
        for i, spec in enumerate(args.bricks.split(",")):
            name, _, rest = spec.partition("=")
            idx, _, path = rest.partition("=")
            bricks.append({"name": name, "index": int(idx),
                           "path": path})
        worker = GeoRepMonitor(
            primary, secondary, glusterd=(ph, pp), volume=pv,
            bricks=bricks, group_size=args.group_size,
            state_dir=os.path.dirname(args.state) or ".",
            session_state=args.state, interval=args.interval,
            statusfile=args.statusfile)
        run_task = asyncio.ensure_future(worker.run())
    else:  # legacy single-worker mode (--changelogs)
        worker = GeoRepWorker(primary, secondary,
                              args.changelogs.split(","),
                              args.state, args.interval)
        worker.start()
        run_task = None
    if args.statusfile and not args.bricks:
        with open(args.statusfile + ".tmp", "w") as f:
            json.dump({"pid": os.getpid()}, f)
        os.replace(args.statusfile + ".tmp", args.statusfile)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if run_task is not None:
        run_task.cancel()
        try:
            await run_task
        except (asyncio.CancelledError, Exception):
            pass
    await worker.stop()
    await primary.unmount()
    # broker: only proxy the unmount into an agent that is still alive
    # (unmounting through a respawned agent would mount the secondary
    # just to unmount it — or hang shutdown when the site is down), and
    # bound it so a wedged agent can't stop gsyncd from exiting
    if not broker or secondary.alive:
        try:
            await asyncio.wait_for(secondary.unmount(), 15)
        except Exception:
            pass
    if broker:
        await secondary.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-gsyncd")
    p.add_argument("--primary", required=True, help="host:port:volume")
    p.add_argument("--secondary", required=True, help="host:port:volume")
    p.add_argument("--changelogs", default="",
                   help="(legacy) comma-separated brick changelog dirs")
    p.add_argument("--bricks", default="",
                   help="local bricks as name=index=path,... — enables "
                        "the per-brick monitor (monitor.py model)")
    p.add_argument("--group-size", type=int, default=1,
                   help="bricks per replica/disperse subvolume group")
    p.add_argument("--state", required=True)
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--statusfile", default="")
    p.add_argument("--transport", choices=("broker", "direct"),
                   default="broker",
                   help="broker (default): reach the secondary only "
                        "through a spawned agent process (repce/ssh "
                        "analog); direct: mount it in-process")
    args = p.parse_args(argv)
    if not args.bricks and not args.changelogs:
        p.error("one of --bricks or --changelogs is required")
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
