"""Geo-replication worker — the gsyncd analog.

Reference: geo-replication/syncdaemon (primary.py:90-135 crawl/changelog
consumption, resource.py rsync/tar transport): an asynchronous daemon
that discovers what changed on the primary volume from the brick
changelogs and replays it onto a secondary volume, keeping a persisted
checkpoint so a crashed/restarted worker resumes where it left off.

TPU-build shape: one worker per (primary volume -> secondary volume)
link.  It tails every primary brick's journal segments by
(segment, offset) cursor (features/changelog.py), coalesces the batch
(one data-sync per path — the copy reads the CURRENT primary state
through the mounted client, so intermediate writes are free), replays
entry ops in order, and persists cursors only after a fully-applied
batch — replay is idempotent, so re-applying after a crash converges.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import sys

from ..core.fops import FopError
from ..core import gflog

log = gflog.get_logger("gsyncd")

COPY_WINDOW = 1 << 20


class GeoRepWorker:
    def __init__(self, primary, secondary, changelog_dirs: list[str],
                 state_path: str, interval: float = 5.0):
        self.primary = primary      # mounted Client on the primary vol
        self.secondary = secondary  # mounted Client on the secondary vol
        self.dirs = changelog_dirs
        self.state_path = state_path
        self.interval = interval
        self.state = self._load_state()
        self.synced = 0
        self.batches = 0
        self._task: asyncio.Task | None = None

    # -- checkpoint ---------------------------------------------------------

    def _load_state(self) -> dict:
        try:
            with open(self.state_path) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return {"cursors": {}, "last_ts": 0.0}

    def _save_state(self) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
        os.replace(tmp, self.state_path)

    # -- journal tailing ----------------------------------------------------

    def _collect_new(self) -> tuple[list[dict], dict]:
        """Read records past each brick's (segment, offset) cursor.
        Returns (records, advanced-cursor-proposal) WITHOUT touching
        self.state — cursors move only after the batch fully applies,
        so a failed replay is re-read next tick (replay is idempotent)."""
        out: list[dict] = []
        proposal = {d: dict(c)
                    for d, c in self.state["cursors"].items()}
        for d in self.dirs:
            cur = proposal.setdefault(d, {})
            try:
                segs = sorted(int(n.rsplit(".", 1)[1])
                              for n in os.listdir(d)
                              if n.startswith("CHANGELOG."))
            except OSError:
                continue
            for seq in segs:
                if seq < cur.get("segment", 0):
                    continue
                off = cur.get("offset", 0) \
                    if seq == cur.get("segment", 0) else 0
                path = os.path.join(d, f"CHANGELOG.{seq}")
                try:
                    with open(path) as f:
                        f.seek(off)
                        data = f.read()
                except OSError:
                    continue
                # consume only complete lines (a record may be mid-write)
                complete = data.rfind("\n") + 1
                for line in data[:complete].splitlines():
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
                cur["segment"] = seq
                cur["offset"] = off + complete
        out.sort(key=lambda r: r.get("ts", 0))
        return out, proposal

    # -- replay -------------------------------------------------------------

    async def _copy_file(self, path: str, strict: bool = False) -> bool:
        """Sync the CURRENT primary state of path to the secondary.

        ``strict`` is the initial-crawl mode: pre-session data has no
        journal records, so a transient primary-side failure (ENOTCONN,
        EIO) must re-raise and retry the walk — only a genuinely
        vanished entry may be skipped.  Journal replay passes False:
        there a vanished source is benign because a later record
        covers the final state."""
        _gone = (errno.ENOENT, errno.ESTALE)
        try:
            ia = await self.primary.stat(path)
        except FopError as e:
            if strict and e.err not in _gone:
                raise
            return False  # vanished since the record; a later E handles it
        try:
            f_in = await self.primary.open(path)
        except FopError as e:
            if strict and e.err not in _gone:
                raise
            return False  # vanished on primary: benign
        try:
            try:
                f_out = await self.secondary.create(path)
            except FopError as e:
                if e.err != errno.EEXIST:
                    raise  # secondary trouble is a REAL failure: retry batch
                f_out = await self.secondary.open(path, os.O_RDWR)
            try:
                off = 0
                while off < ia.size:
                    chunk = await f_in.read(
                        min(COPY_WINDOW, ia.size - off), off)
                    if not chunk:
                        break
                    await f_out.write(chunk, off)
                    off += len(chunk)
                await self.secondary.truncate(path, ia.size)
            finally:
                await f_out.close()
        finally:
            await f_in.close()
        return True

    async def _ensure_parents(self, path: str) -> None:
        parts = [p for p in path.strip("/").split("/")[:-1] if p]
        cur = ""
        for p in parts:
            cur += "/" + p
            try:
                await self.secondary.mkdir(cur)
            except FopError:
                pass

    async def _replay(self, rec: dict) -> bool:
        """Apply one record to the secondary; False = hard failure (the
        caller must NOT advance the cursors; the batch re-applies next
        tick)."""
        op, path = rec.get("op", ""), rec.get("path", "")
        if not path:
            return True
        try:
            if op in ("unlink",):
                try:
                    await self.secondary.unlink(path)
                except FopError as e:
                    if e.err != errno.ENOENT:
                        raise
            elif op == "rmdir":
                try:
                    await self.secondary.rmdir(path)
                except FopError as e:
                    if e.err not in (errno.ENOENT, errno.ENOTEMPTY):
                        raise
            elif op == "mkdir":
                await self._ensure_parents(path)
                try:
                    await self.secondary.mkdir(path)
                except FopError as e:
                    if e.err != errno.EEXIST:
                        raise
            elif op == "rename":
                dst = rec.get("path2", "")
                if dst:
                    await self._ensure_parents(dst)
                    try:
                        await self.secondary.rename(path, dst)
                    except FopError:
                        # source absent on secondary: materialize dst
                        await self._copy_file(dst)
                    try:
                        await self.secondary.unlink(path)
                    except FopError:
                        pass
            elif op == "link":
                dst = rec.get("path2", "")
                if dst:
                    await self._ensure_parents(dst)
                    try:
                        await self.secondary.link(path, dst)
                    except FopError:
                        # source missing on secondary: materialize dst
                        await self._copy_file(dst)
            elif op == "symlink":
                try:
                    target = await self.primary.readlink(path)
                    await self._ensure_parents(path)
                    await self.secondary.symlink(target, path)
                except FopError:
                    pass
            elif rec.get("type") in ("D", "E"):
                # create/write/truncate/...: sync current file state
                await self._ensure_parents(path)
                if await self._copy_file(path):
                    self.synced += 1
            elif rec.get("type") == "M":
                try:
                    ia = await self.primary.stat(path)
                    await self.secondary.setattr(
                        path, {"mode": ia.mode & 0o7777})
                except FopError:
                    pass
        except FopError as e:
            log.warning(1, "replay %s %s failed: %s", op, path, e)
            return False
        return True

    _SYNC_OPS = {"create", "icreate", "put"}

    @classmethod
    def _is_sync(cls, r: dict) -> bool:
        """Records whose replay is 'copy current file state'."""
        return r.get("type") == "D" or r.get("op") in cls._SYNC_OPS

    @classmethod
    def _coalesce(cls, recs: list[dict]) -> list[dict]:
        """One data-sync per path per batch: create + N writev records
        collapse to the LAST such record (the copy reads the current
        primary state anyway)."""
        last: dict[str, int] = {}
        for i, r in enumerate(recs):
            if cls._is_sync(r):
                last[r.get("path", "")] = i
        return [r for i, r in enumerate(recs)
                if not cls._is_sync(r) or last.get(r.get("path", "")) == i]

    async def process_once(self) -> int:
        import time as _t

        # stamp BEFORE the scan: a record journaled between scan and
        # stamp must not fall inside a "synced through" window
        scan_started = _t.time()
        recs, proposal = self._collect_new()
        if not recs:
            # caught up THROUGH the scan start: checkpoint completion
            # must not wait for new traffic on an idle session
            # (gsyncdstatus checkpoint semantics)
            self.state["synced_through"] = scan_started
            self._save_state()
            return 0
        batch = self._coalesce(recs)
        ok = True
        for rec in batch:
            ok = await self._replay(rec) and ok
        if not ok:
            # leave the cursors where they were: the whole batch is
            # re-read and re-applied (idempotently) next tick
            return 0
        self.state["cursors"] = proposal
        self.state["last_ts"] = recs[-1].get("ts", 0)
        self.state["synced_through"] = self.state["last_ts"]
        self.batches += 1
        self._save_state()
        self._prune_consumed()
        return len(batch)

    def _prune_consumed(self) -> None:
        """Delete journal segments fully behind the persisted cursor —
        the consumed changelog would otherwise grow without bound (the
        reference archives processed changelogs the same way)."""
        for d, cur in self.state["cursors"].items():
            current = cur.get("segment", 0)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                if not n.startswith("CHANGELOG."):
                    continue
                try:
                    seq = int(n.rsplit(".", 1)[1])
                except ValueError:
                    continue
                if seq < current:
                    try:
                        os.unlink(os.path.join(d, n))
                    except OSError:
                        pass

    async def initial_crawl(self) -> int:
        """Hybrid/xsync crawl (reference primary.py XCrawlMixin): data
        written BEFORE the session existed has no journal records —
        walk the primary namespace once and materialize everything on
        the secondary, then hand over to changelog tailing.  Runs
        before the first journal batch; idempotent (copy reads current
        primary state), so a crash mid-crawl just re-walks."""
        from ..core.iatt import IAType

        synced = 0

        # pre-session data has NO journal records, so a transiently
        # failing secondary op here (ENOTCONN, EIO) loses the entry
        # forever if swallowed — only the benign races (entry already
        # there / vanished under live churn) may pass; everything else
        # re-raises so run() retries the walk, same as the listdir path.
        _benign = (errno.EEXIST, errno.ENOENT, errno.ESTALE)

        async def meta(child: str, ia) -> None:
            # pre-session data has no 'M' journal records: carry
            # mode/ownership in the crawl itself
            try:
                await self.secondary.setattr(
                    child, {"mode": ia.mode & 0o7777,
                            "uid": ia.uid, "gid": ia.gid})
            except FopError as e:
                if e.err not in _benign:
                    raise

        async def walk(path: str) -> int:
            n = 0
            try:
                entries = await self.primary.listdir_with_stat(path)
            except FopError as e:
                if e.err in (errno.ENOENT, errno.ESTALE):
                    # directory vanished mid-crawl (live churn): its
                    # removal IS journaled, so skipping is safe
                    return 0
                # transient trouble (ENOTCONN, EIO): pre-session data
                # has NO journal records — finishing the crawl now
                # would mark initial_done with this subtree missing
                # forever; re-raise so run() retries the whole walk
                raise
            for name, ia in entries:
                child = path.rstrip("/") + "/" + name
                if ia is not None and ia.is_dir():
                    try:
                        await self.secondary.mkdir(child)
                    except FopError as e:
                        if e.err not in _benign:
                            raise
                    await meta(child, ia)
                    n += await walk(child)
                elif ia is not None and ia.ia_type is IAType.LNK:
                    # symlinks must stay symlinks (journal replay's
                    # op=='symlink' path does the same)
                    try:
                        target = await self.primary.readlink(child)
                        await self.secondary.symlink(target, child)
                        n += 1
                    except FopError as e:
                        if e.err not in _benign:
                            raise
                else:
                    if await self._copy_file(child, strict=True):
                        if ia is not None:
                            await meta(child, ia)
                        n += 1
            return n

        synced = await walk("/")
        self.state["initial_done"] = True
        self._save_state()
        log.info(3, "initial crawl synced %d files", synced)
        return synced

    async def run(self) -> None:
        while not self.state.get("initial_done"):
            try:
                await self.initial_crawl()
            except Exception as e:
                log.error(4, "initial crawl failed (will retry): %r", e)
                await asyncio.sleep(self.interval)
        while True:
            try:
                await self.process_once()
            except Exception as e:  # a bad batch must not kill the link
                log.error(2, "gsyncd batch failed: %r", e)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def status(self) -> dict:
        return {"batches": self.batches, "files_synced": self.synced,
                "last_ts": self.state.get("last_ts", 0)}


def _parse_endpoint(spec: str) -> tuple[str, int, str]:
    host, port, vol = spec.rsplit(":", 2)
    return host, int(port), vol


async def _amain(args) -> None:
    from .glusterd import mount_volume

    # fail FAST on malformed endpoints: a retry loop cannot fix a typo,
    # and in broker mode it would respawn a doomed agent forever
    ph, pp, pv = _parse_endpoint(args.primary)
    _parse_endpoint(args.secondary)
    primary = secondary = None
    broker = args.transport == "broker"
    while primary is None or secondary is None:
        try:
            if primary is None:
                primary = await mount_volume(ph, pp, pv)
            if secondary is None:
                if broker:
                    # the "geo" in geo-rep: the secondary site is only
                    # reachable through a spawned agent (repce/ssh
                    # analog) — THIS process holds no secondary client
                    from .repce import RepceClient

                    secondary = RepceClient(args.secondary)
                    await secondary._call("__ping__")  # spawn + mount
                else:
                    sh, sp, sv = _parse_endpoint(args.secondary)
                    secondary = await mount_volume(sh, sp, sv)
        except Exception as e:
            log.warning(3, "gsyncd mount retry: %r", e)
            if broker and secondary is not None:
                await secondary.close()
                secondary = None
            await asyncio.sleep(1.0)
    worker = GeoRepWorker(primary, secondary, args.changelogs.split(","),
                          args.state, args.interval)
    if args.statusfile:
        with open(args.statusfile + ".tmp", "w") as f:
            json.dump({"pid": os.getpid()}, f)
        os.replace(args.statusfile + ".tmp", args.statusfile)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    worker.start()
    await stop.wait()
    await worker.stop()
    await primary.unmount()
    # broker: only proxy the unmount into an agent that is still alive
    # (unmounting through a respawned agent would mount the secondary
    # just to unmount it — or hang shutdown when the site is down), and
    # bound it so a wedged agent can't stop gsyncd from exiting
    if not broker or secondary.alive:
        try:
            await asyncio.wait_for(secondary.unmount(), 15)
        except Exception:
            pass
    if broker:
        await secondary.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-gsyncd")
    p.add_argument("--primary", required=True, help="host:port:volume")
    p.add_argument("--secondary", required=True, help="host:port:volume")
    p.add_argument("--changelogs", required=True,
                   help="comma-separated brick changelog dirs")
    p.add_argument("--state", required=True)
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--statusfile", default="")
    p.add_argument("--transport", choices=("broker", "direct"),
                   default="broker",
                   help="broker (default): reach the secondary only "
                        "through a spawned agent process (repce/ssh "
                        "analog); direct: mount it in-process")
    args = p.parse_args(argv)
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
