"""Management daemon — the glusterd analog (scoped to the ~5% that
matters: volinfo store, peers, txn, volgen, brick lifecycle, portmap,
volfile serving; SURVEY.md §7 "keep the management plane small").

Reference: xlators/mgmt/glusterd (108k LoC).  Kept behaviors:

* **Persistent store** (glusterd-store.c:561,1643): volumes + peers
  survive restart (JSON under the workdir).
* **Volume lifecycle**: create/start/stop/delete/set + info/status
  (op-sm commit path); start spawns one brick daemon per local brick
  (glusterd-utils.c runner) and records its port (portmap,
  glusterd-pmap.c:661).
* **Volgen** (glusterd-volgen.c): brick + client volfiles from volinfo.
* **Volfile serving** (__server_getspec, glusterd-handshake.c:867):
  clients fetch their graph over the mgmt RPC and mount it.
* **Peers + distributed txn** (glusterd-op-sm.c states lock -> stage ->
  commit): peer probe forms a cluster; volume ops lock all peers, stage
  (validate), commit (apply + store) — driven by the originating node
  (mgmt-v3 style, glusterd-mgmt.c).
* **Heal/profile/rebalance entry points** (glusterd-op-sm op handlers):
  forwarded to a temporary client graph mounted in-process.

The mgmt wire protocol reuses rpc/wire framing with method dispatch.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any

from ..core import gflog
from ..core.events import gf_event
from .bitd import DEFAULT_SCRUB_THROTTLE
from ..core.fops import FopError
from ..protocol.server import STATUS_KINDS
from ..rpc import wire
from . import volgen

log = gflog.get_logger("mgmt")

# this build's management op-version (xlator.h:758 / GD_OP_VERSION):
# the constant lives at the package root so client processes can
# advertise it without importing the mgmt plane; re-exported here for
# the historical import path
from .. import OP_VERSION  # noqa: F401


def _new_volinfo(state: dict, name: str, vtype: str, bricks: list,
                 redundancy: int) -> dict:
    """Volinfo scaffolding shared by volume-create and snapshot-clone:
    tombstone-seeded config generation, fresh id, and the per-volume
    credential pairs (client pair in every volfile, mgmt pair only in
    brick volfiles — glusterd_auth_set_username trusted-volfile model).
    The two creation paths must mint identical shapes."""
    return {
        "name": name, "type": vtype, "bricks": bricks,
        "redundancy": redundancy, "status": "created",
        "version": int(state.get("tombstones", {}).get(name, 0)) + 1,
        "options": {}, "id": str(uuid.uuid4()),
        "auth": {"username": str(uuid.uuid4()),
                 "password": str(uuid.uuid4()),
                 "mgmt-username": str(uuid.uuid4()),
                 "mgmt-password": str(uuid.uuid4())},
    }


def _copy_store(src: str, dst: str) -> None:
    """Replace a brick store with a copy of another (snapshot restore
    and clone both land here): a file-level copy changes every inode,
    so the gfid identity store and handle farm are rebound onto the
    copied files afterwards."""
    import shutil

    from ..storage.posix import rebuild_identity

    shutil.rmtree(dst, ignore_errors=True)
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    shutil.copytree(src, dst, symlinks=True)
    rebuild_identity(dst)


class MgmtError(Exception):
    pass


class Glusterd:
    """One management daemon instance (one per node)."""

    def __init__(self, workdir: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.workdir = os.path.abspath(workdir)
        self.host = host
        self.port = port
        os.makedirs(self.workdir, exist_ok=True)
        self._store = os.path.join(self.workdir, "store.json")
        self.state = self._load()
        self.uuid = self.state.setdefault("uuid", str(uuid.uuid4()))
        self.op_version = OP_VERSION
        self.bricks: dict[str, subprocess.Popen] = {}  # brickname -> proc
        self.ports: dict[str, int] = {}  # portmap: brickname -> port
        self.shd: dict[str, subprocess.Popen] = {}  # volname -> shd proc
        self.gsync: dict[str, subprocess.Popen] = {}  # volname -> gsyncd
        self.bitd: dict[str, subprocess.Popen] = {}  # volname -> bitd
        self.quotad: dict[str, subprocess.Popen] = {}  # volname -> quotad
        self.gateway: dict[str, subprocess.Popen] = {}  # volname -> gateway
        self.rebalanced: dict[str, subprocess.Popen] = {}  # volname -> rebal
        self._rb_saved: dict[str, float] = {}  # volname -> last ckpt save
        self._server: asyncio.AbstractServer | None = None
        self._txn_lock = asyncio.Lock()
        self._txn_holder: str | None = None
        self._subs: dict[str, set] = {}  # volname -> subscribed writers
        # server-quorum (glusterd-server-quorum.c): volumes whose bricks
        # this node killed because the mgmt cluster lost quorum
        self.quorum_interval = 5.0
        self._quorum_blocked: set[str] = set()
        self._quorum_task: asyncio.Task | None = None
        # brick multiplexing (glusterfsd-mgmt.c ATTACH): one shared
        # daemon per node serving every brick-multiplex'd brick
        self._mux: dict | None = None  # {proc, port, bricks:set}
        self._mux_lock = asyncio.Lock()
        # strong refs to fire-and-forget work (drain, post-replace
        # heal): the loop keeps only weak refs, and a GC'd drain task
        # would strand remove-brick in status "started" forever
        self._bg_tasks: set[asyncio.Task] = set()

    # -- store (glusterd-store.c analog) -----------------------------------

    def _load(self) -> dict:
        try:
            with open(self._store) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"volumes": {}, "peers": {}}

    def _save(self) -> None:
        tmp = self._store + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f, indent=1)
        os.replace(tmp, self._store)

    @staticmethod
    def _bump(vol: dict) -> None:
        """Advance a volume's config generation.  Every cluster-txn commit
        that mutates volinfo bumps in lockstep on the nodes that saw it;
        peer-hello reconciliation then imports the higher generation into
        nodes that missed the txn (the friend-sm volinfo import of
        glusterd-utils.c glusterd_compare_friend_volume, keyed there on
        volinfo->version exactly like this)."""
        vol["version"] = int(vol.get("version", 1)) + 1

    # -- service -----------------------------------------------------------

    async def start(self) -> int:
        self._server = await asyncio.start_server(self._serve, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.state["endpoint"] = f"{self.host}:{self.port}"
        self._save()
        log.info(10, "glusterd %s on %s:%d (workdir %s)", self.uuid[:8],
                 self.host, self.port, self.workdir)
        # restart-resume: bricks/shd/gsyncd/bitd of started volumes
        for vol in self.state["volumes"].values():
            if vol.get("status") == "started":
                await self._start_local_bricks(vol)
                # fire-and-forget: the fan-out waits up to 10s per
                # unreachable peer and must not stall daemon startup
                self._spawn_task(self._broadcast_local_ports(vol))
                self._spawn_shd(vol)
                if vol.get("georep", {}).get("status") == "started":
                    self._spawn_gsync(vol)
                if volgen._bool(vol.get("options", {}).get(
                        "features.bitrot", "off")):
                    self._spawn_bitd(vol)
                if volgen._bool(vol.get("options", {}).get(
                        "features.quota", "off")):
                    self._spawn_quotad(vol)
                if vol.get("gateway", {}).get("status") == "started":
                    self._spawn_gateway(vol)
                if vol.get("rebalance", {}).get("status") == "started" \
                        and vol["rebalance"].get("node") == self.uuid:
                    # restart-resume: the daemon picks its checkpoint
                    # out of the volinfo and CONTINUES the walk
                    self._spawn_rebalanced(vol)
        # activated snapshots resume serving too
        for s in self.state.get("snaps", {}).values():
            vi = s.get("volinfo")
            if vi:
                for b in vi["bricks"]:
                    await self._spawn_brick(vi, b)
        self._quorum_task = asyncio.create_task(self._quorum_loop())
        # catch up on config txns committed while this node was down
        # (the restart side of the friend handshake)
        if any(p["uuid"] != self.uuid
               for p in self.state["peers"].values()):
            self._spawn_task(self._refresh_peers())
        return self.port

    async def stop(self) -> None:
        # daemon shutdown kills workers WITHOUT touching the persisted
        # session status: a restarted glusterd resumes started sessions
        if self._quorum_task is not None:
            self._quorum_task.cancel()
            try:
                await self._quorum_task
            except (asyncio.CancelledError, Exception):
                pass
            self._quorum_task = None
        for name in list(self.gsync):
            self._kill_gsync(name)
        for name in list(self.bitd):
            self._kill_bitd(name)
        for name in list(self.quotad):
            self._kill_quotad(name)
        for name in list(self.gateway):
            self._kill_gateway(name)
        for name in list(self.rebalanced):
            self._kill_rebalanced(name)
        for name in list(self.shd):
            self._kill_shd(name)
        for name in list(self.bricks):
            self._kill_brick(name)
        if self._mux is not None:
            proc = self._mux["proc"]
            if proc.poll() is None:
                proc.terminate()
                try:
                    await asyncio.to_thread(proc.wait, timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
            self._mux = None
        if self._server is not None:
            self._server.close()
            for w in list(getattr(self, "_writers", [])):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    async def _serve(self, reader, writer) -> None:
        self._writers = getattr(self, "_writers", set())
        self._writers.add(writer)
        try:
            while True:
                try:
                    rec = await wire.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                xid, mtype, payload = wire.unpack(rec)
                try:
                    method, kwargs = payload
                    if method == "subscribe":
                        # volfile-change notifications for this
                        # connection (the reference's mgmt fetch-spec
                        # callback channel, glusterfsd-mgmt.c)
                        self._subs.setdefault(
                            kwargs["name"], set()).add(writer)
                        writer.write(wire.pack(xid, wire.MT_REPLY,
                                               {"ok": True}))
                        await writer.drain()
                        continue
                    fn = getattr(self, "op_" + method.replace("-", "_"),
                                 None)
                    if fn is None:
                        raise MgmtError(f"unknown op {method!r}")
                    ret = fn(**(kwargs or {}))
                    if asyncio.iscoroutine(ret):
                        ret = await ret
                    resp = (wire.MT_REPLY, ret)
                except (MgmtError, FopError) as e:
                    resp = (wire.MT_ERROR, FopError(
                        getattr(e, "err", errno.EINVAL), str(e)))
                except Exception as e:
                    log.error(11, "mgmt op failed: %r", e)
                    resp = (wire.MT_ERROR, FopError(errno.EIO, repr(e)))
                try:
                    writer.write(wire.pack(xid, *resp))
                    await writer.drain()
                except ConnectionError:
                    break
        finally:
            self._writers.discard(writer)
            for subs in self._subs.values():
                subs.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    def _notify_subscribers(self, name: str) -> None:
        """Push volfile-modified to every subscribed client connection."""
        frame = wire.pack(0, wire.MT_EVENT,
                          {"event": "volfile-modified", "volume": name})
        for w in list(self._subs.get(name, ())):
            try:
                w.write(frame)
            except Exception:
                self._subs[name].discard(w)

    # -- peers (glusterd-sm.c peer membership) -----------------------------

    async def op_peer_probe(self, host: str, port: int) -> dict:
        async with MgmtClient(host, port) as peer:
            info = await peer.call("peer-hello", me=self._peer_info(),
                                   **self._volume_export())
        self.state["peers"][info["uuid"]] = {
            k: v for k, v in info.items()
            if k not in ("volumes", "tombstones")}
        self._save()
        await self._reconcile_volumes(info.get("volumes"),
                                      info.get("tombstones"),
                                      from_uuid=info["uuid"])
        return {"ok": True, "peer": info}

    async def op_peer_hello(self, me: dict, volumes: dict | None = None,
                            tombstones: dict | None = None) -> dict:
        self.state["peers"][me["uuid"]] = me
        self._save()
        await self._reconcile_volumes(volumes, tombstones,
                                      from_uuid=me["uuid"])
        return {**self._peer_info(), **self._volume_export()}

    def _volume_export(self) -> dict:
        """Everything a peer needs to catch up on missed config txns."""
        return {"volumes": self.state["volumes"],
                "tombstones": self.state.get("tombstones", {})}

    async def _reconcile_volumes(self, volumes: dict | None,
                                 tombstones: dict | None,
                                 from_uuid: str | None = None
                                 ) -> list[str]:
        """Import newer volume generations a handshaking peer carries.

        The reference's friend handshake imports/compares volumes
        (glusterd-sm.c friend-sm + glusterd_compare_friend_volume); this
        is what lets its op-sm safely skip disconnected peers — they
        catch up here, not in the txn.  Deletions travel as tombstones
        (name -> generation at delete) so a peer that missed
        volume-delete drops the volume instead of resurrecting it; a
        re-created volume starts past its tombstone generation, so it
        survives reconciliation against stale tombstones.
        """
        changed: list[str] = []
        dirty = False  # learned tombstones must persist even with no
        vols = self.state["volumes"]  # volume change (else a restart
        tset = self.state.setdefault("tombstones", {})  # forgets them)
        for name, tver in (tombstones or {}).items():
            mine = vols.get(name)
            if mine is not None and tver >= int(mine.get("version", 1)):
                log.info(24, "reconcile: dropping %s (deleted at gen %d "
                         "while this node was away)", name, tver)
                vols.pop(name)
                await self._conform_local_daemons(
                    {**mine, "status": "stopped", "name": name},
                    deleted=True)
                self._notify_subscribers(name)
                changed.append(name)
            if int(tset.get(name, 0)) < int(tver):
                tset[name] = int(tver)
                dirty = True
        for name, vi in (volumes or {}).items():
            if int(tset.get(name, -1)) >= int(vi.get("version", 1)):
                continue  # deleted here at/after that generation
            mine = vols.get(name)
            if mine is None or \
                    int(vi.get("version", 1)) > int(mine.get("version", 1)):
                log.info(24, "reconcile: importing %s gen %d (had %s)",
                         name, int(vi.get("version", 1)),
                         "none" if mine is None
                         else f"gen {int(mine.get('version', 1))}")
                vols[name] = json.loads(json.dumps(vi))  # own copy
                changed.append(name)
        # brick ports are RUNTIME state owned by the hosting node, not
        # config: adopt the sender's ports for bricks IT hosts even when
        # generations tie (two nodes that both restarted hold equal gens
        # yet each has rebound its own bricks — version-keyed import
        # alone would leave both serving the other's dead ports)
        for name, vi in (volumes or {}).items():
            mine = vols.get(name)
            if mine is None or from_uuid is None or name in changed:
                continue
            theirs = {b["name"]: b["port"] for b in vi.get("bricks", ())
                      if b.get("node") == from_uuid and b.get("port")}
            for b in mine["bricks"]:
                p = theirs.get(b["name"])
                if p and b.get("port") != p:
                    b["port"] = p
                    self.ports[b["name"]] = p
                    dirty = True
                    if name not in changed:
                        changed.append(name)
        if changed or dirty:
            self._save()
            for name in changed:
                vol = vols.get(name)
                if vol is not None:
                    await self._conform_local_daemons(vol)
                    self._notify_subscribers(name)
        return changed

    async def _conform_local_daemons(self, vol: dict,
                                     deleted: bool = False) -> None:
        """Make local processes match an imported volinfo: start missing
        bricks/daemons of started volumes, stop leftovers of stopped or
        shrunk ones (the respawn side of glusterd_import_friend_volume).
        ``deleted``: the volume was dropped by a tombstone — every
        worker goes, including the geo-rep one a plain stop keeps."""
        name = vol["name"]
        started = vol.get("status") == "started"
        want = {b["name"] for b in vol["bricks"] if b["node"] == self.uuid}
        prefix = f"{name}-brick-"
        for bname in [b for b in self.bricks if b.startswith(prefix)]:
            if not started or bname not in want:
                b = next((x for x in vol["bricks"] if x["name"] == bname),
                         {"name": bname, "node": self.uuid})
                await self._stop_brick(vol, b)
        if started:
            try:
                await self._start_local_bricks(vol)
            except MgmtError as e:
                log.error(24, "reconcile: brick start for %s failed: %s",
                          name, e)
            # the imported volinfo carries the PEER's (possibly stale)
            # view of this node's brick ports: re-assert the live local
            # ports and push them cluster-wide, else peers keep serving
            # client volfiles pointing at the pre-restart ports.
            # Fire-and-forget: this runs inside the peer-hello RPC
            # handler, and a second unreachable peer would stall the
            # reply past the caller's 5s timeout, losing the catch-up.
            self._spawn_task(self._broadcast_local_ports(vol))
            self._spawn_shd(vol)
            if volgen._bool(vol.get("options", {}).get(
                    "features.bitrot", "off")):
                self._spawn_bitd(vol)
            if volgen._bool(vol.get("options", {}).get(
                    "features.quota", "off")):
                self._spawn_quotad(vol)
            if vol.get("georep", {}).get("status") == "started":
                self._spawn_gsync(vol)
            if vol.get("gateway", {}).get("status") == "started":
                self._spawn_gateway(vol)
            else:
                self._kill_gateway(name)
            if vol.get("rebalance", {}).get("status") == "started" and \
                    vol["rebalance"].get("node") == self.uuid:
                self._spawn_rebalanced(vol)
        else:
            self._kill_shd(name)
            self._kill_bitd(name)
            self._kill_quotad(name)
            self._kill_gateway(name)
            self._kill_rebalanced(name)
            if deleted:
                self._kill_gsync(name)

    def op_peer_status(self) -> dict:
        return {"me": self._peer_info(),
                "peers": list(self.state["peers"].values())}

    def _peer_info(self) -> dict:
        return {"uuid": self.uuid, "host": self.host, "port": self.port,
                "workdir": self.workdir, "op-version": self.op_version}

    def cluster_op_version(self) -> int:
        """The version every member supports: min over self + peers
        (peers probed by older builds advertise nothing -> 1)."""
        vers = [self.op_version]
        for p in self.state["peers"].values():
            if p["uuid"] != self.uuid:
                vers.append(int(p.get("op-version", 1)))
        return min(vers)

    async def _refresh_peers(self) -> None:
        """Re-handshake every reachable peer so stored peer info (esp.
        op-version) reflects its CURRENT build — the stored value is a
        probe-time snapshot, and an upgraded-and-restarted peer must be
        able to lift the cluster op-version without detach+re-probe
        (the reference re-advertises on every RPC handshake)."""
        for p in list(self.state["peers"].values()):
            if p["uuid"] == self.uuid:
                continue
            try:
                info = await asyncio.wait_for(self._node_call(
                    p, "peer-hello", me=self._peer_info(),
                    **self._volume_export()), 5)
                self.state["peers"][info["uuid"]] = {
                    k: v for k, v in info.items()
                    if k not in ("volumes", "tombstones")}
            except Exception:
                continue  # unreachable: keep the snapshot
            await self._reconcile_volumes(info.get("volumes"),
                                          info.get("tombstones"),
                                          from_uuid=info["uuid"])
        self._save()

    def _all_nodes(self) -> list[dict]:
        return [self._peer_info()] + [
            p for p in self.state["peers"].values()
            if p["uuid"] != self.uuid]

    def op_peer_ping(self) -> dict:
        return {"ok": True, "uuid": self.uuid}

    def _spawn_task(self, coro) -> asyncio.Task:
        t = asyncio.create_task(coro)
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)
        return t

    # -- server quorum (glusterd-server-quorum.c) --------------------------
    # cluster.server-quorum-type=server volumes have their local bricks
    # killed while fewer than server-quorum-ratio percent of the mgmt
    # cluster's nodes are reachable, and respawned when quorum returns —
    # fencing writes on a partitioned node so the majority side's heal
    # has a single authoritative history.

    def _quorum_volumes(self) -> list[dict]:
        return [v for v in self.state["volumes"].values()
                if v.get("status") == "started"
                and v.get("options", {}).get(
                    "cluster.server-quorum-type") == "server"]

    async def _alive_count(self) -> tuple[int, int]:
        """(reachable nodes incl. me, total nodes incl. me)."""
        peers = [p for p in self.state["peers"].values()
                 if p["uuid"] != self.uuid]

        async def ping(p: dict) -> bool:
            async def one() -> None:
                async with MgmtClient(p["host"], p["port"]) as c:
                    await c.call("peer-ping")

            # bound the CONNECT too: a black-holed peer (packets dropped,
            # no RST) must not stall loss detection for the kernel's
            # minutes-long connect timeout
            try:
                await asyncio.wait_for(one(), 2)
                return True
            except Exception:
                return False

        alive = await asyncio.gather(*(ping(p) for p in peers))
        return 1 + sum(alive), 1 + len(peers)

    def _quorum_met(self, vol: dict, alive: int, total: int) -> bool:
        ratio = float(vol.get("options", {}).get(
            "cluster.server-quorum-ratio", 51))
        return alive * 100 >= ratio * total

    async def _quorum_loop(self) -> None:
        while True:
            await asyncio.sleep(self.quorum_interval)
            try:
                await self._check_server_quorum()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.debug(14, "quorum check failed: %r", e)

    async def _check_server_quorum(self) -> None:
        vols = self._quorum_volumes()
        peers = [p for p in self.state["peers"].values()
                 if p["uuid"] != self.uuid]
        # volumes blocked earlier that stopped enforcing (option
        # flipped to none) or lost their peers (detach): unblock them —
        # single-node clusters are quorate, and a non-enforcing volume
        # must never stay fenced
        if self._quorum_blocked:
            enforcing = {v["name"] for v in vols} if peers else set()
            for stale in list(self._quorum_blocked - enforcing):
                vol = self.state["volumes"].get(stale)
                if vol is None or vol.get("status") != "started":
                    self._quorum_blocked.discard(stale)
                    continue
                # un-block only AFTER the respawn succeeds: a failed
                # spawn must leave the name in the set so the next
                # tick retries instead of stranding the bricks
                for b in vol["bricks"]:
                    if b["node"] == self.uuid and \
                            b["name"] not in self.bricks:
                        await self._spawn_brick(vol, b, port=b.get("port"))
                self._quorum_blocked.discard(stale)
                log.info(16, "quorum enforcement lifted: restarted "
                         "bricks of %s", stale)
        if not vols or not peers:
            return
        alive, total = await self._alive_count()
        for vol in vols:
            name = vol["name"]
            met = self._quorum_met(vol, alive, total)
            if not met and name not in self._quorum_blocked:
                self._quorum_blocked.add(name)
                for b in vol["bricks"]:
                    if b["node"] == self.uuid:
                        await self._stop_brick(vol, b)
                log.error(15, "server quorum lost (%d/%d): stopped "
                          "bricks of %s", alive, total, name)
                gf_event("SERVER_QUORUM_LOST", volume=name,
                         alive=alive, total=total)
            elif met and name in self._quorum_blocked:
                for b in vol["bricks"]:
                    if b["node"] == self.uuid and \
                            b["name"] not in self.bricks:
                        # reuse the recorded port: fenced clients are
                        # still retrying it
                        await self._spawn_brick(vol, b, port=b.get("port"))
                # only now: a failed respawn keeps the volume blocked
                # so the next tick retries
                self._quorum_blocked.discard(name)
                log.info(16, "server quorum regained (%d/%d): restarted "
                         "bricks of %s", alive, total, name)
                gf_event("SERVER_QUORUM_REGAINED", volume=name,
                         alive=alive, total=total)

    # -- hooks (glusterd-hooks.c) ------------------------------------------
    # Executable S*-prefixed scripts under
    # <workdir>/hooks/1/<op>/{pre,post}/ run around each volume op's
    # commit on every committing node, with --volname=<name> plus
    # op-specific args; failures are logged, never fatal (the
    # reference's advisory hook semantics).

    async def _run_hooks(self, op: str, phase: str, volname: str,
                         extra: tuple = ()) -> list[str]:
        # scripts block; keep the mgmt event loop (peer pings!) live
        return await asyncio.to_thread(
            self._run_hooks_sync, op, phase, volname, extra)

    def _run_hooks_sync(self, op: str, phase: str, volname: str,
                        extra: tuple = ()) -> list[str]:
        hookdir = os.path.join(self.workdir, "hooks", "1", op, phase)
        try:
            scripts = sorted(s for s in os.listdir(hookdir)
                             if s.startswith("S"))
        except FileNotFoundError:
            return []
        env = dict(os.environ)
        env["GLUSTERD_WORKDIR"] = self.workdir
        ran = []
        for s in scripts:
            path = os.path.join(hookdir, s)
            if not os.access(path, os.X_OK):
                continue
            try:
                res = subprocess.run(
                    [path, f"--volname={volname}", *extra], env=env,
                    timeout=30, check=False, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE)
                if res.returncode != 0:
                    log.error(17, "hook %s/%s/%s exited %d: %s", op,
                              phase, s, res.returncode,
                              (res.stderr or b"")[-500:].decode(
                                  errors="replace"))
                ran.append(s)
            except Exception as e:
                log.error(17, "hook %s/%s/%s failed: %r", op, phase, s, e)
        return ran

    # -- txn engine (lock -> stage -> commit, glusterd-op-sm.h:28-43) ------

    def op_txn_lock(self, holder: str) -> dict:
        # single-threaded event loop: check-and-set is atomic here
        if self._txn_holder is not None and self._txn_holder != holder:
            raise MgmtError(f"cluster busy (locked by {self._txn_holder})")
        self._txn_holder = holder
        return {"ok": True}

    def op_txn_unlock(self, holder: str) -> dict:
        if self._txn_holder == holder:
            self._txn_holder = None
        return {"ok": True}

    async def op_txn_stage(self, op: str, payload: dict) -> dict:
        fn = getattr(self, "stage_" + op.replace("-", "_"), None)
        if fn is not None:
            fn(**payload)
        return {"ok": True}

    async def op_txn_commit(self, op: str, payload: dict) -> dict:
        fn = getattr(self, "commit_" + op.replace("-", "_"))
        ret = fn(**payload)
        if asyncio.iscoroutine(ret):
            ret = await ret
        return {"ok": True, "result": ret}

    async def _cluster_txn(self, op: str, payload: dict) -> list:
        """Run lock/stage/commit across all reachable nodes (originator
        drives).  Peers that cannot be reached at lock time are skipped
        for the whole txn — the reference's op-sm spans only connected
        peers (rpc-state gated), so a dead node never wedges volume ops;
        it re-syncs state on its next handshake."""
        nodes = []
        holder = self.uuid
        locked: list[dict] = []
        try:
            for n in self._all_nodes():
                try:
                    # EOFError: peer died between connect and reply
                    # (IncompleteReadError); 10s bound: accepted-but-hung
                    # peers must not wedge every volume op
                    await asyncio.wait_for(
                        self._node_call(n, "txn-lock", holder=holder), 10)
                except FopError:
                    # the peer ANSWERED (e.g. cluster busy): a real
                    # rejection, not unreachability — abort the txn
                    raise
                except asyncio.TimeoutError:
                    # the peer may have APPLIED the lock after we gave
                    # up: keep it out of stage/commit but send the
                    # best-effort unlock, else its stale holder wedges
                    # every later txn
                    locked.append(n)
                    log.error(18, "peer %s lock timed out: skipped "
                              "from %s txn", n["uuid"][:8], op)
                    continue
                except (ConnectionError, OSError, EOFError):
                    log.error(18, "peer %s unreachable: skipped from "
                              "%s txn", n["uuid"][:8], op)
                    continue
                nodes.append(n)
                locked.append(n)
            # bounded like the lock phase: a peer hanging AFTER it
            # granted its lock must not hold the cluster lock forever.
            # Stage validates (fast); commit may spawn bricks, so its
            # bound is generous.  Timeout aborts the txn (a commit is
            # not safely skippable) — the finally-unlock still runs.
            for n in nodes:
                await asyncio.wait_for(
                    self._node_call(n, "txn-stage", op=op,
                                    payload=payload), 60)
            results = []
            for n in nodes:
                results.append(await asyncio.wait_for(
                    self._node_call(n, "txn-commit", op=op,
                                    payload=payload), 600))
            return results
        finally:
            for n in locked:
                try:
                    await asyncio.wait_for(
                        self._node_call(n, "txn-unlock", holder=holder),
                        10)
                except Exception:
                    pass

    async def _node_call(self, node: dict, method: str, **kwargs):
        if node["uuid"] == self.uuid:
            fn = getattr(self, "op_" + method.replace("-", "_"))
            ret = fn(**kwargs)
            if asyncio.iscoroutine(ret):
                ret = await ret
            return ret
        async with MgmtClient(node["host"], node["port"]) as c:
            return await c.call(method, **kwargs)

    # -- volume ops --------------------------------------------------------

    async def op_volume_create(self, name: str, vtype: str,
                               bricks: list, redundancy: int = 2,
                               group_size: int = 0,
                               arbiter: int = 0,
                               thin_arbiter: int = 0,
                               systematic: int = -1) -> dict:
        """bricks: list of {host, port(optional: mgmt node), path} or
        'host:/path' strings; host must match a node's host:port mgmt id
        or 'localhost'.

        ``systematic``: -1 (unset) defaults NEW disperse volumes to the
        systematic code layout once the whole cluster is at op-version
        12 (ROADMAP item 5's standing note; the parity-delta write
        plane is the write-side justification, zero-decode healthy
        reads were the read side).  Explicit 0 opts out (CLI:
        ``volume create ... non-systematic``)."""
        if name in self.state["volumes"]:
            raise MgmtError(f"volume {name} exists")
        if name.startswith("snap-"):
            raise MgmtError("volume names starting with 'snap-' are "
                            "reserved for activated snapshots")
        parsed = []
        for i, b in enumerate(bricks):
            if isinstance(b, str):
                nodeid, _, path = b.partition(":")
                b = {"node": nodeid, "path": path}
            node = self._resolve_node(b["node"]) if b.get("node") \
                else self._peer_info()
            parsed.append({
                "index": i, "node": node["uuid"],
                "host": b.get("host", node["host"]),
                "path": b["path"],
                "name": f"{name}-brick-{i}",
            })
        volinfo = _new_volinfo(self.state, name, vtype, parsed,
                               redundancy)
        if group_size:
            volinfo["group-size"] = group_size
        if arbiter:
            g = group_size or len(parsed)
            if vtype != "replicate" or arbiter != 1 or g != 3:
                # 2 data copies + 1 witness; anything else either has a
                # single data copy or is shapes gluster also rejects
                raise MgmtError("arbiter needs replica 3 arbiter 1")
            volinfo["arbiter"] = 1
        if thin_arbiter:
            if vtype != "replicate" or len(parsed) != 3 or arbiter:
                raise MgmtError("thin-arbiter needs replica 2 + one "
                                "tie-breaker brick (3 bricks)")
            volinfo["thin-arbiter"] = 1
        if systematic < 0:
            # default-on for new disperse volumes (explicit opt-out
            # only), mixed-version guarded: a pre-12 peer's volgen
            # would hand out non-systematic volfiles for this volume
            systematic = 1 if vtype == "disperse" and \
                self.cluster_op_version() >= 12 else 0
        if systematic:
            if vtype != "disperse":
                raise MgmtError("systematic applies to disperse volumes")
            # mixed-version guard (same gate volume-set keys get): an
            # older peer's volgen has no systematic branch and would
            # hand clients non-systematic volfiles for this volume —
            # writes through them would corrupt the fragment format
            if self.cluster_op_version() < 4:
                raise MgmtError(
                    "systematic volumes need cluster op-version >= 4 "
                    f"(cluster is at {self.cluster_op_version()})")
            # fragment format on the bricks: create-time only (flipping
            # it on existing fragments decodes to garbage)
            volinfo["systematic"] = 1
        if vtype == "disperse":
            n = len(parsed)
            g = group_size or n
            if g - redundancy < 1 or g % 1 or n % g:
                raise MgmtError("bad disperse geometry")
        await self._cluster_txn("volume-create", {"volinfo": volinfo})
        return {"ok": True, "volume": name}

    async def commit_volume_create(self, volinfo: dict) -> dict:
        await self._run_hooks("create", "pre", volinfo["name"])
        self.state["volumes"][volinfo["name"]] = volinfo
        self.state.get("tombstones", {}).pop(volinfo["name"], None)
        self._save()
        gf_event("VOLUME_CREATE", name=volinfo["name"],
                 type=volinfo["type"])
        await self._run_hooks("create", "post", volinfo["name"])
        return {"created": volinfo["name"]}

    def stage_volume_create(self, volinfo: dict) -> None:
        if volinfo["name"] in self.state["volumes"]:
            raise MgmtError(f"volume {volinfo['name']} exists here")

    async def op_volume_start(self, name: str) -> dict:
        self._vol(name)
        results = await self._cluster_txn("volume-start", {"name": name})
        # merge every node's portmap and broadcast it (pmap sync)
        ports: dict[str, int] = {}
        for r in results:
            ports.update(r.get("result", {}).get("ports", {}))
        for node in self._all_nodes():
            try:
                await self._node_call(node, "portmap-update",
                                      name=name, ports=ports)
            except Exception:
                pass
        return {"ok": True, "ports": ports}

    async def commit_volume_start(self, name: str) -> dict:
        vol = self._vol(name)
        await self._run_hooks("start", "pre", name)
        vol["status"] = "started"
        self._bump(vol)
        self._save()
        await self._start_local_bricks(vol)
        self._spawn_shd(vol)
        if volgen._bool(vol.get("options", {}).get("features.bitrot",
                                                   "off")):
            self._spawn_bitd(vol)
        if volgen._bool(vol.get("options", {}).get("features.quota",
                                                   "off")):
            self._spawn_quotad(vol)
        if vol.get("gateway", {}).get("status") == "started":
            self._spawn_gateway(vol)
        if vol.get("rebalance", {}).get("status") == "started" and \
                vol["rebalance"].get("node") == self.uuid:
            self._spawn_rebalanced(vol)
        gf_event("VOLUME_START", name=name)
        await self._run_hooks("start", "post", name)
        return {"started": name,
                "ports": {b["name"]: self.ports[b["name"]]
                          for b in vol["bricks"]
                          if b["name"] in self.ports}}

    def op_portmap_update(self, name: str, ports: dict) -> dict:
        vol = self._vol(name)
        for b in vol["bricks"]:
            if b["name"] in ports:
                b["port"] = ports[b["name"]]
        self.ports.update(ports)
        self._save()
        return {"ok": True}

    async def op_volume_stop(self, name: str) -> dict:
        await self._cluster_txn("volume-stop", {"name": name})
        return {"ok": True}

    async def commit_volume_stop(self, name: str) -> dict:
        vol = self._vol(name)
        await self._run_hooks("stop", "pre", name)
        vol["status"] = "stopped"
        self._quorum_blocked.discard(name)
        self._bump(vol)
        self._save()
        self._kill_bitd(name)
        self._kill_quotad(name)
        self._kill_gateway(name)
        self._kill_rebalanced(name)
        self._kill_shd(name)
        for b in vol["bricks"]:
            if b["node"] == self.uuid:
                await self._stop_brick(vol, b)
        gf_event("VOLUME_STOP", name=name)
        await self._run_hooks("stop", "post", name)
        return {"stopped": name}

    async def op_volume_delete(self, name: str) -> dict:
        vol = self._vol(name)
        if vol["status"] == "started":
            raise MgmtError("stop the volume first")
        await self._cluster_txn("volume-delete", {"name": name})
        return {"ok": True}

    async def commit_volume_delete(self, name: str) -> dict:
        await self._run_hooks("delete", "pre", name)
        vol = self.state["volumes"].pop(name, None)
        if vol is not None:
            self.state.setdefault("tombstones", {})[name] = \
                int(vol.get("version", 1))
        self._save()
        gf_event("VOLUME_DELETE", name=name)
        await self._run_hooks("delete", "post", name)
        return {"deleted": name}

    async def op_volume_set(self, name: str, key: str, value: str) -> dict:
        if key not in volgen.OPTION_MAP:
            raise MgmtError(f"unknown option {key!r}")
        need = volgen.OPTION_MIN_OPVERSION.get(key, 1)
        if need > self.cluster_op_version():
            # stored versions are probe-time snapshots: re-handshake
            # before refusing, so upgraded-and-restarted peers lift the
            # cluster without a detach + re-probe
            await self._refresh_peers()
        have = self.cluster_op_version()
        if need > have:
            # mixed-version skew guard (glusterd op-version gating): a
            # member that doesn't understand the option would silently
            # build wrong volfiles
            raise MgmtError(
                f"option {key!r} requires cluster op-version {need}, "
                f"but a member is at {have} (upgrade all nodes first)")
        if key == "server.ssl" and volgen._bool(value):
            opts = self._vol(name).get("options", {})
            if not opts.get("ssl.cert"):
                raise MgmtError("server.ssl needs ssl.cert set first "
                                "(bricks would fail to start)")
        if key == "config.transport" and value not in ("tcp",):
            # the one transport this build speaks (rdma is a descope;
            # see docs/volume_options.md)
            raise MgmtError(f"unsupported transport {value!r} "
                            "(this build speaks tcp)")
        if key == "cluster.mesh-codec" and volgen._bool(value) and \
                self._vol(name).get("systematic") and \
                self.cluster_op_version() < 14:
            # pre-14 members have no systematic mesh tier (ops/batch
            # only armed it on non-systematic codecs): storing the key
            # would silently do nothing on them — refuse loudly.  At
            # cluster op-version >= 14 the mesh tier runs systematic
            # volumes through the parity-rows-only sharded encode, so
            # the old mutual exclusion is lifted (ROADMAP item 5).
            raise MgmtError(
                "cluster.mesh-codec on a systematic volume needs "
                "cluster op-version >= 14 (a member's mesh tier has "
                f"no systematic mode; cluster is at "
                f"{self.cluster_op_version()})")
        results = await self._cluster_txn(
            "volume-set", {"name": name, "key": key, "value": value})
        return {"ok": True,
                "applied": [r.get("result", {}).get("applied", "stored")
                            for r in results]}

    async def commit_volume_set(self, name: str, key: str, value: str) -> dict:
        vol = self._vol(name)
        await self._run_hooks("set", "pre", name, (f"-o{key}={value}",))
        vol.setdefault("options", {})[key] = value
        self._bump(vol)
        self._save()
        applied = "stored"
        if vol["status"] == "started":
            applied = await self._apply_to_bricks(vol)
            self._notify_subscribers(name)
        await self._run_hooks("set", "post", name, (f"-o{key}={value}",))
        return {name: {key: value}, "applied": applied}

    async def _apply_to_bricks(self, vol: dict) -> str:
        """Push the regenerated brick volfiles to running local bricks:
        same topology -> live __reconfigure__ over the brick RPC; shape
        change (feature toggle) -> respawn on the same port (the
        reference's volfile-compare + graph switch, graph.c:980-1089)."""
        outcome = "reconfigured"
        bdir = os.path.join(self.workdir, "bricks")
        for b in vol["bricks"]:
            if b["node"] != self.uuid or b["name"] not in self.bricks:
                continue
            text = volgen.build_brick_volfile(vol, b)
            ok = False
            port = self.ports.get(b["name"])
            if port:
                ok = await self._brick_reconfigure(
                    vol, port, text, subvol=b["name"] + "-server")
            if not ok:
                await self._stop_brick(vol, b)
                await self._spawn_brick(vol, b, port=b.get("port"))
                outcome = "respawned"
            volfile = os.path.join(bdir, b["name"] + ".vol")
            try:
                with open(volfile, "w") as f:
                    f.write(text)
            except OSError:
                pass
        return outcome

    @staticmethod
    async def _brick_call(vol: dict, port: int, name: str, args: list,
                          subvol: str = ""):
        """One authenticated mgmt call to a local brick: SETVOLUME
        handshake with the volume's generated credentials, then the
        call (bricks refuse unauthenticated RPC).  subvol routes to a
        specific brick graph on a multiplexed daemon."""
        ssl_ctx = None
        opts = vol.get("options", {})
        if volgen._bool(opts.get("server.ssl", "off")):
            from ..rpc import tls

            ssl_ctx = tls.client_context(opts.get("ssl.ca", ""),
                                         opts.get("ssl.cert", ""),
                                         opts.get("ssl.key", ""))
        # short timeout: during an ssl on/off transition the brick may
        # still speak the other protocol — fail fast to the respawn path
        # instead of wedging the cluster txn on a mutual stall
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection("127.0.0.1", port, ssl=ssl_ctx), 5)
        try:
            auth = vol.get("auth") or {}
            creds = {"username": auth.get("mgmt-username",
                                          auth.get("username", "")),
                     "password": auth.get("mgmt-password",
                                          auth.get("password", ""))}
            writer.write(wire.pack(1, wire.MT_CALL, [
                "__handshake__", [b"glusterd", subvol, creds], {}]))
            await writer.drain()
            rec = await asyncio.wait_for(wire.read_frame(reader), 5)
            _, mtype, payload = wire.unpack(rec)
            if mtype != wire.MT_REPLY or not payload.get("ok"):
                raise MgmtError("brick handshake refused")
            writer.write(wire.pack(2, wire.MT_CALL, [name, args, {}]))
            await writer.drain()
            rec = await asyncio.wait_for(wire.read_frame(reader), 5)
            _, mtype, payload = wire.unpack(rec)
            return payload if mtype == wire.MT_REPLY else None
        finally:
            writer.close()

    @classmethod
    async def _brick_reconfigure(cls, vol: dict, port: int,
                                 text: str, subvol: str = "") -> bool:
        try:
            payload = await cls._brick_call(vol, port,
                                            "__reconfigure__", [text],
                                            subvol=subvol)
            return bool(payload and payload.get("ok"))
        except Exception:
            return False

    def op_volume_info(self, name: str | None = None) -> dict:
        if name:
            return {name: self._vol(name)}
        return dict(self.state["volumes"])

    def op_volume_status(self, name: str) -> dict:
        vol = self._vol(name)
        bricks = []
        for b in vol["bricks"]:
            proc = self.bricks.get(b["name"])
            bricks.append({
                "name": b["name"], "path": b["path"], "node": b["node"],
                "port": self.ports.get(b["name"], 0),
                "online": proc is not None and proc.poll() is None,
            })
        shd = self.shd.get(name)
        out = {"volume": name, "status": vol["status"], "bricks": bricks,
               "shd": {"online": shd is not None and shd.poll() is None,
                       "pid": shd.pid if shd is not None else 0}}
        tasks = self._volume_tasks(vol)
        if tasks:
            out["tasks"] = tasks
        alerts = self._volume_alerts_block(vol)
        if alerts is not None:
            out["alerts"] = alerts
        return out

    def _volume_alerts_block(self, vol: dict) -> dict | None:
        """The status "alerts" section: rule-set shape from volume
        config (validation errors surface HERE, where the operator who
        just volume-set a bad rule is looking) plus the most recent
        ``volume alerts`` fan-out's active set.  Status stays a sync
        local op, so the live set is as-of the last fan-out — ``gftpu
        volume alerts`` is the fresh view."""
        rules_text = str(vol.get("options", {}).get(
            "diagnostics.slo-rules", "") or "")
        if not rules_text.strip():
            return None
        from ..core import slo

        rules, errors = slo.parse_rules(rules_text)
        block: dict[str, Any] = {"rules": len(rules)}
        if errors:
            block["rule_errors"] = errors
        cached = getattr(self, "_alerts_cache", {}).get(vol["name"])
        if cached:
            block["active"] = cached["active"]
            block["as_of"] = cached["ts"]
        return block

    @staticmethod
    def _volume_tasks(vol: dict) -> list[dict]:
        """Active background task state for the status "tasks" section
        (the reference appends rebalance/remove-brick task rows to
        every status answer, glusterd-op-sm.c _add_task_to_dict) — the
        data already lives in volinfo, it just wasn't surfaced."""
        tasks = []
        rb = vol.get("remove-brick")
        if rb:
            row = {"type": "remove-brick",
                   "status": rb.get("status", "unknown"),
                   "bricks": rb.get("bricks", [])}
            for k in ("progress", "moved", "scanned", "error"):
                if k in rb:
                    row[k] = rb[k]
            tasks.append(row)
        reb = vol.get("rebalance")
        if reb and reb.get("mode") != "drain":
            # a drain's task row is the remove-brick one above — two
            # rows for one background walk would double-report it
            row = {"type": "rebalance",
                   "status": reb.get("status", "unknown"),
                   "mode": reb.get("mode", "full"),
                   "phase": reb.get("phase", "idle")}
            for k in ("counters", "throttle", "error", "resumed_from"):
                if k in reb:
                    row[k] = reb[k]
            tasks.append(row)
        return tasks

    async def op_volume_heal(self, name: str, action: str = "info",
                             path: str = "") -> dict:
        """``gluster volume heal <v> [info|full|<path>]`` (glfs-heal.c /
        glusterd heal op analog): mounts a temporary client graph and
        drives the index-based heal surface."""
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        from . import shd as shd_mod

        client = await mount_volume(self.host, self.port, name)
        try:
            if action == "info":
                return await shd_mod.gather_heal_info(client)
            if action == "full":
                # full namespace sweep (ec_shd_full_sweep): also heals
                # bricks with no index record (replaced/wiped); file
                # heals run shd-max-threads wide so their re-encodes
                # coalesce (one mesh launch on a mesh-codec volume)
                return await shd_mod.full_crawl(
                    client, max_heals=self._shd_max_heals(vol))
            if action == "index":
                return await shd_mod.crawl_once(client)
            if action == "file":
                if not path:
                    raise MgmtError("heal file needs a path")
                layers = shd_mod._heal_layers(client.graph)
                if not layers:
                    raise MgmtError("volume has no heal-capable layer")
                out = {}
                for l in layers:
                    try:
                        out[l.name] = await l.heal_file(path)
                    except FopError as e:
                        out[l.name] = {"error": str(e)}
                return out
            raise MgmtError(f"unknown heal action {action!r}")
        finally:
            await client.unmount()

    # -- deep volume status (GF_CLI_STATUS_{DETAIL,CLIENTS,INODE,FD,
    # CALLPOOL,MEM}, glusterd-op-sm.c) -------------------------------------

    STATUS_KINDS = STATUS_KINDS  # the protocol/server op family

    async def op_volume_status_deep(self, name: str,
                                    what: str = "clients") -> dict:
        """``gftpu volume status <v> detail|clients|fds|inodes|
        callpool|mem`` — per-brick deep state gathered from every
        node's live brick processes and merged, with a ``partial``
        field naming unreachable nodes (never a fake-complete merge)."""
        if what not in self.STATUS_KINDS:
            raise MgmtError(f"unknown status kind {what!r} "
                            f"(one of {', '.join(self.STATUS_KINDS)})")
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-status-local", nodes=self._vol_nodes(vol),
            name=name, what=what)
        return self._merge_partial(
            {"volume": name, "what": what, "bricks": bricks}, partial)

    async def op_volume_status_local(self, name: str,
                                     what: str = "clients") -> dict:
        """One node's share of deep status: its local bricks' __status__
        RPC (the brick half lives in protocol/server._status_of)."""
        vol = self._vol(name)
        out: dict[str, Any] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            proc = self.bricks.get(b["name"])
            if not port or proc is None or proc.poll() is not None:
                # a dead LOCAL brick is still reported — as offline,
                # not silently dropped from the merge
                out[b["name"]] = {"offline": True}
                continue
            try:
                payload = await self._brick_call(
                    vol, port, "__status__", [what],
                    subvol=b["name"] + "-server")
            except Exception as e:
                out[b["name"]] = {"offline": True,
                                  "error": repr(e)[:200]}
                continue
            # None = the brick ANSWERED with an error (a pre-__status__
            # build, or an EINVAL kind): it is live and serving, so
            # report the refusal — never mislabel it offline
            out[b["name"]] = payload if payload is not None \
                else {"error": "__status__ refused "
                               "(older brick build?)"}
        return {"bricks": out}

    async def op_volume_heal_count(self, name: str) -> dict:
        """``volume heal <v> statistics heal-count`` — pending-heal
        entry counts straight from each brick's index layer
        (XA_INDEX_COUNT virtual xattr), no temporary client graph
        mounted (the reference answers from shd counters the same
        way, glusterd-volume-ops.c heal statistics)."""
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-heal-count-local", nodes=self._vol_nodes(vol),
            name=name)
        total = sum(v.get("count", 0) for v in bricks.values()
                    if isinstance(v, dict))
        return self._merge_partial(
            {"volume": name, "bricks": bricks, "total": total}, partial)

    async def op_volume_heal_count_local(self, name: str) -> dict:
        """One node's share of heal-count: each local brick's pending
        index entry count via one authenticated getxattr."""
        from ..core.layer import Loc
        from ..features.index import XA_INDEX_COUNT

        vol = self._vol(name)
        out: dict[str, dict] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            if not port:
                out[b["name"]] = {"offline": True, "count": 0}
                continue
            try:
                r = await self._brick_call(
                    vol, port, "getxattr", [Loc("/"), XA_INDEX_COUNT],
                    subvol=b["name"] + "-server")
                out[b["name"]] = {
                    "count": int((r or {}).get(XA_INDEX_COUNT, b"0"))}
            except Exception as e:
                out[b["name"]] = {"offline": True, "count": 0,
                                  "error": repr(e)[:200]}
        return {"bricks": out}

    async def op_volume_clear_locks(self, name: str, path: str,
                                    kind: str = "all") -> dict:
        """``gftpu volume clear-locks <v> <path> kind
        {blocked|granted|all}`` — operator-forced lock clearing riding
        the revocation machinery (the reference's clear-locks command,
        glusterd-volume-ops.c GF_CLI_CLEAR_LOCKS): fans out to every
        brick's features/locks and merges the per-brick cleared
        counts."""
        if kind not in ("blocked", "granted", "all"):
            raise MgmtError(f"clear-locks kind {kind!r} not one of "
                            "blocked/granted/all")
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-clear-locks-local", nodes=self._vol_nodes(vol),
            name=name, path=path, kind=kind)
        total = sum(v.get("total", 0) for v in bricks.values()
                    if isinstance(v, dict))
        return self._merge_partial(
            {"volume": name, "path": path, "kind": kind,
             "bricks": bricks, "total": total}, partial)

    async def op_volume_clear_locks_local(self, name: str, path: str,
                                          kind: str = "all") -> dict:
        """One node's share of clear-locks: each local brick's
        features/locks.clear_locks via the authenticated RPC extra."""
        vol = self._vol(name)
        out: dict[str, dict] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            if not port:
                out[b["name"]] = {"offline": True, "total": 0}
                continue
            try:
                r = await self._brick_call(
                    vol, port, "clear_locks", [path, kind],
                    subvol=b["name"] + "-server")
                out[b["name"]] = r or {"total": 0}
            except FopError as e:
                if e.err == errno.ENOENT:  # path not on this brick (dht)
                    out[b["name"]] = {"total": 0, "absent": True}
                else:
                    out[b["name"]] = {"total": 0, "error": str(e)}
            except Exception as e:
                out[b["name"]] = {"offline": True, "total": 0,
                                  "error": repr(e)[:200]}
        return {"bricks": out}

    _TOP_METRICS = ("open", "read", "write", "read-bytes",
                    "write-bytes")

    def _vol_nodes(self, vol: dict) -> list[dict]:
        """The nodes actually hosting this volume's bricks (fan-out
        targets: a peer with no brick of the volume can neither answer
        nor meaningfully be 'missing' from the merge)."""
        want = {b["node"] for b in vol["bricks"]}
        return [n for n in self._all_nodes() if n["uuid"] in want]

    async def _gather_bricks(self, local_op: str, nodes=None,
                             **kw) -> tuple[dict, list[str]]:
        """Fan a per-node brick query out CONCURRENTLY (bounded per
        node) and merge the 'bricks' maps — shared by volume status /
        top / profile / metrics / heal-count; a hung peer costs one
        timeout, not a serial wait, and never hides the other nodes'
        answers.

        Returns ``(bricks, partial)``: a dead or hung peer no longer
        vanishes into an empty merge — it is NAMED in ``partial`` so
        every consumer can say which nodes are missing instead of
        pretending full coverage (the silent-{} bug of ISSUE 5)."""
        targets = list(nodes) if nodes is not None else self._all_nodes()

        async def one(node):
            try:
                return await asyncio.wait_for(
                    self._node_call(node, local_op, **kw), 30)
            except Exception as e:
                log.warning(22, "node %s missing from %s fan-out: %r",
                            node["uuid"][:8], local_op, e)
                return None

        parts = await asyncio.gather(*(one(n) for n in targets))
        out: dict[str, dict] = {}
        partial: list[str] = []
        for node, part in zip(targets, parts):
            if part is None:
                partial.append(f"{node['uuid'][:8]}"
                               f"@{node['host']}:{node['port']}")
                continue
            out.update(part.get("bricks", {}))
        return out, partial

    @staticmethod
    def _merge_partial(out: dict, partial: list[str]) -> dict:
        if partial:
            out["partial"] = partial
        return out

    async def op_volume_profile(self, name: str) -> dict:
        """``gluster volume profile <v> info`` — BRICK-side cumulative
        per-fop counters/latency from each brick's io-stats layer (the
        reference aggregates brick responses the same way;
        io-stats.c:129-197)."""
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-profile-local", nodes=self._vol_nodes(vol),
            name=name)
        return self._merge_partial(
            {"volume": name, "bricks": bricks}, partial)

    async def op_volume_profile_local(self, name: str) -> dict:
        vol = self._vol(name)
        out: dict[str, dict] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            if not port:
                continue
            dump = await self._brick_statedump(
                vol, port, subvol=b["name"] + "-server")
            layers = (dump or {}).get("layers", {})
            prof = next((l.get("private") for l in layers.values()
                         if l.get("type") == "debug/io-stats"
                         and "fops" in (l.get("private") or {})), None)
            if prof is not None:
                out[b["name"]] = prof
        return {"bricks": out}

    async def op_volume_metrics(self, name: str) -> dict:
        """``gftpu volume metrics <v>`` — each brick process's unified
        metrics-registry scrape (core/metrics.py): decode-program cache
        hit/miss, wire blob lanes, io-threads queue depth, write-behind
        occupancy, codec probe state... resolved per brick by graph
        walk like top_stats."""
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-metrics-local", nodes=self._vol_nodes(vol),
            name=name)
        return self._merge_partial(
            {"volume": name, "bricks": bricks}, partial)

    async def op_volume_metrics_local(self, name: str) -> dict:
        """One node's share of volume-metrics: its local bricks, plus
        this node's gateway daemon's families when it exposes them
        (``gateway.metrics-port``) — under a worker pool that endpoint
        is the supervisor's AGGREGATED per-worker merge, so `volume
        metrics` sees the whole pool as one front door."""
        vol = self._vol(name)
        out: dict[str, dict] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            if not port:
                continue
            try:
                snap = await self._brick_call(
                    vol, port, "metrics_dump", [],
                    subvol=b["name"] + "-server")
            except Exception:
                snap = None  # dead brick: report empty, not an error
            out[b["name"]] = snap or {}
        gw_snap = await self._gateway_metrics(vol)
        if gw_snap is not None:
            out[f"gateway:{self.host}"] = gw_snap
        return {"bricks": out}

    async def _gateway_metrics(self, vol: dict) -> dict | None:
        """This node's gateway families over its /metrics.json (both
        the single-process daemon and the worker-pool supervisor serve
        it); None when no gateway/metrics-port is armed."""
        name = vol["name"]
        proc = self.gateway.get(name)
        mport = int(vol.get("options", {}).get("gateway.metrics-port",
                                               0) or 0)
        if proc is None or proc.poll() is not None or not mport:
            return None
        host = str(vol.get("options", {}).get("gateway.listen-host",
                                              "127.0.0.1"))
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, mport), 3)
            try:
                writer.write(b"GET /metrics.json HTTP/1.0\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 5)
            finally:
                writer.close()
            body = raw.split(b"\r\n\r\n", 1)[1]
            return json.loads(body.decode())
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return None

    # -- incident plane (flight-recorder capture fan-out) ------------------

    def _incident_dir(self, vol: dict) -> str:
        """Effective incident directory for this volume's cluster
        bundles: ``diagnostics.incident-dir`` when set (the same dir
        every process auto-captures into, so ``incident list`` shows
        both kinds side by side), else a workdir fallback so the
        operator command works on an unconfigured volume."""
        d = str(vol.get("options", {}).get("diagnostics.incident-dir",
                                           "") or "")
        return d or os.path.join(self.workdir, "incidents", vol["name"])

    def _incident_max_bytes(self, vol: dict) -> int:
        from ..core.options import parse_size

        try:
            return parse_size(vol.get("options", {}).get(
                "diagnostics.incident-max-bytes", "64MB"))
        except Exception:
            return 64 * 1024 * 1024

    async def op_volume_incident_capture(self, name: str) -> dict:
        """``gftpu volume incident capture <v>`` — fan a flight-recorder
        snapshot request across every node's bricks, gateway and
        service daemons, and merge the answers into ONE timestamped
        cluster bundle in the effective incident dir.  A dead peer is
        NAMED in ``partial`` (the volume-status contract), never
        silently missing from the merge."""
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        procs, partial = await self._gather_bricks(
            "volume-incident-local", nodes=self._vol_nodes(vol),
            name=name)
        bundle = self._merge_partial(
            {"volume": name, "ts": round(time.time(), 6),
             "reason": "capture", "origin": self.uuid,
             "processes": procs}, partial)
        idir = self._incident_dir(vol)
        os.makedirs(idir, exist_ok=True)
        path = os.path.join(
            idir, f"incident-{time.time_ns()}-cluster-{name}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=repr, separators=(",", ":"),
                      sort_keys=True)
        os.replace(tmp, path)
        from ..core import flight

        flight.prune_dir(idir, self._incident_max_bytes(vol))
        return self._merge_partial(
            {"volume": name, "bundle": path,
             "processes": sorted(procs)}, partial)

    async def op_volume_incident_local(self, name: str) -> dict:
        """One node's share of incident capture: each local brick's
        ``__incident__`` RPC, the gateway's ``/incident.json`` (the
        supervisor aggregates its workers there), and the SIGUSR2
        capture door of shd / rebalanced.  Non-brick processes ride the
        shared 'bricks' merge under reserved ``role:host`` keys, the
        volume-metrics idiom."""
        vol = self._vol(name)
        out: dict[str, Any] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            proc = self.bricks.get(b["name"])
            if not port or proc is None or proc.poll() is not None:
                out[b["name"]] = {"offline": True}
                continue
            try:
                payload = await self._brick_call(
                    vol, port, "__incident__", [],
                    subvol=b["name"] + "-server")
            except Exception as e:
                out[b["name"]] = {"offline": True,
                                  "error": repr(e)[:200]}
                continue
            out[b["name"]] = payload if payload is not None \
                else {"error": "__incident__ refused "
                               "(older brick build?)"}
        gw = await self._gateway_incident(vol)
        if gw is not None:
            out[f"gateway:{self.host}"] = gw
        name_ = vol["name"]
        shd_snap = await self._signal_incident(
            self.shd.get(name_),
            os.path.join(self.workdir, f"shd-{name_}.json.incident"))
        if shd_snap is not None:
            out[f"shd:{self.host}"] = shd_snap
        reb_snap = await self._signal_incident(
            self.rebalanced.get(name_),
            os.path.join(self.workdir,
                         f"rebalanced-{name_}.json.incident"))
        if reb_snap is not None:
            out[f"rebalance:{self.host}"] = reb_snap
        return {"bricks": out}

    async def _gateway_incident(self, vol: dict) -> dict | None:
        """This node's gateway flight bundle over /incident.json (the
        worker-pool supervisor answers with supervisor + per-worker
        snapshots merged); None when no gateway runs here."""
        name = vol["name"]
        proc = self.gateway.get(name)
        if proc is None or proc.poll() is not None:
            return {"offline": True} if proc is not None else None
        mport = int(vol.get("options", {}).get("gateway.metrics-port",
                                               0) or 0)
        if not mport:
            return {"error": "gateway.metrics-port not set "
                             "(no incident door)"}
        host = str(vol.get("options", {}).get("gateway.listen-host",
                                              "127.0.0.1"))
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, mport), 3)
            try:
                writer.write(b"GET /incident.json HTTP/1.0\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 5)
            finally:
                writer.close()
            body = raw.split(b"\r\n\r\n", 1)[1]
            return json.loads(body.decode())
        except Exception as e:  # noqa: BLE001 - one process of many
            return {"offline": True, "error": repr(e)[:200]}

    @staticmethod
    async def _signal_incident(proc, path: str) -> dict | None:
        """SIGUSR2 capture door for service daemons with no inbound
        RPC surface (shd, rebalanced): signal, poll for the bundle
        file, parse it.  None = no such daemon on this node."""
        if proc is None:
            return None
        if proc.poll() is not None:
            return {"offline": True}
        try:
            os.unlink(path)
        except OSError:
            pass
        try:
            proc.send_signal(signal.SIGUSR2)
        except OSError as e:
            return {"offline": True, "error": repr(e)[:200]}
        for _ in range(40):
            await asyncio.sleep(0.05)
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                continue  # not written yet / mid-rename
        return {"error": "signal capture timed out"}

    def op_volume_incident_list(self, name: str) -> dict:
        """``gftpu volume incident list <v>`` — the bundles (auto-
        captured AND operator-captured) in the effective incident
        dir."""
        vol = self._vol(name)
        idir = self._incident_dir(vol)
        bundles = []
        try:
            names = os.listdir(idir)
        except OSError:
            names = []
        for fn in sorted(names):
            if not (fn.startswith("incident-")
                    and fn.endswith(".json")):
                continue
            try:
                st = os.stat(os.path.join(idir, fn))
            except OSError:
                continue
            bundles.append({"name": fn, "bytes": st.st_size,
                            "mtime": round(st.st_mtime, 3)})
        return {"volume": name, "dir": idir, "bundles": bundles}

    def op_volume_incident_show(self, name: str,
                                bundle: str = "") -> dict:
        """``gftpu volume incident show <v> [bundle]`` — round-trip one
        bundle's JSON (default: the newest)."""
        vol = self._vol(name)
        idir = self._incident_dir(vol)
        if not bundle:
            rows = self.op_volume_incident_list(name)["bundles"]
            if not rows:
                raise MgmtError(
                    f"no incident bundles for {name} in {idir}")
            bundle = max(rows, key=lambda r: r["mtime"])["name"]
        base = os.path.basename(bundle)  # stay inside the incident dir
        path = os.path.join(idir, base)
        try:
            with open(path) as f:
                return json.load(f)
        except OSError as e:
            raise MgmtError(f"cannot read bundle {base}: "
                            f"{e}") from e
        except ValueError as e:
            raise MgmtError(f"bundle {base} is not valid JSON: "
                            f"{e}") from e

    # -- alerts plane (SLO engine fan-out, ISSUE 20) -----------------------

    _ALERT_ACTIONS = ("list", "history", "rules")

    async def op_volume_alerts(self, name: str,
                               action: str = "list") -> dict:
        """``gftpu volume alerts <v> [list|history|rules]`` — the
        cluster view of the SLO plane: every process evaluates rules
        against its OWN history ring (core/slo.py); this op gathers
        and merges their engine state per node, tagging each row with
        the process it came from.  ``rules`` answers from volume
        config alone (validation errors included) — no fan-out."""
        if action not in self._ALERT_ACTIONS:
            raise MgmtError(f"unknown alerts action {action!r} "
                            f"(one of {', '.join(self._ALERT_ACTIONS)})")
        vol = self._vol(name)
        rules_text = str(vol.get("options", {}).get(
            "diagnostics.slo-rules", "") or "")
        if action == "rules":
            from ..core import slo

            rules, errors = slo.parse_rules(rules_text)
            return {"volume": name, "rules": rules,
                    "rule_errors": errors}
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        procs, partial = await self._gather_bricks(
            "volume-alerts-local", nodes=self._vol_nodes(vol),
            name=name)
        active: list[dict] = []
        transitions: list[dict] = []
        rule_errors: list[str] = []
        for proc_name, st in sorted(procs.items()):
            if not isinstance(st, dict):
                continue
            for a in st.get("active", []):
                active.append({"process": proc_name, **a})
            for t in st.get("history", []):
                transitions.append({"process": proc_name, **t})
            for e in st.get("rule_errors", []):
                if e not in rule_errors:
                    rule_errors.append(e)
        active.sort(key=lambda a: a.get("since", 0.0))
        transitions.sort(key=lambda t: t.get("ts", 0.0))
        out = {"volume": name, "active": active,
               "processes": sorted(procs)}
        if rule_errors:
            out["rule_errors"] = rule_errors
        if action == "history":
            out["history"] = transitions
        # volume status surfaces this summary without re-fanning-out
        self._alerts_cache = getattr(self, "_alerts_cache", {})
        self._alerts_cache[name] = {"ts": round(time.time(), 3),
                                    "active": active}
        return self._merge_partial(out, partial)

    async def op_volume_alerts_local(self, name: str) -> dict:
        """One node's share of volume-alerts: each local brick's
        ``__alerts__`` door, the gateway's ``/alerts.json`` (the
        supervisor unions its workers there), and shd's tick-mirrored
        ``<statefile>.alerts`` file — the incident-local trio, minus
        daemons that mount no io-stats graph."""
        vol = self._vol(name)
        out: dict[str, Any] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            proc = self.bricks.get(b["name"])
            if not port or proc is None or proc.poll() is not None:
                out[b["name"]] = {"offline": True}
                continue
            try:
                payload = await self._brick_call(
                    vol, port, "__alerts__", [],
                    subvol=b["name"] + "-server")
            except Exception as e:
                out[b["name"]] = {"offline": True,
                                  "error": repr(e)[:200]}
                continue
            out[b["name"]] = payload if payload is not None \
                else {"error": "__alerts__ refused "
                               "(older brick build?)"}
        gw = await self._gateway_json(vol, "/alerts.json")
        if gw is not None:
            out[f"gateway:{self.host}"] = gw
        shd_st = self._read_alerts_file(
            self.shd.get(vol["name"]),
            os.path.join(self.workdir,
                         f"shd-{vol['name']}.json.alerts"))
        if shd_st is not None:
            out[f"shd:{self.host}"] = shd_st
        return {"bricks": out}

    async def _gateway_json(self, vol: dict, path: str) -> dict | None:
        """GET one JSON document off this node's gateway metrics
        endpoint (single-process daemon and worker-pool supervisor
        both serve it); None when no gateway runs here."""
        proc = self.gateway.get(vol["name"])
        if proc is None or proc.poll() is not None:
            return {"offline": True} if proc is not None else None
        mport = int(vol.get("options", {}).get("gateway.metrics-port",
                                               0) or 0)
        if not mport:
            return None
        host = str(vol.get("options", {}).get("gateway.listen-host",
                                              "127.0.0.1"))
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, mport), 3)
            try:
                writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 5)
            finally:
                writer.close()
            return json.loads(raw.split(b"\r\n\r\n", 1)[1].decode())
        except Exception as e:  # noqa: BLE001 - one process of many
            return {"offline": True, "error": repr(e)[:200]}

    @staticmethod
    def _read_alerts_file(proc, path: str) -> dict | None:
        """shd's alerts door: the daemon mirrors its engine status
        beside the statefile on every sampler tick (mgmt/shd.py), so
        reading it is passive — no signal round-trip.  None = no such
        daemon on this node or no rules configured (the mirror is only
        written once rules exist)."""
        if proc is None:
            return None
        if proc.poll() is not None:
            return {"offline": True}
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    async def op_volume_top(self, name: str, metric: str = "open",
                            count: int = 10) -> dict:
        """``gluster volume top <v> open|read|write|read-bytes|
        write-bytes`` — per-brick ranked per-path counters from each
        brick's io-stats layer (io-stats.c ios_stat_list backend),
        aggregated across every node's bricks."""
        if metric not in self._TOP_METRICS:
            # validate HERE: a typo'd metric must not come back as
            # empty rows indistinguishable from "no activity"
            raise MgmtError(f"unknown top metric {metric!r} "
                            f"(one of {', '.join(self._TOP_METRICS)})")
        vol = self._vol(name)
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        bricks, partial = await self._gather_bricks(
            "volume-top-local", nodes=self._vol_nodes(vol), name=name,
            metric=metric, count=int(count))
        return self._merge_partial(
            {"volume": name, "metric": metric, "bricks": bricks},
            partial)

    async def op_volume_top_local(self, name: str, metric: str = "open",
                                  count: int = 10) -> dict:
        """One node's share of volume-top: its local bricks."""
        vol = self._vol(name)
        out: dict[str, list] = {}
        for b in vol["bricks"]:
            if b["node"] != self.uuid:
                continue
            port = self.ports.get(b["name"])
            if not port:
                continue
            try:
                rows = await self._brick_call(
                    vol, port, "top_stats", [metric, int(count)],
                    subvol=b["name"] + "-server")
            except Exception:
                rows = None  # dead brick: report empty, not an error
            out[b["name"]] = rows or []
        return {"bricks": out}

    async def op_volume_brick(self, name: str, brick: str,
                              action: str) -> dict:
        """Stop / start one local brick daemon (the tests' kill_brick +
        ``volume start force`` analog); restart reuses the recorded port
        so connected clients can reconnect."""
        vol = self._vol(name)
        b = next((x for x in vol["bricks"] if x["name"] == brick), None)
        if b is None:
            raise MgmtError(f"no brick {brick!r} in {name}")
        if action == "stop":
            await self._stop_brick(vol, b)
            return {"stopped": brick}
        if action == "start":
            proc = self.bricks.get(brick)
            if proc is not None and proc.poll() is None:
                return {"already-running": brick}
            await self._spawn_brick(vol, b, port=b.get("port"))
            return {"started": brick, "port": self.ports.get(brick, 0)}
        raise MgmtError(f"unknown brick action {action!r}")

    # -- eventsapi (events/src/peer_eventsapi.py analog) -------------------
    # Webhook config is cluster-wide: the op fans out over the txn and
    # every node forwards to ITS eventsd's ctl port (from the
    # GFTPU_EVENTSD_CTL env, set by whoever runs gftpu-eventsd there).

    async def op_eventsapi(self, action: str, url: str = "") -> dict:
        if action in ("webhook-add", "webhook-del"):
            if not url:
                raise MgmtError(f"{action} needs a url")
            results = await self._cluster_txn(
                "eventsapi", {"action": action, "url": url})
            return {"ok": True,
                    "nodes": [r.get("result", {}) for r in results]}
        if action == "status":
            # cluster-wide view (peer_eventsapi status): the contacted
            # node having no eventsd must not hide everyone else's
            out = {}
            for node in self._all_nodes():
                try:
                    out[node["uuid"][:8]] = await asyncio.wait_for(
                        self._node_call(node, "eventsapi-local",
                                        ctl_method="status"), 10)
                except Exception as e:
                    out[node["uuid"][:8]] = {"error": repr(e)[:120]}
            return {"nodes": out}
        raise MgmtError(f"unknown eventsapi action {action!r}")

    async def op_eventsapi_local(self, ctl_method: str) -> dict:
        return await self._eventsd_ctl(ctl_method, {})

    async def commit_eventsapi(self, action: str, url: str) -> dict:
        return await self._eventsd_ctl(action, {"url": url})

    async def _eventsd_ctl(self, method: str, kwargs: dict) -> dict:
        ep = os.environ.get("GFTPU_EVENTSD_CTL", "")
        if not ep:
            return {"skipped": "no eventsd on this node "
                               "(GFTPU_EVENTSD_CTL unset)"}
        host, _, port = ep.partition(":")
        if not host or not port.isdigit():
            return {"skipped": f"malformed GFTPU_EVENTSD_CTL {ep!r} "
                               "(want host:port)"}
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), 5)
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            # a crashed eventsd must degrade like an absent one, not
            # abort the cluster txn half-committed
            return {"skipped": f"eventsd unreachable: {e!r}"[:200]}
        try:
            writer.write(wire.pack(1, wire.MT_CALL, [method, kwargs]))
            await writer.drain()
            rec = await asyncio.wait_for(wire.read_frame(reader), 5)
            _, mtype, payload = wire.unpack(rec)
            if mtype != wire.MT_REPLY:
                raise MgmtError(f"eventsd refused {method}: {payload}")
            return payload
        except (ConnectionError, OSError, asyncio.TimeoutError,
                asyncio.IncompleteReadError) as e:
            return {"skipped": f"eventsd unreachable: {e!r}"[:200]}
        finally:
            writer.close()

    # -- brick ops: add / remove / replace (glusterd-brick-ops.c,
    # glusterd-replace-brick.c) --------------------------------------------

    def _resolve_node(self, nodeid: str) -> dict:
        """'node' in a brick spec -> {uuid, host}: accepts a node uuid
        (or prefix) or a peer's host[:port] — anything else would wire
        a brick NO glusterd ever spawns into the volume."""
        me = self._peer_info()
        cands = [me] + [p for p in self.state["peers"].values()
                        if p["uuid"] != self.uuid]
        for p in cands:
            if p["uuid"] == nodeid or (
                    len(nodeid) >= 8 and p["uuid"].startswith(nodeid)):
                return p
        for p in cands:
            if nodeid in (p["host"], f"{p['host']}:{p['port']}",
                          "localhost"):
                return p
        raise MgmtError(f"brick node {nodeid!r} matches no cluster "
                        "member (peer probe it first)")

    def _parse_new_bricks(self, vol: dict, bricks: list) -> list[dict]:
        start = 1 + max((b["index"] for b in vol["bricks"]), default=-1)
        parsed = []
        for i, b in enumerate(bricks):
            if isinstance(b, str):
                nodeid, _, path = b.partition(":")
                b = {"node": nodeid, "path": path}
            node = self._resolve_node(b["node"]) if b.get("node") \
                else self._peer_info()
            idx = start + i
            parsed.append({
                "index": idx, "node": node["uuid"],
                "host": b.get("host", node["host"]), "path": b["path"],
                "name": f"{vol['name']}-brick-{idx}",
            })
        return parsed

    def _group_size(self, vol: dict) -> int:
        return vol.get("group-size") or len(vol["bricks"])

    async def op_volume_add_brick(self, name: str, bricks: list) -> dict:
        """``volume add-brick`` — grow the volume.  disperse/replicate
        volumes grow by whole groups (the volume becomes / stays
        distributed-X); plain distribute grows brick by brick."""
        vol = self._vol(name)
        if not bricks:
            raise MgmtError("add-brick needs bricks")
        group_size = 0
        if vol["type"] in ("disperse", "replicate"):
            group_size = self._group_size(vol)
            if len(bricks) % group_size:
                raise MgmtError(
                    f"add-brick on a {vol['type']} volume needs a "
                    f"multiple of {group_size} bricks (whole groups)")
        if (vol.get("rebalance") or {}).get("status") == "started":
            # a live rebalance walks the CURRENT layout; growing it
            # mid-run would leave the new brick unstamped by the
            # already-passed fix-layout directories (the reference
            # refuses the same way, glusterd-brick-ops.c)
            raise MgmtError("a rebalance is in progress; stop it "
                            "before add-brick")
        parsed = self._parse_new_bricks(vol, bricks)
        results = await self._cluster_txn(
            "add-brick", {"name": name, "bricks": parsed,
                          "group_size": group_size})
        if vol["status"] == "started":
            ports: dict[str, int] = {}
            for r in results:
                ports.update(r.get("result", {}).get("ports", {}))
            for node in self._all_nodes():
                try:
                    await self._node_call(node, "portmap-update",
                                          name=name, ports=ports)
                except Exception:
                    pass
        return {"ok": True, "added": [b["name"] for b in parsed]}

    def stage_add_brick(self, name: str, bricks: list,
                        group_size: int = 0) -> None:
        vol = self._vol(name)
        have = {b["name"] for b in vol["bricks"]}
        if any(b["name"] in have for b in bricks):
            raise MgmtError("brick name collision")

    async def commit_add_brick(self, name: str, bricks: list,
                               group_size: int = 0) -> dict:
        vol = self._vol(name)
        if group_size and "group-size" not in vol:
            # first growth of a single-group volume fixes the group
            # size so volgen starts emitting the dht aggregate
            vol["group-size"] = group_size
        vol["bricks"].extend(bricks)
        self._bump(vol)
        self._save()
        if vol["status"] == "started":
            for b in bricks:
                if b["node"] == self.uuid:
                    await self._spawn_brick(vol, b)
            self._notify_subscribers(name)  # topology change: graph swap
        gf_event("VOLUME_ADD_BRICK", name=name,
                 bricks=[b["name"] for b in bricks])
        return {"added": [b["name"] for b in bricks],
                "ports": {b["name"]: self.ports[b["name"]]
                          for b in bricks
                          if b["name"] in self.ports}}

    async def op_volume_remove_brick(self, name: str, bricks: list,
                                     action: str = "start") -> dict:
        """``volume remove-brick start|status|commit`` — shrink the
        volume: start excludes the leaving bricks from the dht layout
        and drains their data (decommission rebalance,
        dht-rebalance.c); commit drops them once drained."""
        vol = self._vol(name)
        rb = vol.get("remove-brick") or {}
        if action == "status":
            return dict(rb) or {"status": "not-started"}
        if action == "start":
            if vol["status"] != "started":
                # the drain migrates THROUGH a mounted client; on a
                # stopped volume it would no-op "completed" and a
                # later commit would silently drop un-drained data
                raise MgmtError("volume must be started to drain "
                                "bricks (remove-brick start)")
            if rb.get("status") == "started":
                raise MgmtError("a remove-brick is already in "
                                "progress; commit or wait first")
            if (vol.get("rebalance") or {}).get("status") == "started":
                # the drain rides the SAME daemon slot: starting it
                # under a live full rebalance would clobber that run's
                # record while the old daemon keeps walking (and its
                # next checkpoint push would flip the mode back,
                # stranding the remove-brick record 'started' forever)
                raise MgmtError("a rebalance is in progress; stop it "
                                "before remove-brick start")
            if self.cluster_op_version() < 13:
                # the drain rides the rebalance daemon machinery
                # (rebalance-start txn + rebalance-update pushes): a
                # v12 peer has neither op, and failing mid-txn-pair
                # would strand remove-brick 'started' with no daemon
                # draining it.  Re-handshake before refusing (the
                # volume-set ladder's pattern).
                await self._refresh_peers()
            if self.cluster_op_version() < 13:
                raise MgmtError(
                    "remove-brick start needs cluster op-version "
                    f">= 13 (cluster is at {self.cluster_op_version()})")
            leaving = set(bricks or ())
            have = {b["name"] for b in vol["bricks"]}
            if not leaving or not leaving <= have:
                raise MgmtError(f"unknown bricks {sorted(leaving - have)}")
            if len(leaving) >= len(have):
                raise MgmtError("cannot remove every brick")
            if vol["type"] in ("disperse", "replicate"):
                g = self._group_size(vol)
                if len(leaving) % g:
                    raise MgmtError(
                        f"remove-brick on a {vol['type']} volume "
                        f"drains whole groups of {g}")
                ordered = [b["name"] for b in vol["bricks"]]
                for j in range(0, len(ordered), g):
                    grp = set(ordered[j:j + g])
                    if grp & leaving and not grp <= leaving:
                        raise MgmtError("partial group in remove-brick")
            await self._cluster_txn("remove-brick-start", {
                "name": name, "bricks": sorted(leaving)})
            # the drain IS a rebalance: the managed daemon walks the
            # namespace in drain mode (decommissioned children are
            # already excluded from placement, dht.py:88-90), so
            # shrink gets status/stop/checkpoints for free
            await self._cluster_txn("rebalance-start", {
                "name": name, "mode": "drain", "node": self.uuid,
                "ts": time.time()})
            return {"ok": True, "status": "started"}
        if action == "stop":
            if rb.get("status") != "started":
                raise MgmtError("no remove-brick in progress")
            await self._cluster_txn("remove-brick-stop", {"name": name})
            gf_event("REBALANCE_STOPPED", name=name, mode="drain")
            return {"ok": True, "status": "stopped"}
        if action in ("commit", "force"):
            if not rb:
                raise MgmtError("no remove-brick in progress")
            if rb.get("status") != "completed" and action != "force":
                raise MgmtError(
                    f"migration {rb.get('status')!r}; wait or use force")
            await self._cluster_txn("remove-brick-commit",
                                    {"name": name})
            return {"ok": True, "removed": rb.get("bricks", [])}
        raise MgmtError(f"unknown remove-brick action {action!r}")

    def commit_remove_brick_start(self, name: str,
                                  bricks: list) -> dict:
        vol = self._vol(name)
        vol["remove-brick"] = {"status": "started", "bricks": bricks}
        self._bump(vol)
        self._save()
        if vol["status"] == "started":
            self._notify_subscribers(name)  # layout excludes leavers
        return {"draining": bricks}

    def commit_remove_brick_stop(self, name: str) -> dict:
        """Abort a shrink: kill the drain daemon and drop the
        decommission so the leavers re-join the layout (the
        reference's remove-brick stop restores the node map)."""
        vol = self._vol(name)
        self._kill_rebalanced(name)
        vol.pop("remove-brick", None)
        reb = vol.get("rebalance")
        if reb is not None and reb.get("mode") == "drain" and \
                reb.get("status") == "started":
            reb["status"] = "stopped"
        self._bump(vol)
        self._save()
        if vol["status"] == "started":
            self._notify_subscribers(name)  # leavers re-enter layout
        return {"stopped": name}

    async def commit_remove_brick_commit(self, name: str) -> dict:
        vol = self._vol(name)
        rb = vol.pop("remove-brick", None) or {}
        leaving = set(rb.get("bricks") or ())
        keep, gone = [], []
        for b in vol["bricks"]:
            (gone if b["name"] in leaving else keep).append(b)
        vol["bricks"] = keep
        self._bump(vol)
        self._save()
        for b in gone:
            if b["node"] == self.uuid:
                await self._stop_brick(vol, b)
        if vol["status"] == "started":
            self._notify_subscribers(name)
        gf_event("VOLUME_REMOVE_BRICK", name=name,
                 bricks=sorted(leaving))
        return {"removed": sorted(leaving)}

    async def op_volume_replace_brick(self, name: str, brick: str,
                                      new_path: str) -> dict:
        """``volume replace-brick ... commit force`` — swap a brick for
        an empty one; the self-heal daemon rebuilds its content from
        the surviving replicas/fragments (glusterd-replace-brick.c +
        full heal)."""
        vol = self._vol(name)
        if vol["type"] not in ("replicate", "disperse"):
            raise MgmtError("replace-brick needs a replicate or "
                            "disperse volume (distribute would lose "
                            "that brick's data)")
        if not any(b["name"] == brick for b in vol["bricks"]):
            raise MgmtError(f"no brick {brick!r} in {name}")
        results = await self._cluster_txn("replace-brick", {
            "name": name, "brick": brick, "new_path": new_path})
        if vol["status"] == "started":
            # the replacement bound a fresh port on its node: broadcast
            # it (volume-start's pmap sync) so peers' volfiles carry it
            ports: dict[str, int] = {}
            for r in results:
                ports.update(r.get("result", {}).get("ports", {}))
            for node in self._all_nodes():
                try:
                    await self._node_call(node, "portmap-update",
                                          name=name, ports=ports)
                except Exception:
                    pass
            # rebuild the empty brick NOW (the reference triggers a
            # full self-heal on replace); shd's crawl also covers it
            self._spawn_task(self._heal_full(name))
        return {"ok": True, "replaced": brick, "path": new_path}

    async def commit_replace_brick(self, name: str, brick: str,
                                   new_path: str) -> dict:
        vol = self._vol(name)
        b = next(x for x in vol["bricks"] if x["name"] == brick)
        if b["node"] == self.uuid and b["name"] in self.bricks:
            await self._stop_brick(vol, b)
        b["path"] = new_path
        b.pop("port", None)
        self._bump(vol)
        self._save()
        if vol["status"] == "started" and b["node"] == self.uuid:
            await self._spawn_brick(vol, b)
            self._notify_subscribers(name)
        gf_event("VOLUME_REPLACE_BRICK", name=name, brick=brick)
        # only the HOSTING node reports a port: peers still hold the
        # old port in self.ports and would overwrite the fresh one in
        # the originator's last-write-wins merge
        ports = {}
        if b["node"] == self.uuid and brick in self.ports:
            ports[brick] = self.ports[brick]
        return {"replaced": brick, "ports": ports}

    async def _heal_full(self, name: str) -> None:
        try:
            from . import shd as shd_mod

            client = await mount_volume(self.host, self.port, name)
            try:
                await shd_mod.full_crawl(
                    client, max_heals=self._shd_max_heals(self._vol(name)))
            finally:
                await client.unmount()
        except Exception as e:
            log.warning(22, "post-replace heal of %s: %r", name, e)

    # -- rebalance daemon lifecycle (glusterd-rebalance.c analog) ----------
    # ``volume rebalance NAME start[ fix-layout]|status|stop`` — a
    # per-volume daemon owned by the starting node, spawned like the
    # gateway/shd service daemons, reporting resumable checkpoints back
    # into the volinfo over the rebalance-update RPC: SIGKILL + respawn
    # CONTINUES the walk from the last completed directory, never
    # restarts it.

    async def op_volume_rebalance(self, name: str,
                                  action: str = "status",
                                  flavor: str = "") -> dict:
        vol = self._vol(name)
        if action == "status":
            return await self._rebalance_status(vol)
        if action not in ("start", "stop"):
            raise MgmtError(f"bad rebalance action {action!r} "
                            "(want start|status|stop)")
        if self.cluster_op_version() < 13:
            # stored versions are probe-time snapshots: re-handshake
            # before refusing (the volume-set ladder's pattern)
            await self._refresh_peers()
        if self.cluster_op_version() < 13:
            raise MgmtError(
                "volume rebalance needs cluster op-version >= 13 "
                f"(cluster is at {self.cluster_op_version()})")
        rb = vol.get("rebalance") or {}
        if action == "stop":
            if rb.get("status") != "started":
                raise MgmtError("no rebalance in progress")
            if rb.get("mode") == "drain":
                # stopping the drain daemon without dropping the
                # decommission would strand remove-brick 'started'
                # with nothing draining it — the remove-brick stop op
                # owns that cleanup
                raise MgmtError("this rebalance is a remove-brick "
                                "drain; use `volume remove-brick ... "
                                "stop`")
            await self._cluster_txn("rebalance-stop", {"name": name})
            gf_event("REBALANCE_STOPPED", name=name,
                     mode=rb.get("mode", "full"))
            return {"ok": True, "status": "stopped",
                    "checkpoint": (self._vol(name).get("rebalance")
                                   or {}).get("checkpoint")}
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        if flavor not in ("", "fix-layout"):
            raise MgmtError(f"bad rebalance flavor {flavor!r} "
                            "(only fix-layout)")
        if (vol.get("remove-brick") or {}).get("status") == "started":
            raise MgmtError("a remove-brick drain is in progress; its "
                            "daemon IS a rebalance — wait or stop it")
        mode = flavor or "full"
        if rb.get("status") == "started":
            proc = self.rebalanced.get(name)
            if proc is not None and proc.poll() is None:
                raise MgmtError("rebalance already in progress")
            if rb.get("node") != self.uuid:
                raise MgmtError(
                    "rebalance owned by node "
                    f"{(rb.get('node') or '?')[:8]}; start it there")
            # dead daemon (SIGKILL, crash): respawn — the checkpoint
            # in the volinfo makes this a RESUME, never a restart
            self._spawn_rebalanced(vol)
            return {"ok": True, "status": "resumed",
                    "checkpoint": rb.get("checkpoint")}
        await self._cluster_txn("rebalance-start", {
            "name": name, "mode": mode, "node": self.uuid,
            "ts": time.time()})
        return {"ok": True, "status": "started", "mode": mode}

    @staticmethod
    def _rebal_topology(vol: dict) -> dict:
        """What a rebalance checkpoint is valid AGAINST: the brick set
        and (for drain) which bricks are leaving.  A checkpoint taken
        under one topology must never steer a run under another —
        resuming a pre-add-brick checkpoint skips fix-layout for the
        new leg, and resuming drain-A's checkpoint for drain-B never
        scans B's files and a later commit drops them undrained."""
        return {"bricks": sorted(b["name"] for b in vol["bricks"]),
                "drain": sorted((vol.get("remove-brick") or {})
                                .get("bricks") or ())}

    def commit_rebalance_start(self, name: str, mode: str, node: str,
                               ts: float) -> dict:
        vol = self._vol(name)
        prev = vol.get("rebalance") or {}
        rb = {"status": "started", "mode": mode, "node": node,
              "started": ts, "topology": self._rebal_topology(vol)}
        if prev.get("status") == "stopped" and \
                prev.get("mode") == mode and prev.get("checkpoint") \
                and prev.get("topology") == rb["topology"]:
            # stop -> start continues from the stop's checkpoint (the
            # counters ride inside it) — but ONLY under the same
            # topology it was taken against
            rb["checkpoint"] = prev["checkpoint"]
        vol["rebalance"] = rb
        self._bump(vol)
        self._save()
        if node == self.uuid and vol["status"] == "started":
            self._spawn_rebalanced(vol)
            gf_event("REBALANCE_START", name=name, mode=mode)
        return {"rebalance": mode}

    def commit_rebalance_stop(self, name: str) -> dict:
        vol = self._vol(name)
        rb = vol.get("rebalance") or {}
        if rb.get("node") == self.uuid:
            # SIGTERM: the daemon pushes a final stopped update with
            # its checkpoint before exiting; the stamp below covers a
            # daemon that was already dead
            self._kill_rebalanced(name)
        if rb.get("status") == "started":
            rb["status"] = "stopped"
        self._bump(vol)
        self._save()
        return {"stopped": name}

    async def _rebalance_status(self, vol: dict) -> dict:
        """Per-node daemon state fan-out merged like ``volume status``
        (the defrag status aggregation of glusterd-rebalance.c), with
        unreachable nodes NAMED in ``partial``."""
        name = vol["name"]
        rb = dict(vol.get("rebalance") or {"status": "not-started"})
        nodes = {n["uuid"]: n for n in self._vol_nodes(vol)}
        owner = rb.get("node")
        if owner and owner not in nodes:
            for n in self._all_nodes():
                if n["uuid"] == owner:
                    nodes[owner] = n
        per_node, partial = await self._gather_bricks(
            "volume-rebalance-local", nodes=list(nodes.values()),
            name=name)
        for row in per_node.values():
            if row.get("owner") and row.get("rebalance"):
                # the owner's row carries the freshest pushed state
                rb = row["rebalance"]
        return self._merge_partial(
            {"volume": name, "rebalance": rb, "nodes": per_node},
            partial)

    def op_volume_rebalance_local(self, name: str) -> dict:
        """One node's share of rebalance status: its daemon liveness
        plus its volinfo view (rides the _gather_bricks merge, keyed
        by node id)."""
        vol = self._vol(name)
        rb = vol.get("rebalance") or {}
        proc = self.rebalanced.get(name)
        online = proc is not None and proc.poll() is None
        row: dict[str, Any] = {
            "online": online, "pid": proc.pid if online else 0,
            "owner": bool(rb) and rb.get("node") == self.uuid}
        if rb:
            row["rebalance"] = dict(rb)
        return {"bricks": {self.uuid[:8]: row}}

    async def op_rebalance_update(self, name: str, info: dict) -> dict:
        """The daemon (or the owner's terminal fan-out) pushes
        rebalance progress into the volinfo; CHECKPOINTS land here,
        which is what makes SIGKILL + respawn resume."""
        vol = self._vol(name)
        rb = vol.get("rebalance")
        if rb is None:
            rb = vol["rebalance"] = {}
        rb.update(info)
        terminal = info.get("status") in ("completed", "failed",
                                          "stopped")
        if rb.get("mode") == "drain":
            self._mirror_drain(vol, rb, info)
        if terminal:
            self._bump(vol)
            self._save()
        else:
            # checkpoint pushes can arrive many times a second; the
            # in-memory volinfo is what status ops and a daemon
            # respawn read, so persist at most once a second (a
            # glusterd CRASH resumes from a slightly older checkpoint
            # — the walk is idempotent)
            now = time.monotonic()
            if now - self._rb_saved.get(name, 0.0) >= 1.0:
                self._rb_saved[name] = now
                self._save()
        if terminal and rb.get("node") == self.uuid:
            # propagate terminal state so status/commit addressed to
            # ANY node sees it; peers that miss the push catch up via
            # peer-hello volinfo reconciliation (the generation bumped)
            for node in self._all_nodes():
                if node["uuid"] == self.uuid:
                    continue
                try:
                    await asyncio.wait_for(self._node_call(
                        node, "rebalance-update", name=name,
                        info=dict(rb)), 10)
                except Exception:
                    pass
        return {"ok": True}

    def _mirror_drain(self, vol: dict, rb: dict, info: dict) -> None:
        """A drain-mode rebalance IS the remove-brick migration: its
        progress and terminal state land on the remove-brick record
        that ``remove-brick status``/``commit`` read."""
        rbk = vol.get("remove-brick")
        if rbk is None:
            return
        ctr = rb.get("counters") or {}
        rbk["progress"] = {"phase": rb.get("phase", ""), **ctr}
        status = info.get("status")
        if status == "completed":
            rbk["status"] = "completed"
            rbk["moved"] = ctr.get("moved", 0)
            rbk["scanned"] = ctr.get("scanned", 0)
        elif status == "failed":
            rbk["status"] = "failed"
            rbk["error"] = rb.get("error", "")

    def _spawn_rebalanced(self, vol: dict) -> None:
        name = vol["name"]
        proc = self.rebalanced.get(name)
        if proc is not None and proc.poll() is None:
            return
        rb = vol.get("rebalance") or {}
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        statusfile = os.path.join(self.workdir,
                                  f"rebalanced-{name}.json")
        if not rb.get("checkpoint"):
            # a FRESH run must not inherit a previous run's
            # statusfile: the daemon only writes it at its first push
            # (after the mount settles), and a stop before that would
            # harvest the OLD run's checkpoint into this record —
            # whose topology stamp is this run's own, so the
            # fingerprint guard cannot catch the swap
            try:
                os.unlink(statusfile)
            except OSError:
                pass
        with open(os.path.join(self.workdir, f"rebalanced-{name}.log"),
                  "ab") as logf:
            self.rebalanced[name] = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.mgmt.rebalanced",
                 "--glusterd", f"{self.host}:{self.port}",
                 "--volname", name,
                 "--mode", rb.get("mode", "full"),
                 "--statusfile", statusfile],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_rebalanced(self, name: str) -> None:
        proc = self.rebalanced.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
            # the daemon's final rebalance-update cannot land while
            # THIS loop is blocked in wait() (the daemon bounds that
            # push and exits) — its statusfile carries the same final
            # checkpoint, so harvest it here to keep the
            # stop-continues-from-the-stop's-checkpoint contract
            self._harvest_rebal_statusfile(name)

    def _harvest_rebal_statusfile(self, name: str) -> None:
        vol = self.state["volumes"].get(name)
        if vol is None or not (vol.get("rebalance") or {}).get("node"):
            return
        rb = vol["rebalance"]
        if rb.get("node") != self.uuid or \
                rb.get("status") == "completed":
            return
        try:
            with open(os.path.join(
                    self.workdir, f"rebalanced-{name}.json")) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return
        for k in ("checkpoint", "counters", "phase"):
            if k in snap:
                rb[k] = snap[k]

    def _snap_volinfo_by_name(self, volname: str) -> dict | None:
        for s in self.state.get("snaps", {}).values():
            vi = s.get("volinfo")
            if vi and vi["name"] == volname:
                return vi
        return None

    def op_getspec(self, name: str) -> dict:
        """Serve the client volfile (__server_getspec analog); activated
        snapshots are served like volumes (snapd's volfile)."""
        vol = self.state["volumes"].get(name)
        is_snap = False
        if vol is None:
            vol = self._snap_volinfo_by_name(name)
            is_snap = vol is not None
        if vol is None:
            raise MgmtError(f"no volume {name!r}")
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        # no /.snaps inside a snapshot; classification is by identity
        # ('snap-' user volume names are refused at create)
        mgmt = None if is_snap else f"{self.host}:{self.port}"
        return {"volfile": volgen.build_client_volfile(
                    vol, self.ports, mgmt=mgmt),
                "volname": name}

    def _vol(self, name: str) -> dict:
        vol = self.state["volumes"].get(name)
        if vol is None:
            raise MgmtError(f"no volume {name!r}")
        return vol

    # -- snapshots (glusterd-snapshot.c analog, store-level) ---------------
    # The reference snapshots LVM thin volumes; the TPU-build store is a
    # plain directory, so a snapshot is a barriered full copy of each
    # brick store (SURVEY §7's store-level checkpoint), restorable onto
    # a stopped volume.

    async def op_snapshot_create(self, name: str, volume: str) -> dict:
        self._vol(volume)
        if name in self.state.setdefault("snaps", {}):
            raise MgmtError(f"snapshot {name} exists")
        # three cluster-wide phases, reference glusterd-snapshot.c order:
        # barrier EVERY node's bricks, then copy everywhere, then
        # release — a write landing between one node's copy and
        # another's would otherwise make replicas/stripe-groups diverge
        # inside one snapshot
        await self._cluster_txn("snapshot-barrier",
                                {"volume": volume, "on": True})
        try:
            await self._cluster_txn("snapshot-create",
                                    {"name": name, "volume": volume})
        finally:
            await self._cluster_txn("snapshot-barrier",
                                    {"volume": volume, "on": False})
        return {"ok": True, "snapshot": name}

    async def commit_snapshot_barrier(self, volume: str, on: bool) -> dict:
        vol = self._vol(volume)
        if vol["status"] != "started":
            return {"barriered": False}
        if on:
            await self._set_barrier(vol, True)
            await self._await_barrier_drain(vol)
            # eager-window quiesce: clients hold inodelks with DELAYED
            # post-ops (post-op-delay semantics) — data is on the bricks
            # but size/version commit on a timer.  Fire a contention
            # upcall at every held lock (the same signal a conflicting
            # locker sends, ec_lock_release on INODELK_CONTENTION) and
            # wait for the holders to commit + release, so the snapshot
            # captures settled counters, not a crash image needing heal.
            await self._quiesce_client_locks(vol)
        else:
            await self._set_barrier(vol, False, strict=False)
        return {"barriered": on}

    async def _quiesce_client_locks(self, vol: dict,
                                    timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        for b in vol["bricks"]:
            if b["node"] != self.uuid or b["name"] not in self.bricks:
                continue
            port = self.ports.get(b["name"])
            if not port:
                continue
            try:
                await self._brick_call(vol, port, "contend_held_locks",
                                       [], subvol=b["name"] + "-server")
            except Exception:
                continue  # old/bare brick: crash-consistent copy
            while time.monotonic() < deadline:
                dump = await self._brick_statedump(
                    vol, port, subvol=b["name"] + "-server")
                layers = (dump or {}).get("layers", {})
                granted = [l["private"].get("granted", 0)
                           for l in layers.values()
                           if l.get("type") == "features/locks"]
                if granted and sum(granted) == 0:
                    break
                await asyncio.sleep(0.05)

    def stage_snapshot_create(self, name: str, volume: str) -> None:
        # per-node duplicate check: snapshot state is per-node, and a
        # half-committed earlier attempt must fail the retry here in
        # stage — commit's failure cleanup may only ever delete
        # directories this run created
        if name in self.state.get("snaps", {}):
            raise MgmtError(f"snapshot {name} exists on {self.uuid[:8]}")
        if os.path.exists(os.path.join(self.workdir, "snaps", name)):
            raise MgmtError(f"stale snapshot dir for {name!r}; "
                            "delete the snapshot first")

    async def commit_snapshot_create(self, name: str, volume: str) -> dict:
        import shutil

        from ..storage.posix import snapshot_copy

        vol = self._vol(volume)
        snapdir = os.path.join(self.workdir, "snaps", name)
        os.makedirs(snapdir, exist_ok=True)
        try:
            taken = {}
            for b in vol["bricks"]:
                if b["node"] != self.uuid:
                    continue
                dst = os.path.join(snapdir, b["name"])
                await asyncio.to_thread(snapshot_copy, b["path"], dst)
                taken[b["name"]] = dst
        except BaseException:
            # no partial snapshot may survive: a retry of the same name
            # would hit copytree FileExistsError with no way out.
            # (Safe to remove the whole dir: stage proved it did not
            # pre-exist, so everything under it is ours.)
            await asyncio.to_thread(shutil.rmtree, snapdir,
                                    ignore_errors=True)
            raise
        self.state.setdefault("snaps", {})[name] = {
            "volume": volume, "ts": time.time(), "bricks": taken,
            # the volume's SHAPE at snap time: restore/clone must pair
            # snapped stores with the geometry they were taken under,
            # not whatever the volume grew into afterwards
            "src_volinfo": json.loads(json.dumps(vol)),
        }
        self._save()
        gf_event("SNAPSHOT_CREATED", snapshot=name, volume=volume)
        return {"snapped": sorted(taken)}

    # -- snapshot clone (glusterd-snapshot.c clone: a snapshot becomes a
    # NEW independent writable volume) -------------------------------------

    async def op_snapshot_clone(self, clonename: str,
                                snapname: str) -> dict:
        snap = self.state.get("snaps", {}).get(snapname)
        if snap is None:
            raise MgmtError(f"no snapshot {snapname!r}")
        if clonename in self.state["volumes"]:
            raise MgmtError(f"volume {clonename} exists")
        if clonename.startswith("snap-"):
            raise MgmtError("volume names starting with 'snap-' are "
                            "reserved for activated snapshots")
        base = snap.get("src_volinfo") or self._vol(snap["volume"])
        nodes = {n["uuid"]: n for n in self._all_nodes()}
        bricks, sources = [], {}
        for i, b in enumerate(base["bricks"]):
            node = nodes.get(b["node"])
            if node is None:
                raise MgmtError(f"brick node {b['node'][:8]} unknown")
            bname = f"{clonename}-brick-{i}"
            bricks.append({
                "index": i, "node": b["node"], "host": b["host"],
                "path": os.path.join(node["workdir"], "clones",
                                     clonename, bname),
                "name": bname,
            })
            sources[bname] = b["name"]
        volinfo = _new_volinfo(self.state, clonename, base["type"],
                               bricks, base.get("redundancy", 0))
        volinfo["options"] = dict(base.get("options", {}))
        # systematic rides along: the clone serves the snapped
        # FRAGMENTS, and the fragment format is a property of those
        # bytes — a non-systematic volfile over systematic fragments
        # decodes to garbage (and vice versa)
        for key in ("group-size", "arbiter", "thin-arbiter",
                    "systematic"):
            if key in base:
                volinfo[key] = base[key]
        await self._cluster_txn("snapshot-clone", {
            "snapname": snapname, "volinfo": volinfo,
            "sources": sources})
        return {"ok": True, "volume": clonename}

    def stage_snapshot_clone(self, snapname: str, volinfo: dict,
                             sources: dict) -> None:
        """Per-node validation BEFORE any store copies: a commit-phase
        failure on one node would leave a half-created clone that
        reconciliation then spreads cluster-wide with an empty brick."""
        if volinfo["name"] in self.state["volumes"]:
            raise MgmtError(f"volume {volinfo['name']} exists here")
        snap = self.state.get("snaps", {}).get(snapname) or {}
        for b in volinfo["bricks"]:
            if b["node"] != self.uuid:
                continue
            src = snap.get("bricks", {}).get(sources.get(b["name"], ""))
            if not src or not os.path.isdir(src):
                raise MgmtError(
                    f"no snapped store for {b['name']} on this node")

    async def commit_snapshot_clone(self, snapname: str, volinfo: dict,
                                    sources: dict) -> dict:
        snap = self.state.get("snaps", {}).get(snapname) or {}
        cloned = []
        for b in volinfo["bricks"]:
            if b["node"] != self.uuid:
                continue
            src = snap.get("bricks", {}).get(sources.get(b["name"], ""))
            if not src:
                raise MgmtError(
                    f"no snapped store for {b['name']} on this node")
            await asyncio.to_thread(_copy_store, src, b["path"])
            cloned.append(b["name"])
        self.state["volumes"][volinfo["name"]] = volinfo
        self.state.get("tombstones", {}).pop(volinfo["name"], None)
        self._save()
        gf_event("SNAPSHOT_CLONED", snapshot=snapname,
                 volume=volinfo["name"])
        return {"cloned": cloned}

    async def _set_barrier(self, vol: dict, on: bool,
                           strict: bool = True) -> None:
        """Arm/release the barrier on this node's running bricks via
        live reconfigure (glusterd_snap_brick_barrier analog).  strict:
        a failed arm raises — copying an unquiesced brick would produce
        a torn snapshot reported as success.  Release is best-effort
        (the barrier timeout unwedges a brick we could not reach)."""
        tmp = dict(vol)
        tmp["options"] = dict(vol.get("options", {}))
        tmp["options"]["features.barrier"] = "on" if on else "off"
        for b in vol["bricks"]:
            if b["node"] != self.uuid or b["name"] not in self.bricks:
                continue
            port = self.ports.get(b["name"])
            ok = bool(port) and await self._brick_reconfigure(
                vol, port, volgen.build_brick_volfile(tmp, b),
                subvol=b["name"] + "-server")
            if not ok and strict:
                raise MgmtError(
                    f"could not {'arm' if on else 'release'} barrier on "
                    f"brick {b['name']}")

    async def _await_barrier_drain(self, vol: dict,
                                   timeout: float = 10.0) -> None:
        """Wait until every running brick's barrier layer reports zero
        in-flight gated fops (writes that passed the gate before it was
        armed are still mutating the store; copying under them tears
        the snapshot)."""
        deadline = time.monotonic() + timeout
        for b in vol["bricks"]:
            if b["node"] != self.uuid or b["name"] not in self.bricks:
                continue
            port = self.ports.get(b["name"])
            if not port:
                continue
            while True:
                dump = await self._brick_statedump(
                    vol, port, subvol=b["name"] + "-server")
                layers = (dump or {}).get("layers", {})
                inflight = [l["private"].get("inflight", 0)
                            for l in layers.values()
                            if l.get("type") == "features/barrier"]
                # a dump with no barrier layer would vacuously "drain";
                # treat it as not-quiesced so the bug surfaces as a
                # timeout, not a torn snapshot
                if dump is not None and inflight and \
                        all(n == 0 for n in inflight):
                    break
                if time.monotonic() > deadline:
                    raise MgmtError(
                        f"brick {b['name']} did not quiesce in "
                        f"{timeout:.0f}s")
                await asyncio.sleep(0.02)

    @classmethod
    async def _brick_statedump(cls, vol: dict, port: int,
                               subvol: str = "") -> dict | None:
        try:
            return await cls._brick_call(vol, port, "__statedump__", [],
                                         subvol=subvol)
        except Exception:
            return None

    def op_snapshot_list(self, volume: str | None = None) -> dict:
        snaps = self.state.get("snaps", {})
        out = {n: {"volume": s["volume"], "ts": s["ts"],
                   "bricks": sorted(s["bricks"]),
                   "activated": bool(s.get("volinfo"))}
               for n, s in snaps.items()
               if volume is None or s["volume"] == volume}
        return {"snapshots": out}

    # -- USS: snapshot activate/deactivate (the snapd analog: a
    # snapshot becomes a served read-only volume the snapview layer
    # mounts under /.snaps) ------------------------------------------------

    def _snap_volname(self, name: str) -> str:
        return f"snap-{name}"

    async def op_snapshot_activate(self, name: str) -> dict:
        snap = self.state.get("snaps", {}).get(name)
        if snap is None:
            raise MgmtError(f"no snapshot {name!r}")
        if snap.get("volinfo"):
            return {"ok": True, "already": True}
        parent = self._vol(snap["volume"])
        vi = json.loads(json.dumps(parent))  # deep, store-safe copy
        sv = self._snap_volname(name)
        vi["name"] = sv
        vi["status"] = "started"
        bricks = []
        for b in vi["bricks"]:
            src = snap["bricks"].get(b["name"])
            if src is None:
                continue  # brick lived on another node
            nb = dict(b)
            nb["path"] = src
            nb["name"] = f"{sv}-brick-{b['index']}"
            nb.pop("port", None)
            bricks.append(nb)
        if not bricks:
            raise MgmtError("no local snapshot bricks to activate")
        if len(bricks) < len(parent["bricks"]):
            # partial activation would serve silently-partial history
            # (distribute) or fail every read (disperse < k fragments)
            raise MgmtError(
                "snapshot bricks incomplete on this node: "
                f"{len(bricks)}/{len(parent['bricks'])} "
                "(multi-node snapshot activation is not supported)")
        vi["bricks"] = bricks
        # the snapshot is a file-level copy: rebind the gfid identity
        # store onto the copied inodes before serving (restore does the
        # same; LVM snapshots in the reference keep inodes so skip it)
        from ..storage.posix import rebuild_identity

        for b in bricks:
            await asyncio.to_thread(rebuild_identity, b["path"])
        # a snapshot is immutable history: read-only, no journals or
        # background services
        opts = vi.setdefault("options", {})
        opts["features.read-only"] = "on"
        for k in ("changelog.changelog", "features.bitrot",
                  "features.quota"):
            opts.pop(k, None)
        spawned = []
        try:
            for b in bricks:
                proc = self.bricks.get(b["name"])
                if proc is not None and proc.poll() is None:
                    continue  # a retry after partial failure
                await self._spawn_brick(vi, b)
                spawned.append(b)
        except BaseException:
            # no half-activated snapshot: stop what we started (detach,
            # not kill, when multiplexed — the shared daemon serves
            # other volumes' bricks too)
            for b_ in spawned:
                await self._stop_brick(vi, b_)
            raise
        snap["volinfo"] = vi
        self._save()
        gf_event("SNAPSHOT_ACTIVATED", snapshot=name)
        return {"ok": True, "volume": sv}

    async def op_snapshot_deactivate(self, name: str) -> dict:
        snap = self.state.get("snaps", {}).get(name)
        if snap is None:
            raise MgmtError(f"no snapshot {name!r}")
        vi = snap.pop("volinfo", None)
        if vi:
            for b in vi["bricks"]:
                await self._stop_brick(vi, b)
                self.ports.pop(b["name"], None)
        self._save()
        return {"ok": True}

    async def op_snapshot_delete(self, name: str) -> dict:
        if name not in self.state.get("snaps", {}):
            raise MgmtError(f"no snapshot {name!r}")
        await self._cluster_txn("snapshot-delete", {"name": name})
        return {"ok": True}

    async def commit_snapshot_delete(self, name: str) -> dict:
        import shutil

        if self.state.get("snaps", {}).get(name, {}).get("volinfo"):
            await self.op_snapshot_deactivate(name)
        snap = self.state.get("snaps", {}).pop(name, None)
        self._save()
        if snap:
            await asyncio.to_thread(
                shutil.rmtree, os.path.join(self.workdir, "snaps", name),
                ignore_errors=True)
        return {"deleted": name}

    async def op_snapshot_restore(self, name: str) -> dict:
        snap = self.state.get("snaps", {}).get(name)
        if snap is None:
            raise MgmtError(f"no snapshot {name!r}")
        vol = self._vol(snap["volume"])
        if vol["status"] == "started":
            raise MgmtError("stop the volume before restore")
        await self._cluster_txn("snapshot-restore", {"name": name})
        return {"ok": True, "restored": snap["volume"]}

    async def commit_snapshot_restore(self, name: str) -> dict:
        snap = self.state.get("snaps", {}).get(name)
        if snap is None:
            return {"restored": []}
        vol = self._vol(snap["volume"])
        # restore rolls the volume's SHAPE back to snap time too (the
        # reference swaps in the snapshot's volinfo wholesale): a volume
        # grown after the snapshot must not end up with bricks from two
        # epochs — snap-time content on the old bricks, post-snap
        # content on the new ones — serving inconsistent stripes
        src_vi = snap.get("src_volinfo")
        if src_vi is not None:
            for key in ("type", "bricks", "redundancy", "group-size",
                        "arbiter", "thin-arbiter"):
                if key in src_vi:
                    vol[key] = json.loads(json.dumps(src_vi[key]))
                else:
                    vol.pop(key, None)
            self._bump(vol)
            self._save()
        restored = []
        for b in vol["bricks"]:
            src = snap["bricks"].get(b["name"])
            if b["node"] != self.uuid or not src:
                continue
            await asyncio.to_thread(_copy_store, src, b["path"])
            restored.append(b["name"])
        return {"restored": restored}

    # -- bit-rot (glusterd-bitrot.c op handlers analog) --------------------

    async def op_volume_bitrot(self, name: str, action: str) -> dict:
        """enable / disable / status / scrub-status for bit-rot
        detection on a volume."""
        vol = self._vol(name)
        if action == "enable":
            await self._cluster_txn("volume-set", {
                "name": name, "key": "features.bitrot", "value": "on"})
            # spawn on EVERY node holding bricks, not just the originator
            await self._cluster_txn("bitrot-ctl",
                                    {"name": name, "action": "spawn"})
            return {"ok": True, "enabled": name}
        if action == "disable":
            await self._cluster_txn("bitrot-ctl",
                                    {"name": name, "action": "kill"})
            await self._cluster_txn("volume-set", {
                "name": name, "key": "features.bitrot", "value": "off"})
            return {"ok": True, "disabled": name}
        if action in ("status", "scrub-status"):
            proc = self.bitd.get(name)
            out = {"online": proc is not None and proc.poll() is None}
            try:
                with open(os.path.join(self.workdir,
                                       f"bitd-{name}.json")) as f:
                    out.update(json.load(f))
            except (FileNotFoundError, ValueError):
                pass
            return out
        raise MgmtError(f"unknown bitrot action {action!r}")

    def commit_bitrot_ctl(self, name: str, action: str) -> dict:
        vol = self._vol(name)
        if action == "spawn":
            if vol["status"] == "started":
                self._spawn_bitd(vol)
        else:
            self._kill_bitd(name)
        return {action: name}

    # -- quota (quota.c enforcement + quotad-aggregator.c) -----------------

    async def op_volume_quota(self, name: str, action: str,
                              path: str = "", limit: int = 0) -> dict:
        """gluster volume quota <v> enable|disable|limit-usage|remove|
        list analog."""
        self._vol(name)
        if action == "enable":
            await self._cluster_txn("volume-set", {
                "name": name, "key": "features.quota", "value": "on"})
            await self._cluster_txn("quota-ctl",
                                    {"name": name, "action": "spawn"})
            return {"ok": True, "enabled": name}
        if action == "disable":
            await self._cluster_txn("quota-ctl",
                                    {"name": name, "action": "kill"})
            await self._cluster_txn("volume-set", {
                "name": name, "key": "features.quota", "value": "off"})
            return {"ok": True, "disabled": name}
        if action == "limit-usage":
            if not path or int(limit) <= 0:
                raise MgmtError("limit-usage needs a path and a "
                                "positive byte limit")
            await self._cluster_txn("quota-limit", {
                "name": name, "path": path, "limit": int(limit)})
            return {"ok": True, "path": path, "limit": int(limit)}
        if action == "remove":
            if not path:
                raise MgmtError("remove needs a path")
            await self._cluster_txn("quota-limit", {
                "name": name, "path": path, "limit": 0})
            return {"ok": True, "removed": path}
        if action == "list":
            if not volgen._bool(self._vol(name).get("options", {}).get(
                    "features.quota", "off")):
                raise MgmtError(f"quota not enabled on {name}")
            port = self._quotad_port(name)
            if port:
                try:
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection("127.0.0.1", port), 5)
                    try:
                        writer.write(wire.pack(1, wire.MT_CALL,
                                               ["quota-list"]))
                        await writer.drain()
                        rec = await asyncio.wait_for(
                            wire.read_frame(reader), 10)
                        _, _, payload = wire.unpack(rec)
                        return payload
                    finally:
                        writer.close()
                except Exception:
                    pass
            # quotad unreachable: last persisted aggregate
            try:
                with open(os.path.join(self.workdir,
                                       f"quotad-{name}.json")) as f:
                    return json.load(f).get("usage", {})
            except (FileNotFoundError, ValueError):
                return {}
        raise MgmtError(f"unknown quota action {action!r}")

    async def commit_quota_limit(self, name: str, path: str,
                                 limit: int) -> dict:
        vol = self._vol(name)
        limits = vol.setdefault("quota", {}).setdefault("limits", {})
        p = path.rstrip("/") or "/"
        if limit > 0:
            limits[p] = int(limit)
        else:
            limits.pop(p, None)
        self._bump(vol)
        self._save()
        applied = "stored"
        if vol["status"] == "started" and volgen._bool(
                vol.get("options", {}).get("features.quota", "off")):
            # limits ride the quota layer's `limits` option: live
            # reconfigure, no brick restart
            applied = await self._apply_to_bricks(vol)
        return {"applied": applied}

    def commit_quota_ctl(self, name: str, action: str) -> dict:
        vol = self._vol(name)
        if action == "spawn":
            if vol["status"] == "started":
                self._spawn_quotad(vol)
        else:
            self._kill_quotad(name)
        return {action: name}

    def _quotad_port(self, name: str) -> int:
        try:
            with open(os.path.join(self.workdir,
                                   f"quotad-{name}.port")) as f:
                return int(f.read())
        except (FileNotFoundError, ValueError):
            return 0

    def _spawn_quotad(self, vol: dict) -> None:
        from . import svcutil

        name = vol["name"]
        proc = self.quotad.get(name)
        if proc is not None and proc.poll() is None:
            return
        local = [(b["name"], self.ports.get(b["name"], 0),
                  svcutil.brick_group(vol, b["index"]))
                 for b in vol["bricks"]
                 if b["node"] == self.uuid and self.ports.get(b["name"])]
        if not local:
            return
        env = svcutil.spawn_env(vol, "GFTPU_QUOTAD")
        portfile = os.path.join(self.workdir, f"quotad-{name}.port")
        if os.path.exists(portfile):
            os.unlink(portfile)
        statusfile = os.path.join(self.workdir, f"quotad-{name}.json")
        with open(os.path.join(self.workdir, f"quotad-{name}.log"),
                  "ab") as logf:
            self.quotad[name] = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.mgmt.quotad",
                 "--bricks", ",".join(f"{n}:{p}:{g}" for n, p, g in local),
                 *svcutil.spawn_ssl_argv(vol.get("options", {})),
                 "--portfile", portfile, "--statusfile", statusfile],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_quotad(self, name: str) -> None:
        proc = self.quotad.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        # stale port/status files would make 'quota list' report old
        # numbers as live after a disable
        for suffix in (".port", ".json"):
            try:
                os.unlink(os.path.join(self.workdir,
                                       f"quotad-{name}{suffix}"))
            except FileNotFoundError:
                pass

    def _spawn_bitd(self, vol: dict) -> None:
        name = vol["name"]
        proc = self.bitd.get(name)
        if proc is not None and proc.poll() is None:
            return
        local = [(b["name"], self.ports.get(b["name"], 0))
                 for b in vol["bricks"]
                 if b["node"] == self.uuid and self.ports.get(b["name"])]
        if not local:
            return
        from . import svcutil

        opts = vol.get("options", {})
        scrub_off = str(opts.get("features.scrub", "on")).lower() in (
            "off", "false", "no", "0", "pause")
        # features.scrub-freq maps onto the sweep interval (hourly/
        # daily/... in the reference; seconds here, names accepted)
        freq = opts.get("features.scrub-freq",
                        opts.get("bitrot.scrub-interval", 60))
        freq = {"hourly": 3600, "daily": 86400, "weekly": 604800,
                "biweekly": 1209600, "monthly": 2592000}.get(
                    str(freq).lower(), freq)
        thr = opts.get("features.scrub-throttle",
                       opts.get("bitrot.scrub-throttle",
                                DEFAULT_SCRUB_THROTTLE))
        thr = {"lazy": DEFAULT_SCRUB_THROTTLE / 4,
               "normal": DEFAULT_SCRUB_THROTTLE,
               "aggressive": DEFAULT_SCRUB_THROTTLE * 8}.get(
                   str(thr).lower(), thr)
        env = svcutil.spawn_env(vol, "GFTPU_BITD")
        statusfile = os.path.join(self.workdir, f"bitd-{name}.json")
        with open(os.path.join(self.workdir, f"bitd-{name}.log"),
                  "ab") as logf:
            self.bitd[name] = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.mgmt.bitd",
                 "--bricks", ",".join(f"{n}:{p}" for n, p in local),
                 *svcutil.spawn_ssl_argv(opts),
                 # features.expiry-time: the signer's quiesce window
                 "--quiesce", str(opts.get("features.expiry-time",
                                           opts.get(
                                               "bitrot.signer-quiesce",
                                               120))),
                 "--scrub-interval", str(freq),
                 "--scrub-throttle", str(thr),
                 *(["--no-scrub"] if scrub_off else []),
                 "--statusfile", statusfile],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_bitd(self, name: str) -> None:
        proc = self.bitd.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    # -- HTTP object gateway (gateway/, ISSUE 6) ---------------------------
    # Lifecycle rides the cluster txn like geo-rep: every node stores
    # the started/stopped state and runs (or not) its own gateway
    # daemon — the second front door scales out with the mgmt cluster.

    async def op_volume_gateway(self, name: str,
                                action: str = "status") -> dict:
        vol = self._vol(name)
        if action == "status":
            return self._gateway_status(vol)
        if action not in ("start", "stop"):
            raise MgmtError(f"bad gateway action {action!r} "
                            "(want start|stop|status)")
        if action == "start" and vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        if self.cluster_op_version() < 8:
            raise MgmtError(
                "volume gateway needs cluster op-version >= 8 "
                f"(cluster is at {self.cluster_op_version()})")
        await self._cluster_txn(f"gateway-{action}", {"name": name})
        return {"ok": True, **self._gateway_status(vol)}

    def commit_gateway_start(self, name: str) -> dict:
        vol = self._vol(name)
        vol["gateway"] = {"status": "started"}
        self._bump(vol)
        self._save()
        self._spawn_gateway(vol)
        return {"gateway-started": name}

    def commit_gateway_stop(self, name: str) -> dict:
        vol = self._vol(name)
        vol["gateway"] = {"status": "stopped"}
        self._bump(vol)
        self._save()
        self._kill_gateway(name)
        return {"gateway-stopped": name}

    def _gateway_port(self, name: str) -> int:
        try:
            with open(os.path.join(self.workdir,
                                   f"gateway-{name}.port")) as f:
                return int(f.read())
        except (FileNotFoundError, ValueError):
            return 0

    def _gateway_status(self, vol: dict) -> dict:
        name = vol["name"]
        proc = self.gateway.get(name)
        online = proc is not None and proc.poll() is None
        return {"volume": name,
                "gateway": {
                    "status": vol.get("gateway", {}).get("status",
                                                         "stopped"),
                    "online": online,
                    "pid": proc.pid if online else 0,
                    "port": self._gateway_port(name) if online else 0}}

    def _spawn_gateway(self, vol: dict) -> None:
        from . import svcutil

        name = vol["name"]
        proc = self.gateway.get(name)
        if proc is not None and proc.poll() is None:
            return
        opts = vol.get("options", {})
        env = svcutil.spawn_env(vol, "GFTPU_GATEWAY")
        portfile = os.path.join(self.workdir, f"gateway-{name}.port")
        if os.path.exists(portfile):
            os.unlink(portfile)
        argv = [sys.executable, "-m", "glusterfs_tpu.gateway",
                "--glusterd", f"{self.host}:{self.port}",
                "--volume", name,
                "--host", str(opts.get("gateway.listen-host",
                                       "127.0.0.1")),
                "--listen", str(opts.get("gateway.port", 0)),
                "--pool", str(opts.get("gateway.pool-size", 4)),
                "--max-clients", str(opts.get("gateway.max-clients",
                                              512)),
                "--object-cache",
                str(opts.get("gateway.object-cache-size", 0)),
                "--portfile", portfile]
        workers = int(opts.get("gateway.workers", 0) or 0)
        if volgen._bool(opts.get("server.qos", "off")):
            # HTTP clients inherit the volume's QoS plane: the same
            # server.qos-* rates the bricks enforce per wire identity,
            # applied per peer IP at the gateway door (429 +
            # Retry-After instead of EAGAIN + notice).  Spawn-time
            # plumbing: retuning these keys live re-spawns via gateway
            # stop/start (documented in docs/qos.md).  The per-worker
            # buckets are shared-nothing, so the spawn-time rates are
            # DIVIDED across the pool — N workers must enforce the
            # operator's ONE budget, not N of them (the PR-17 ceiling)
            share = max(1, workers)

            def _rate(key):
                # 0 = unlimited stays unlimited at any pool width;
                # bytes-per-sec is a size option ("1MB"), so parse it
                # the way the gateway would before dividing
                from ..core.options import parse_size
                try:
                    v = float(parse_size(opts.get(key, 0) or 0))
                except Exception:
                    v = 0.0
                return v / share if v > 0 else 0

            argv += ["--qos-fops",
                     str(_rate("server.qos-fops-per-sec")),
                     "--qos-bytes",
                     str(_rate("server.qos-bytes-per-sec")),
                     "--qos-burst",
                     str(max(1, int(float(opts.get("server.qos-burst", 1)
                                          or 1) // share)))]
        if workers > 0:
            # the shared-nothing worker pool (op-version 14): the
            # spawned process becomes the supervisor; worker pids land
            # in the statusfile so status/chaos tooling can see them
            argv += ["--workers", str(workers),
                     "--statusfile",
                     os.path.join(self.workdir,
                                  f"gateway-{name}.workers")]
        if opts.get("gateway.metrics-port"):
            # the daemon's gftpu_gateway_* families are in ITS process:
            # without this the managed front door is metrics-blind
            argv += ["--metrics-port",
                     str(opts["gateway.metrics-port"])]
        if opts.get("diagnostics.incident-dir"):
            # the supervisor mounts no volfile, so the diagnostics.*
            # keys never reach it through io-stats — arm its
            # auto-capture (worker-respawn bundles) via argv
            argv += ["--incident-dir",
                     str(opts["diagnostics.incident-dir"])]
        ev = os.environ.get("GFTPU_EVENTSD")
        if ev:
            argv += ["--eventsd", ev]
        with open(os.path.join(self.workdir, f"gateway-{name}.log"),
                  "ab") as logf:
            self.gateway[name] = subprocess.Popen(
                argv, env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_gateway(self, name: str) -> None:
        proc = self.gateway.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        try:
            os.unlink(os.path.join(self.workdir,
                                   f"gateway-{name}.port"))
        except FileNotFoundError:
            pass

    # -- geo-replication (glusterd-geo-rep.c session mgmt analog) ----------
    # Session ops run through the cluster txn so every node stores the
    # link and runs a worker over ITS local bricks' changelogs — a
    # change landing on a remote node's brick is journaled and replayed
    # there (workers partition by brick; replay is idempotent so replica
    # overlap across nodes converges).

    async def op_georep_create(self, name: str, secondary: str) -> dict:
        """Create a geo-rep link: secondary is 'host:port:volume' of the
        secondary volume's glusterd."""
        self._vol(name)
        host, port, svol = secondary.rsplit(":", 2)
        if not (host and port.isdigit() and svol):
            raise MgmtError(f"bad secondary spec {secondary!r} "
                            f"(want host:port:volume)")
        await self._cluster_txn("georep-create",
                                {"name": name, "secondary": secondary})
        return {"ok": True, "primary": name, "secondary": secondary}

    async def commit_georep_create(self, name: str, secondary: str) -> dict:
        vol = self._vol(name)
        vol["georep"] = {"secondary": secondary, "status": "created"}
        # the journal feeds gsyncd: enable changelog and respawn local
        # bricks so their graphs pick it up (reference: geo-rep create
        # force-enables changelog + marker)
        vol.setdefault("options", {})["changelog.changelog"] = "on"
        self._bump(vol)
        self._save()
        if vol["status"] == "started":
            for b in vol["bricks"]:
                if b["node"] == self.uuid and b["name"] in self.bricks:
                    port = b.get("port")
                    await self._stop_brick(vol, b)
                    await self._spawn_brick(vol, b, port=port)
        return {"created": name}

    async def op_georep_start(self, name: str) -> dict:
        vol = self._vol(name)
        if not vol.get("georep"):
            raise MgmtError(f"no geo-rep session on {name}")
        if vol["status"] != "started":
            raise MgmtError(f"volume {name} not started")
        await self._cluster_txn("georep-start", {"name": name})
        return {"ok": True}

    def commit_georep_start(self, name: str) -> dict:
        vol = self._vol(name)
        geo = vol["georep"]
        geo["status"] = "started"
        self._bump(vol)
        self._save()
        self._spawn_gsync(vol)
        return {"started": name}

    def _spawn_gsync(self, vol: dict) -> None:
        name = vol["name"]
        geo = vol.get("georep") or {}
        proc = self.gsync.get(name)
        if proc is not None and proc.poll() is None:
            return
        local = [b for b in vol["bricks"] if b["node"] == self.uuid]
        if not local:
            return  # no journals on this node
        # per-brick worker monitor (monitor.py:63-85): brick specs as
        # name=index=path; the subvolume group size drives the
        # Active/Passive election inside replica/disperse sets
        bricks = ",".join(
            f"{b['name']}={b['index']}={b['path']}" for b in local)
        if vol["type"] in ("replicate", "disperse"):
            gsize = int(vol.get("group-size") or len(vol["bricks"]))
        else:
            gsize = 1
        state = os.path.join(self.workdir, f"gsync-{name}.state")
        statusfile = os.path.join(self.workdir, f"gsync-{name}.json")
        interval = float(vol.get("options", {}).get(
            "georep.sync-interval", 3))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        with open(os.path.join(self.workdir, f"gsync-{name}.log"),
                  "ab") as logf:
            self.gsync[name] = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.mgmt.gsyncd",
                 "--primary", f"{self.host}:{self.port}:{name}",
                 "--secondary", geo["secondary"],
                 "--bricks", bricks, "--group-size", str(gsize),
                 "--state", state,
                 "--interval", str(interval),
                 "--statusfile", statusfile],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_gsync(self, name: str) -> None:
        proc = self.gsync.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    async def op_georep_stop(self, name: str) -> dict:
        vol = self._vol(name)
        if not vol.get("georep"):
            raise MgmtError(f"no geo-rep session on {name}")
        await self._cluster_txn("georep-stop", {"name": name})
        return {"ok": True}

    def commit_georep_stop(self, name: str) -> dict:
        vol = self._vol(name)
        self._kill_gsync(name)
        vol["georep"]["status"] = "stopped"
        self._bump(vol)
        self._save()
        return {"stopped": name}

    async def op_georep_checkpoint(self, name: str) -> dict:
        """Stamp a checkpoint on the session (gsyncd checkpoint):
        status reports it reached once the worker has replayed every
        change journaled before this instant (gsyncdstatus.py
        checkpoint completion)."""
        vol = self._vol(name)
        if not vol.get("georep"):
            raise MgmtError(f"no geo-rep session on {name}")
        ts = time.time()
        await self._cluster_txn("georep-checkpoint",
                                {"name": name, "ts": ts})
        return {"ok": True, "checkpoint": ts}

    def commit_georep_checkpoint(self, name: str, ts: float) -> dict:
        vol = self._vol(name)
        vol["georep"]["checkpoint"] = ts
        self._save()
        return {"checkpoint": ts}

    def op_georep_status(self, name: str) -> dict:
        vol = self._vol(name)
        geo = vol.get("georep")
        if not geo:
            return {"sessions": []}
        proc = self.gsync.get(name)
        state_path = os.path.join(self.workdir, f"gsync-{name}.state")
        worker_state = {}
        try:
            with open(state_path) as f:
                worker_state = json.load(f)
        except (FileNotFoundError, ValueError):
            pass
        last_ts = worker_state.get("last_ts", 0)
        synced_through = worker_state.get("synced_through", last_ts)
        sess = {
            "primary": name, "secondary": geo["secondary"],
            "status": geo["status"],
            "online": proc is not None and proc.poll() is None,
            "last_ts": last_ts,
        }
        # per-brick worker states from the monitor (monitor.py model:
        # Active / Passive / Faulty / Offline per brick)
        try:
            with open(os.path.join(self.workdir,
                                   f"gsync-{name}.json")) as f:
                mon = json.load(f)
            if mon.get("workers"):
                sess["workers"] = mon["workers"]
        except (FileNotFoundError, ValueError):
            pass
        cp = geo.get("checkpoint")
        if cp:
            sess["checkpoint"] = cp
            sess["checkpoint_completed"] = synced_through >= cp
        return {"sessions": [sess]}

    # -- brick lifecycle (glusterd-utils.c runner + pmap) ------------------

    async def _start_local_bricks(self, vol: dict) -> None:
        for b in vol["bricks"]:
            if b["node"] != self.uuid or b["name"] in self.bricks:
                continue
            await self._spawn_brick(vol, b)

    async def _broadcast_local_ports(self, vol: dict) -> None:
        """pmap sync for this node's live bricks: write their current
        ports into volinfo and push them to every peer (the signed-in
        side of glusterd-pmap.c; restart-resume and reconciliation both
        bind fresh ports that peers' volfiles must pick up)."""
        ports = {b["name"]: self.ports[b["name"]]
                 for b in vol["bricks"]
                 if b["node"] == self.uuid and b["name"] in self.ports}
        if not ports:
            return
        changed = False
        for b in vol["bricks"]:
            if b["name"] in ports and b.get("port") != ports[b["name"]]:
                b["port"] = ports[b["name"]]
                changed = True
        if changed:
            self._save()
            self._notify_subscribers(vol["name"])
        for node in self._all_nodes():
            if node["uuid"] == self.uuid:
                continue
            try:
                await asyncio.wait_for(self._node_call(
                    node, "portmap-update", name=vol["name"],
                    ports=ports), 10)
            except Exception:
                continue

    # -- brick multiplexing (glusterfsd-mgmt.c ATTACH / brick-mux) ---------
    # One shared daemon per node anchored on a glusterd-owned stub
    # graph; every brick of a cluster.brick-multiplex volume is
    # attached into it over the ATTACH RPC and served on ONE port,
    # routed by the client's SETVOLUME remote-subvolume.

    def _mux_enabled(self, vol: dict) -> bool:
        if not volgen._bool(vol.get("options", {}).get(
                "cluster.brick-multiplex", "off")):
            return False
        if volgen._bool(vol.get("options", {}).get("server.ssl", "off")):
            # the mux transport carries the anchor's (plaintext) TLS
            # identity; a per-volume-TLS brick needs its own process
            log.warning(19, "%s: server.ssl volume gets a dedicated "
                        "brick process despite brick-multiplex",
                        vol["name"])
            return False
        return True

    def _mux_auth_vol(self) -> dict:
        """Pseudo-volinfo carrying the node's anchor credentials (for
        mgmt calls against the shared daemon's default graph)."""
        auth = self.state.setdefault("mux-auth", {
            "mgmt-username": str(uuid.uuid4()),
            "mgmt-password": str(uuid.uuid4())})
        return {"name": "mux-anchor", "options": {}, "auth": auth}

    async def _spawn_daemon(self, volfile: str, text: str, portfile: str,
                            logfile: str, top: str,
                            port: int | None = None,
                            what: str = "brick",
                            extra_env: dict | None = None
                            ) -> tuple[subprocess.Popen, int]:
        """Shared spawn-and-wait machinery for brick daemons (dedicated
        bricks and the mux anchor use the same path)."""
        with open(volfile, "w") as f:
            f.write(text)
        if os.path.exists(portfile):
            os.unlink(portfile)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        if extra_env:
            env.update(extra_env)
        with open(logfile, "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.daemon",
                 "--volfile", volfile, "--listen", str(port or 0),
                 "--portfile", portfile, "--top", top],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)
        # generous: a cold interpreter+jax import on a loaded host can
        # take the better part of a minute
        deadline = time.time() + 90
        while time.time() < deadline:
            if os.path.exists(portfile):
                with open(portfile) as f:
                    return proc, int(f.read())
            if proc.poll() is not None:
                with open(logfile, "rb") as f:
                    err = f.read().decode(errors="replace")[-2000:]
                raise MgmtError(f"{what} failed: {err}")
            await asyncio.sleep(0.05)
        # kill the straggler (terminate -> wait -> kill escalation): an
        # orphan that binds its port AFTER we give up would serve a
        # brick glusterd no longer tracks
        if proc.poll() is None:
            proc.terminate()
            try:
                await asyncio.to_thread(proc.wait, timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        raise MgmtError(f"{what} did not start in time")

    async def _ensure_mux_proc(self) -> int:
        async with self._mux_lock:
            # re-check under the lock: a concurrent caller may have
            # finished the (up to 90s) spawn while we waited — two
            # anchors would strand the first one's attached bricks
            if self._mux and self._mux["proc"].poll() is None:
                return self._mux["port"]
            anchor = self._mux_auth_vol()
            bdir = os.path.join(self.workdir, "bricks")
            os.makedirs(bdir, exist_ok=True)
            adir = os.path.join(self.workdir, "mux-anchor")
            os.makedirs(adir, exist_ok=True)
            text = (
                f"volume mux-anchor-posix\n    type storage/posix\n"
                f"    option directory {adir}\nend-volume\n"
                f"volume mux-anchor-server\n    type protocol/server\n"
                f"    option auth-mgmt-user "
                f"{anchor['auth']['mgmt-username']}\n"
                f"    option auth-mgmt-password "
                f"{anchor['auth']['mgmt-password']}\n"
                # no client credentials exist for the anchor: refuse
                # every non-mgmt handshake outright
                f"    option auth-reject *\n"
                f"    subvolumes mux-anchor-posix\nend-volume\n")
            proc, port = await self._spawn_daemon(
                os.path.join(bdir, "mux-anchor.vol"), text,
                os.path.join(bdir, "mux-anchor.port"),
                os.path.join(bdir, "mux-anchor.log"),
                "mux-anchor-server", what="mux daemon")
            self._mux = {"proc": proc, "port": port, "bricks": set()}
            return port

    async def _attach_brick(self, vol: dict, b: dict) -> None:
        port = await self._ensure_mux_proc()
        text = volgen.build_brick_volfile(vol, b)
        payload = await self._brick_call(
            self._mux_auth_vol(), port, "__attach__",
            [text, b["name"] + "-server"])
        if not (payload and payload.get("ok")):
            raise MgmtError(f"attach of {b['name']} refused: {payload}")
        self._mux["bricks"].add(b["name"])
        self.bricks[b["name"]] = self._mux["proc"]
        self.ports[b["name"]] = port
        b["port"] = port
        self._save()

    async def _stop_brick(self, vol: dict, b: dict) -> None:
        """Stop serving one brick: detach from the shared daemon when
        multiplexed, else kill its dedicated process."""
        name = b["name"]
        if self._mux and name in self._mux["bricks"]:
            try:
                await self._brick_call(
                    self._mux_auth_vol(), self._mux["port"],
                    "__detach__", [name + "-server"])
            except Exception as e:
                log.warning(20, "detach of %s failed: %r", name, e)
            self._mux["bricks"].discard(name)
            self.bricks.pop(name, None)
            self.ports.pop(name, None)
            return
        self._kill_brick(name)

    def _mesh_env(self, vol: dict, b: dict) -> dict | None:
        """``cluster.mesh-distributed`` (op-version 14): each brick
        daemon of the volume is one ``jax.distributed`` process —
        coordinator on brick 0's node, ``num_processes`` = brick
        count, ``process_id`` = brick index.  The daemon's meshd glue
        (parallel/meshd.py) reads these and initializes in the
        BACKGROUND, so brick startup (and glusterd's one-at-a-time
        spawn loop) never blocks on ranks that aren't up yet."""
        opts = vol.get("options", {})
        if not volgen._bool(opts.get("cluster.mesh-distributed",
                                     "off")):
            return None
        port = vol.get("mesh-coordinator-port")
        if not port:
            # DETERMINISTIC from the replicated volume id: every
            # node's glusterd computes the same coordinator port with
            # no cross-node coordination.  (A lazily-bound ephemeral
            # port picked per node diverged across peers — node B's
            # ranks dialed a port nothing on node A listened on.)
            import hashlib

            h = int(hashlib.sha1(
                str(vol.get("id", vol["name"])).encode()).hexdigest(),
                16)
            port = 30000 + (h % 20000)
            vol["mesh-coordinator-port"] = port
            self._save()
        bricks = vol["bricks"]
        hosts = {n["uuid"]: n["host"] for n in self._all_nodes()}
        coord = hosts.get(bricks[0]["node"], self.host)
        rank = next((i for i, x in enumerate(bricks)
                     if x["name"] == b["name"]), 0)
        return {"GFTPU_MESH_COORDINATOR": f"{coord}:{port}",
                "GFTPU_MESH_PROCESSES": str(len(bricks)),
                "GFTPU_MESH_RANK": str(rank)}

    async def _spawn_brick(self, vol: dict, b: dict,
                           port: int | None = None) -> None:
        if self._mux_enabled(vol):
            await self._attach_brick(vol, b)
            return
        bdir = os.path.join(self.workdir, "bricks")
        os.makedirs(bdir, exist_ok=True)
        proc, bport = await self._spawn_daemon(
            os.path.join(bdir, b["name"] + ".vol"),
            volgen.build_brick_volfile(vol, b),
            os.path.join(bdir, b["name"] + ".port"),
            os.path.join(bdir, b["name"] + ".log"),
            # serve the auth-carrying protocol/server top, not the
            # io-stats layer underneath it
            b["name"] + "-server", port=port,
            what=f"brick {b['name']}",
            extra_env=self._mesh_env(vol, b))
        self.bricks[b["name"]] = proc
        self.ports[b["name"]] = bport
        b["port"] = bport
        self._save()

    def _kill_brick(self, name: str) -> None:
        proc = self.bricks.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.ports.pop(name, None)

    # -- self-heal daemon lifecycle (glusterd-shd-svc.c analog) -----------

    @staticmethod
    def _shd_max_heals(vol: dict) -> int:
        """Concurrent file heals for this volume (shd-max-threads with
        the reference's fallback ladder) — shared by the spawned shd
        and the mounted-client heal ops so ``heal full`` coalesces the
        same way the daemon does."""
        opts = vol.get("options", {})
        prefix = "disperse." if vol["type"] == "disperse" else "cluster."
        return int(opts.get(prefix + "shd-max-threads",
                            opts.get("cluster.background-self-heal-"
                                     "count",
                                     opts.get("disperse.background-"
                                              "heals", 1))))

    def _spawn_shd(self, vol: dict) -> None:
        """One shd per started heal-capable volume on this node."""
        if vol["type"] not in ("disperse", "replicate"):
            return
        opts = vol.get("options", {})
        gate = "cluster.disperse-self-heal-daemon" \
            if vol["type"] == "disperse" else "cluster.self-heal-daemon"
        if str(opts.get(gate, "on")).lower() in ("off", "false", "no",
                                                 "0", "disable"):
            return  # operator turned the healer off for this volume
        name = vol["name"]
        proc = self.shd.get(name)
        if proc is not None and proc.poll() is None:
            return
        interval = float(opts.get("cluster.heal-timeout", 10))
        prefix = "disperse." if vol["type"] == "disperse" else "cluster."
        max_heals = self._shd_max_heals(vol)
        qlen = int(opts.get(prefix + "shd-wait-qlength",
                            opts.get("cluster.heal-wait-queue-length",
                                     opts.get("disperse.heal-wait-"
                                              "qlength", 1024))))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        statefile = os.path.join(self.workdir, f"shd-{name}.json")
        with open(os.path.join(self.workdir, f"shd-{name}.log"),
                  "ab") as logf:
            self.shd[name] = subprocess.Popen(
                [sys.executable, "-m", "glusterfs_tpu.mgmt.shd",
                 "--glusterd", f"{self.host}:{self.port}",
                 "--volname", name, "--interval", str(interval),
                 "--max-heals", str(max_heals),
                 "--wait-qlength", str(qlen),
                 "--statefile", statefile],
                env=env, stdout=subprocess.DEVNULL, stderr=logf)

    def _kill_shd(self, name: str) -> None:
        proc = self.shd.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


class MgmtClient:
    """Client for the mgmt RPC (CLI + peers + mounts use this)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader = None
        self._writer = None
        self._xid = 0

    async def __aenter__(self):
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        return self

    async def __aexit__(self, *exc):
        if self._writer is not None:
            self._writer.close()
        return False

    async def call(self, method: str, **kwargs) -> Any:
        self._xid += 1
        self._writer.write(wire.pack(self._xid, wire.MT_CALL,
                                     [method, kwargs]))
        await self._writer.drain()
        rec = await wire.read_frame(self._reader)
        _, mtype, payload = wire.unpack(rec)
        if mtype == wire.MT_ERROR:
            raise payload if isinstance(payload, FopError) else \
                MgmtError(str(payload))
        return payload


async def _watch_volfile(client, host: str, port: int,
                         volname: str) -> None:
    """Hold a subscribed mgmt connection and re-fetch + apply the
    volfile on change pushes (glusterfsd-mgmt.c fetch-spec callback)."""
    while True:
        try:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                writer.write(wire.pack(1, wire.MT_CALL,
                                       ["subscribe", {"name": volname}]))
                await writer.drain()
                await wire.read_frame(reader)  # subscribe ack
                while True:
                    rec = await wire.read_frame(reader)
                    _, mtype, payload = wire.unpack(rec)
                    if mtype == wire.MT_EVENT and isinstance(payload, dict) \
                            and payload.get("event") == "volfile-modified":
                        async with MgmtClient(host, port) as c:
                            spec = await c.call("getspec", name=volname)
                        how = await client.reload(spec["volfile"])
                        log.info(12, "volfile for %s applied live (%s)",
                                 volname, how)
            finally:
                writer.close()
        except asyncio.CancelledError:
            return
        except Exception as e:
            log.debug(13, "volfile watcher retry (%r)", e)
            await asyncio.sleep(1.0)


async def mount_volume(host: str, port: int, volname: str,
                       origin: str = ""):
    """Fetch the client volfile from glusterd and build a mounted client
    (the glfs_set_volfile_server + GETSPEC path, api/src/glfs-mgmt.c).
    The mount stays subscribed to volfile changes and applies them live
    (reconfigure or graph swap).  ``origin`` attributes the mount's
    traffic to the bricks' QoS plane ("rebalance" rides the paced
    lane) — set here, BEFORE mount, so the very first handshake
    carries it and every reconnect/graph-swap re-carries it."""
    from ..api.glfs import Client, wait_connected
    from ..core.graph import Graph

    async with MgmtClient(host, port) as c:
        spec = await c.call("getspec", name=volname)
    graph = Graph.construct(spec["volfile"])
    client = Client(graph)
    if origin:
        client.traffic_origin = origin
    await client.mount()
    await wait_connected(graph)
    client.watchers.append(
        asyncio.create_task(_watch_volfile(client, host, port, volname)))
    return client


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-glusterd")
    p.add_argument("--workdir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--listen", type=int, default=24007)
    p.add_argument("--portfile", default="")
    args = p.parse_args(argv)

    async def run():
        from ..core import flight, history
        from ..core.metrics import register_build_info

        flight.set_role("glusterd")
        register_build_info("glusterd")
        history.arm()
        d = Glusterd(args.workdir, args.host, args.listen)
        await d.start()
        if args.portfile:
            with open(args.portfile + ".tmp", "w") as f:
                f.write(str(d.port))
            os.replace(args.portfile + ".tmp", args.portfile)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await d.stop()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
