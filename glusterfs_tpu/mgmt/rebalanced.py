"""Rebalance daemon — the per-volume rebalance process analog.

Reference: rebalance runs as a glusterd-managed glusterfs process per
volume (``gluster volume rebalance <v> start`` spawns it with the
client graph; xlators/cluster/dht/src/dht-rebalance.c gf_defrag_start
drives the two phases, glusterd-rebalance.c owns the lifecycle).  The
old in-process ``DistributeLayer.rebalance()`` walk had no owner, no
persistence and no status story; this daemon is the managed form:

* **Private client graph**: the daemon mounts the volume through
  glusterd's GETSPEC like shd/gsyncd — migration I/O rides the full
  wire stack and live ``volume set`` retunes it (the volfile watcher
  reconfigures the mounted graph, so ``cluster.rebal-throttle``
  changes apply to a RUNNING rebalance between waves).
* **Two phases** (gf_defrag_cmd): *fix-layout* stamps a fresh
  commit-hash layout generation over every directory
  (``DistributeLayer.fix_layout_dir``), then *migrate* walks files and
  moves each to its new hashed subvolume via the torn-read-safe
  temp + compound-chain copy + rename commit in
  ``DistributeLayer._migrate_file``.
* **Resumable checkpoints**: the walk is a canonical preorder DFS with
  sorted children, so directory paths are totally ordered; the
  checkpoint is the LAST COMPLETED DIRECTORY plus the per-phase
  counters, pushed into the volinfo through glusterd's
  ``rebalance-update`` RPC.  SIGKILL + respawn CONTINUES from the
  checkpoint — directories at or before it are skipped (their files
  already sit on their hashed subvolume; migration is idempotent
  anyway), counters carry over, and the status records
  ``resumed_from`` so the operator can see it resumed rather than
  restarted.
* **Throttle**: ``cluster.rebal-throttle`` lazy/normal/aggressive maps
  onto concurrent migrations + a cooperative yield exactly like the
  in-process walk (dht-rebalance.c:3269 migrator thread scaling), read
  per wave so a live retune applies mid-run.
* **Drain mode**: ``remove-brick start`` rides the same daemon with
  ``--mode drain`` — decommissioned children are already excluded from
  the layout, so the same misplaced-file walk empties them, and shrink
  gets status/stop/checkpoints for free.
* **Attribution**: every EC layer in the private graph is tagged
  ``traffic_origin = "rebalance"`` so codec batches, mesh launches and
  the gftpu_mesh_* families attribute migration traffic (the PR-8 heal
  precedent); migration cleanup unlinks carry the internal-op xdata
  flag so features/trash never holds rebalance garbage.
* **Observability**: ``gftpu_rebalance_{files,bytes,failures}_total``
  + ``gftpu_rebalance_phase`` registry families over the live
  Rebalancer set, REBALANCE_FILE_FAILED / REBALANCE_COMPLETE events,
  and a statusfile snapshot for the node-local status fan-out.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import sys
import time

from ..core import gflog
from ..core.events import gf_event
from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import Loc, walk
from ..core.metrics import REGISTRY
from .svcutil import ThrottleWave

log = gflog.get_logger("rebalanced")

PHASES = ("idle", "fix-layout", "migrate", "done")

#: gftpu_rebalance_phase gauge values (idle=0 .. done=3)
PHASE_GAUGE = {p: i for i, p in enumerate(PHASES)}

_COUNTERS = ("scanned", "moved", "skipped", "failed", "bytes_moved",
             "dirs_fixed", "dirs_walked", "dirs_vanished",
             "temps_swept")


def _samples_files(r: "Rebalancer"):
    for result in ("moved", "skipped", "failed"):
        yield ({"volume": r.volume, "result": result},
               r.counters[result])


_LIVE = REGISTRY.register_objects(
    "gftpu_rebalance_files_total", "counter",
    "files handled by the rebalance walk by result "
    "(moved / skipped / failed)", _samples_files)
REGISTRY.register_objects(
    "gftpu_rebalance_bytes_total", "counter",
    "bytes migrated to their new hashed subvolume",
    lambda r: [({"volume": r.volume}, r.counters["bytes_moved"])],
    live=_LIVE)
REGISTRY.register_objects(
    "gftpu_rebalance_failures_total", "counter",
    "file migrations that failed (REBALANCE_FILE_FAILED events)",
    lambda r: [({"volume": r.volume}, r.counters["failed"])],
    live=_LIVE)
REGISTRY.register_objects(
    "gftpu_rebalance_phase", "gauge",
    "rebalance phase (0 idle, 1 fix-layout, 2 migrate, 3 done)",
    lambda r: [({"volume": r.volume}, PHASE_GAUGE.get(r.phase, 0))],
    live=_LIVE)


def tag_rebalance_origin(graph) -> int:
    """Tag every origin-aware layer of a (private) client graph so its
    codec traffic is attributed ``origin="rebalance"`` on the batch /
    mesh families — the daemon owns the whole graph, so everything it
    pushes through it IS migration traffic.  Re-applied after live
    graph swaps (a volfile change mid-rebalance builds fresh layers).
    Returns how many layers were tagged."""
    n = 0
    for layer in walk(graph.top):
        if hasattr(layer, "traffic_origin"):
            layer.traffic_origin = "rebalance"
            n += 1
    return n


class RebalanceStopped(Exception):
    """Cooperative stop (SIGTERM / ``volume rebalance stop``)."""


class MgmtLink:
    """Persistent mgmt connection with rate-limited reconnect — the
    PR-11 deferred item: checkpoint pushes must survive a glusterd
    restart without hammering a dead endpoint.

    One TCP connection is held across pushes (a multi-hour migration
    making thousands of rate-limited status pushes should not pay a
    connect per push).  A call failing with a TRANSPORT error (the
    glusterd behind it restarted) drops the connection, reconnects,
    and replays that one call — rebalance-update is a state push and
    the checkpoint never regresses, so replay is idempotent.
    Reconnect attempts are rate-limited to one per
    ``rebalance.checkpoint-interval``: while glusterd stays down, at
    most one dial per checkpoint beat fails fast and the push is
    dropped (the statusfile still carries the state; the next push
    retries).  App-level :class:`MgmtError` is NEVER retried — the
    call reached a live glusterd and was refused."""

    _TRANSPORT_ERRORS = (ConnectionError, OSError, EOFError,
                         asyncio.IncompleteReadError)

    def __init__(self, host: str, port: int,
                 min_reconnect_s: float = 1.0):
        self.host, self.port = host, port
        self.min_reconnect_s = float(min_reconnect_s)
        self._client = None
        self._last_attempt = float("-inf")  # first dial never limited

    async def _drop(self) -> None:
        if self._client is not None:
            try:
                await self._client.__aexit__(None, None, None)
            except Exception:  # noqa: BLE001 - already torn
                pass
            self._client = None

    async def _reconnect(self) -> None:
        now = time.monotonic()
        if now - self._last_attempt < self.min_reconnect_s:
            raise ConnectionError(
                f"mgmt reconnect rate-limited "
                f"({self.min_reconnect_s:.1f}s per attempt)")
        self._last_attempt = now
        from .glusterd import MgmtClient

        c = MgmtClient(self.host, self.port)
        await c.__aenter__()
        self._client = c
        # only FAILED dials arm the limiter: after a long-lived healthy
        # connection dies (glusterd restart) the first reconnect must
        # not be charged for the dial that opened it hours ago
        self._last_attempt = float("-inf")

    async def call(self, method: str, **kw):
        if self._client is None:
            await self._reconnect()
        try:
            return await self._client.call(method, **kw)
        except self._TRANSPORT_ERRORS:
            # glusterd restarted under the held connection: one
            # (rate-limited) reconnect, one replay
            await self._drop()
            await self._reconnect()
            return await self._client.call(method, **kw)

    async def close(self) -> None:
        await self._drop()


class Rebalancer:
    """One rebalance run over a mounted client graph.

    The walk is a preorder DFS with children visited in sorted order,
    which makes directory paths totally ordered (parent before child,
    siblings lexicographic) — the property the checkpoint depends on:
    every directory at or before ``last_dir`` in that order is done.
    """

    def __init__(self, client, volume: str, mode: str = "full",
                 checkpoint: dict | None = None,
                 on_checkpoint=None, checkpoint_interval: float = 1.0):
        self.client = client
        self.volume = volume
        self.mode = mode  # full | fix-layout | drain
        self.on_checkpoint = on_checkpoint  # async callback(info dict)
        self.checkpoint_interval = max(0.02, float(checkpoint_interval))
        self.phase = "idle"
        self.counters = {k: 0 for k in _COUNTERS}
        self.note = ""
        self.resumed_from: dict | None = None
        self._resume = dict(checkpoint or {})
        if self._resume.get("counters"):
            self.counters.update({
                k: int(v) for k, v in self._resume["counters"].items()
                if k in self.counters})
            self.resumed_from = {
                "phase": self._resume.get("phase"),
                "last_dir": self._resume.get("last_dir")}
        self.last_dir: str | None = None
        self._last_push = 0.0
        self._stop = False
        self._sweep_temps = True  # main migrate pass only, not settle
        self._tagged_graph = None  # last graph object tag_* walked
        self.throttle = ""
        self.max_inflight = 0
        #: active walk seconds per phase (settle-pass migrate walks
        #: accumulate; the LAYOUT_TTL settle SLEEPS do not) — the
        #: honest denominator for a migration rate
        self.phase_seconds: dict[str, float] = {}
        _LIVE.add(self)

    # -- walk-order math ---------------------------------------------------

    @staticmethod
    def dir_key(path: str) -> tuple:
        """Canonical preorder position of a directory path: its
        component tuple.  Preorder DFS with sorted children emits
        paths exactly in this tuple order ('/a' < '/a/b' < '/a/c' <
        '/b'), so 'done before the checkpoint' is a plain tuple
        comparison."""
        return tuple(p for p in path.split("/") if p)

    def _done_before_resume(self, phase: str, path: str) -> bool:
        """Was ``path`` completed before the checkpoint this run
        resumed from?  Only directories of the checkpointed phase are
        skippable; a checkpoint taken in the migrate phase means the
        whole fix-layout phase finished earlier."""
        ck_phase = self._resume.get("phase")
        last = self._resume.get("last_dir")
        if ck_phase is None or last is None:
            return False
        if phase == "fix-layout" and ck_phase == "migrate":
            return True  # fix-layout completed before migrate began
        if phase != ck_phase:
            return False
        return self.dir_key(path) <= self.dir_key(last)

    # -- status / checkpoint -----------------------------------------------

    @classmethod
    def _ck_pos(cls, phase: str | None, last_dir: str | None) -> tuple:
        """Total order of checkpoint positions: phase first, then the
        walk order of the last completed directory."""
        try:
            pi = PHASES.index(phase)
        except ValueError:
            pi = 0
        return (pi, cls.dir_key(last_dir) if last_dir else ())

    def checkpoint(self) -> dict:
        ck = {"phase": self.phase, "last_dir": self.last_dir,
              "counters": dict(self.counters)}
        # never REGRESS the persisted checkpoint: a resumed run pushes
        # status while it is still catching up (the skipped fix-layout
        # phase ends with last_dir=None, the resumed migrate phase
        # starts behind the marker) — overwriting the volinfo with an
        # earlier position would make a SECOND kill restart the walk
        if self._resume.get("phase") and \
                self._ck_pos(self.phase, self.last_dir) < \
                self._ck_pos(self._resume.get("phase"),
                             self._resume.get("last_dir")):
            ck["phase"] = self._resume["phase"]
            ck["last_dir"] = self._resume.get("last_dir")
        return ck

    def status(self) -> dict:
        out = {"mode": self.mode, "phase": self.phase,
               "counters": dict(self.counters),
               "checkpoint": self.checkpoint(),
               "throttle": self.throttle,
               "max_inflight": self.max_inflight,
               "phase_seconds": {k: round(v, 3) for k, v
                                 in self.phase_seconds.items()}}
        if self.note:
            out["note"] = self.note
        if self.resumed_from:
            out["resumed_from"] = self.resumed_from
        return out

    async def _push(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < self.checkpoint_interval:
            return
        self._last_push = now
        if self.on_checkpoint is not None:
            try:
                await self.on_checkpoint(self.status())
            except Exception as e:  # a mgmt hiccup must not kill the run
                log.warning(3, "checkpoint push failed: %r", e)

    def stop(self) -> None:
        self._stop = True

    # -- graph plumbing ----------------------------------------------------

    def _dht(self):
        from ..cluster.dht import DistributeLayer

        return next((l for l in self.client.graph.by_name.values()
                     if isinstance(l, DistributeLayer)), None)

    # -- phases ------------------------------------------------------------

    async def run(self) -> dict:
        from ..cluster.dht import LAYOUT_TTL

        dht = self._dht()
        if dht is None:
            # single-subvolume volume: nothing to place differently
            self.phase = "done"
            self.note = "volume has a single subvolume; nothing to " \
                        "rebalance"
            await self._push(force=True)
            return self.status()
        try:
            if self.mode != "drain":
                # drain keeps the persisted layouts: decommissioned
                # children are routed around by placement, and a
                # remove-brick stop must be able to fall back to them
                await self._phase("fix-layout", self._fix_dir)
            hazard_end = time.monotonic() + LAYOUT_TTL
            if self.mode != "fix-layout":
                await self._phase("migrate", self._migrate_dir)
                # the checkpoint this run resumed from is consumed;
                # settle passes below must re-walk everything — and
                # they must not repeat the per-child temp sweep the
                # main pass just finished
                self._resume = {}
                self._sweep_temps = False
                await self._settle(hazard_end)
            self.phase = "done"
        finally:
            await self._push(force=True)
        return self.status()

    async def _settle(self, hazard_end: float) -> None:
        """Converge the races the main pass cannot see coming: a
        serving client whose cached parent layout was read up to
        LAYOUT_TTL before fix-layout stamped fresh ranges keeps
        creating files at the OLD range owner — misplaced, with no
        linkto — until its cache expires.  Any such file behind the
        walk is missed by the main pass, so re-walk until a pass that
        STARTED after every stale cache died moves (and fails) nothing.
        Each extra pass is readdir + placement checks when there is
        nothing left to move."""
        for _ in range(8):
            await asyncio.sleep(max(0.0, hazard_end - time.monotonic()))
            before = self.counters["moved"] + self.counters["failed"]
            started = time.monotonic()
            await self._phase("migrate", self._migrate_dir)
            if started >= hazard_end and \
                    self.counters["moved"] + self.counters["failed"] \
                    == before:
                return
        self.note = "settle passes exhausted; namespace still churning"

    async def _phase(self, phase: str, work) -> None:
        self.phase = phase
        self.last_dir = self._resume.get("last_dir") \
            if self._resume.get("phase") == phase else None
        weights = None
        if phase == "fix-layout":
            dht = self._dht()
            if dht.opts["weighted-rebalance"]:
                weights = await dht._capacity_weights()

        async def rec(path: str) -> None:
            if self._stop:
                raise RebalanceStopped()
            # a live volfile swap builds fresh layers: keep them
            # tagged.  Same-graph reconfigures keep the layer objects
            # (tags survive), so only a SWAPPED graph object needs the
            # re-walk — per directory, identity is all that's checked
            graph = self.client.graph
            if graph is not self._tagged_graph:
                tag_rebalance_origin(graph)
                self._tagged_graph = graph
            try:
                if self._done_before_resume(phase, path):
                    subdirs = await self._list_subdirs(path)
                else:
                    subdirs = await work(path, weights)
                    self.counters["dirs_walked"] += 1
                    self.last_dir = path
                    await self._push()
            except FopError as e:
                if path != "/" and e.err in (errno.ENOENT,
                                             errno.ESTALE):
                    # a serving client rmdir'd it between the parent
                    # listing and this descent: skip the subtree — a
                    # multi-hour run must not fail over one vanished
                    # directory
                    self.counters["dirs_vanished"] += 1
                    return
                raise
            for name in sorted(subdirs):
                await rec(path.rstrip("/") + "/" + name)

        t0 = time.monotonic()
        try:
            await rec("/")
        finally:
            self.phase_seconds[phase] = round(
                self.phase_seconds.get(phase, 0.0)
                + time.monotonic() - t0, 3)
        await self._push(force=True)

    async def _list_subdirs(self, path: str) -> list[str]:
        """Subdirectory names only — the checkpoint-skip descent path.
        readdirP: plain readdir entries may carry no iatt, and a
        skipped directory whose children went unlisted would silently
        truncate the resumed walk."""
        dht = self._dht()
        fd = await dht.opendir(Loc(path))
        try:
            entries = await dht.readdirp(fd)
        finally:
            await dht.release(fd)
        return [name for name, ia in entries
                if ia is not None and ia.ia_type is IAType.DIR]

    async def _fix_dir(self, path: str, weights) -> list[str]:
        dht = self._dht()
        subdirs = await dht.fix_layout_dir(path, weights)
        self.counters["dirs_fixed"] += 1
        return subdirs

    async def _migrate_dir(self, path: str, _weights) -> list[str]:
        """Migrate every misplaced file of ONE directory,
        ``cluster.rebal-throttle`` wide; returns the subdirectories.
        The throttle is re-read per wave so ``volume set`` retunes a
        running migration (the reference's defrag throttle reconf)."""
        dht = self._dht()
        if self._sweep_temps:
            # a predecessor SIGKILLed mid-copy left hidden
            # reserved-suffix temps behind; they are filtered from
            # every listing, so only this walk can reclaim them.
            # EVERY main pass sweeps — a fresh (checkpoint-free) run
            # may still follow a crashed one whose checkpoint was
            # dropped (topology change, `rebalance stop` before the
            # restart), and a skipped sweep would leak the hidden
            # bytes forever.  The flag is cleared before the settle
            # re-walks so they don't repeat the per-child listings
            # after the main pass already reclaimed everything
            await self._sweep_orphan_temps(dht, path)
        fd = await dht.opendir(Loc(path))
        try:
            entries = await dht.readdir(fd)
        finally:
            await dht.release(fd)
        subdirs: list[str] = []
        wave = ThrottleWave()
        for name, ia in entries:
            if ia is not None and ia.ia_type is IAType.DIR:
                subdirs.append(name)
                continue
            if self._stop:
                break
            child = path.rstrip("/") + "/" + name
            cloc = Loc(child)
            try:
                # direct everywhere-scan, NOT _cached_idx: a file
                # created through a stale parent layout is misplaced
                # with no linkto, and the pruned path would
                # lookup-optimize it into ENOENT — the walk must see
                # exactly the files serving clients cannot
                idx, fia = await dht._locate_real(cloc)
                if fia.ia_type is IAType.DIR:
                    subdirs.append(name)
                    continue
                self.counters["scanned"] += 1
                hi = await dht._placed(cloc)
            except FopError:
                continue  # vanished mid-walk (a serving unlink)
            if hi == idx:
                self.counters["skipped"] += 1
                continue
            throttle = str(dht.opts["rebal-throttle"])
            self.throttle = throttle
            width, pause = dht._THROTTLE[throttle]
            await wave.admit(
                self._migrate_one(dht, child, cloc, fia, idx, hi),
                width, pause)
            self.max_inflight = max(self.max_inflight,
                                    wave.max_inflight)
        await wave.drain()
        if self._stop:
            raise RebalanceStopped()
        return subdirs

    async def _sweep_orphan_temps(self, dht, path: str) -> None:
        """Reclaim crash-orphaned migration temps in ``path``.  A
        daemon killed between temp create and the rename commit leaves
        `.NAME.rebalance~` on a destination child holding up to the
        whole file's bytes; dht filters the suffix from every listing,
        so nothing else can even see it.  Runs on the main migrate
        pass (resumed or fresh — a fresh run may follow a crashed one
        whose checkpoint was abandoned), per directory BEFORE that
        directory's migrations launch — the daemon is the volume's
        only migrator, so any temp standing at that point is garbage
        (a re-migrated file re-creates its temp from scratch
        anyway)."""
        from ..features.trash import INTERNAL_OP

        for child in dht.children:
            try:
                fd = await child.opendir(Loc(path))
                try:
                    entries = await child.readdir(fd)
                finally:
                    await child.release(fd)
            except FopError:
                continue  # dir absent on this child
            for name, _ia in entries:
                if not name.endswith(dht.MIGRATE_SUFFIX):
                    continue
                tmp = Loc(path.rstrip("/") + "/" + name)
                try:
                    await child.unlink(tmp, {INTERNAL_OP: True})
                    self.counters["temps_swept"] += 1
                    log.warning(4, "reclaimed orphan temp %s", tmp.path)
                except FopError:
                    pass

    async def _migrate_one(self, dht, child: str, cloc: Loc, ia,
                           idx: int, hi: int) -> None:
        try:
            nbytes = await dht._migrate_file(cloc, ia, idx, hi)
        except Exception as e:
            # ANY escape counts as failed — an uncounted exception
            # would report a clean run with the file still misplaced
            self.counters["failed"] += 1
            log.warning(4, "migrate %s failed: %r", child, e)
            gf_event("REBALANCE_FILE_FAILED", volume=self.volume,
                     path=child, error=repr(e)[:200])
            return
        self.counters["moved"] += 1
        self.counters["bytes_moved"] += nbytes


# ---------------------------------------------------------------------------
# daemon entry (glusterd's spawner runs this)
# ---------------------------------------------------------------------------


def _write_statusfile(path: str, info: dict) -> None:
    if not path:
        return
    snap = REGISTRY.snapshot()
    info = dict(info)
    info["pid"] = os.getpid()
    info["families"] = {
        name: snap[name]["samples"] for name in (
            "gftpu_rebalance_files_total",
            "gftpu_rebalance_bytes_total",
            "gftpu_rebalance_failures_total",
            "gftpu_rebalance_phase") if name in snap}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)


async def _amain(args) -> int:
    from ..core import flight, history
    from ..core.metrics import register_build_info
    from .glusterd import mount_volume

    flight.set_role("rebalance")
    register_build_info("rebalance")
    history.arm()
    if args.statusfile:
        # incident capture door (no inbound RPC surface): SIGUSR2
        # writes the flight bundle beside the statusfile, where the
        # incident fan-out polls for it
        flight.arm_signal_capture(args.statusfile + ".incident")
    host, _, port = args.glusterd.rpartition(":")
    host, port = host or "127.0.0.1", int(port)
    link = MgmtLink(host, port)
    mgmt_call = link.call

    # the volinfo carries the resume checkpoint + the daemon's knobs
    info = await mgmt_call("volume-info", name=args.volname)
    vol = info[args.volname]
    rb = vol.get("rebalance") or {}
    opts = vol.get("options", {})
    try:
        interval = float(opts.get("rebalance.checkpoint-interval",
                                  args.checkpoint_interval))
    except (TypeError, ValueError):
        # volume-set stores the raw string; a malformed value must not
        # crash-loop every (re)spawn with the record wedged 'started'
        log.warning(2, "bad rebalance.checkpoint-interval %r; using %s",
                    opts.get("rebalance.checkpoint-interval"),
                    args.checkpoint_interval)
        interval = args.checkpoint_interval
    # reconnect attempts ride the same beat as checkpoint pushes: one
    # dial per interval while glusterd is down
    link.min_reconnect_s = max(0.02, interval)
    mode = args.mode or rb.get("mode") or "full"

    client = None
    while client is None:
        try:
            # origin rides the handshake creds (QoS plane): the brick
            # routes this daemon's fops into the paced rebalance lane
            # from the FIRST post-handshake frame
            client = await mount_volume(host, port, args.volname,
                                        origin="rebalance")
        except Exception as e:
            log.warning(2, "rebalanced mount %s failed (%r), retrying",
                        args.volname, e)
            await asyncio.sleep(1.0)
    tag_rebalance_origin(client.graph)

    async def push(status: dict) -> None:
        _write_statusfile(args.statusfile, status)
        await mgmt_call("rebalance-update", name=args.volname,
                        info=status)

    reb = Rebalancer(client, args.volname, mode=mode,
                     checkpoint=rb.get("checkpoint"),
                     on_checkpoint=push, checkpoint_interval=interval)

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, reb.stop)

    rc = 0
    try:
        final = await reb.run()
        final["status"] = "completed"
        gf_event("REBALANCE_COMPLETE", volume=args.volname,
                 mode=mode, **{k: reb.counters[k] for k in
                               ("scanned", "moved", "failed",
                                "bytes_moved")})
    except RebalanceStopped:
        final = reb.status()
        final["status"] = "stopped"
    except Exception as e:
        log.error(1, "rebalance of %s failed: %r", args.volname, e)
        final = reb.status()
        final["status"] = "failed"
        final["error"] = repr(e)[:300]
        rc = 1
    try:
        _write_statusfile(args.statusfile, final)
        # bounded: on `rebalance stop` the glusterd that SIGTERMed us
        # is blocked reaping this very process, so the push cannot be
        # answered — it harvests the statusfile instead.  An external
        # SIGTERM (operator kill) still lands the push normally.
        await asyncio.wait_for(
            mgmt_call("rebalance-update", name=args.volname,
                      info=final), 2.0)
    except asyncio.TimeoutError:
        log.warning(2, "final rebalance-update timed out "
                       "(statusfile carries the final state)")
    except Exception as e:
        log.error(1, "final rebalance-update failed: %r", e)
        rc = rc or 1
    await link.close()
    await client.unmount()
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-rebalanced")
    p.add_argument("--glusterd", required=True, help="host:port")
    p.add_argument("--volname", required=True)
    p.add_argument("--mode", default="",
                   choices=("", "full", "fix-layout", "drain"))
    p.add_argument("--statusfile", default="")
    p.add_argument("--checkpoint-interval", type=float, default=1.0)
    args = p.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
