"""RPC-over-subprocess-stdio channel for geo-replication (repce analog).

Reference: geo-replication/syncdaemon/repce.py:35-223 — the primary-side
gsyncd never talks to the secondary site directly; it spawns an agent
(there: over ssh to the remote site) and drives it with a pickled RPC
protocol on the agent's stdin/stdout, while resource.py moves data
through the same channel.

Same contract here, tpu-build mechanisms: the agent is a subprocess
whose ONLY link to the worker is its stdio pipes, carrying the
repository's tagged binary wire frames (rpc/wire.py — no pickle).  The
agent mounts the secondary volume in ITS process; the worker process
holds no secondary client at all, which is what makes the link a true
site boundary — swap the local spawn for an ssh spawn and nothing else
changes.

* :class:`RepceClient` — worker side: spawns/respawns the agent,
  correlates xids, exposes the secondary as an async proxy with the
  same method surface a mounted Client has (plus File proxies).
* ``agent`` / ``python -m glusterfs_tpu.mgmt.repce`` — the agent:
  serves ``[method, args, kwargs]`` calls against its mounted client;
  fds are held agent-side in a handle table (fd -> File), the worker
  sees integer handles only.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import itertools
import os
import sys

from ..core.fops import FopError
from ..core import gflog
from ..rpc import wire

log = gflog.get_logger("repce")

_FD_METHODS = ("fwrite", "fread", "fclose")


# ---------------------------------------------------------------------------
# agent side (subprocess; stdio only)
# ---------------------------------------------------------------------------


class _AgentServer:
    def __init__(self, client):
        self.client = client
        self.files: dict[int, object] = {}
        self._ids = itertools.count(1)

    async def handle(self, method: str, args: list, kwargs: dict):
        if method == "__ping__":
            return "pong"
        if method in ("open", "create"):
            f = await getattr(self.client, method)(*args, **kwargs)
            fdid = next(self._ids)
            self.files[fdid] = f
            return {"fd": fdid}
        if method in _FD_METHODS:
            fdid = args[0]
            f = self.files.get(fdid)
            if f is None:
                raise FopError(errno.EBADF, f"agent fd {fdid}")
            if method == "fwrite":
                return await f.write(args[1], args[2])
            if method == "fread":
                return await f.read(args[1], args[2])
            self.files.pop(fdid, None)
            await f.close()
            return None
        fn = getattr(self.client, method, None)
        if fn is None or method.startswith("_"):
            raise FopError(errno.ENOSYS, f"agent method {method!r}")
        ret = await fn(*args, **kwargs)
        # returns stay worker-opaque (the worker only checks errors);
        # shipping Iatt objects across the pipe buys nothing
        return ret if isinstance(ret, (str, bytes, int, list)) else None

    async def serve(self) -> None:
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader()
        await loop.connect_read_pipe(
            lambda: asyncio.StreamReaderProtocol(reader), sys.stdin)
        out_fd = sys.stdout.fileno()
        while True:
            try:
                rec = await wire.read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                return  # worker went away: exit with it
            xid, _mtype, payload = wire.unpack(rec)
            try:
                method, args, kwargs = payload
                ret = await self.handle(method, list(args), kwargs or {})
                frame = wire.pack(xid, wire.MT_REPLY, ret)
            except FopError as e:
                frame = wire.pack(xid, wire.MT_ERROR, e)
            except Exception as e:  # noqa: BLE001 - agent must answer
                frame = wire.pack(xid, wire.MT_ERROR,
                                  FopError(errno.EIO, repr(e)))
            os.write(out_fd, frame)


async def _agent_amain(args) -> None:
    from .glusterd import mount_volume

    host, port, vol = args.secondary.rsplit(":", 2)
    client = None
    while client is None:
        try:
            client = await mount_volume(host, int(port), vol)
        except Exception as e:
            log.warning(1, "agent mount retry: %r", e)
            await asyncio.sleep(1.0)
    try:
        await _AgentServer(client).serve()
    finally:
        try:
            await client.unmount()
        except Exception:
            pass


def agent_main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-georep-agent")
    p.add_argument("--secondary", required=True, help="host:port:volume")
    args = p.parse_args(argv)
    asyncio.run(_agent_amain(args))
    return 0


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class _RemoteFile:
    """File proxy: integer handle on the agent, File surface here."""

    def __init__(self, broker: "RepceClient", fdid: int):
        self._b = broker
        self._fd = fdid

    async def write(self, data: bytes, offset: int = 0) -> int:
        return await self._b._call("fwrite", self._fd, data, offset)

    async def read(self, size: int, offset: int = 0) -> bytes:
        return await self._b._call("fread", self._fd, size, offset)

    async def close(self) -> None:
        await self._b._call("fclose", self._fd)


class RepceClient:
    """The secondary volume as seen through the broker: every call goes
    over the agent's stdio; this process never opens a connection to the
    secondary site."""

    def __init__(self, secondary: str, spawn_env: dict | None = None):
        self.secondary = secondary
        self._env = spawn_env
        self._proc: asyncio.subprocess.Process | None = None
        self._xid = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._reader_task: asyncio.Task | None = None

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    async def _ensure(self) -> None:
        if self.alive:
            return
        # retire the dead channel FIRST: the old reader's unwind clears
        # self._pending, and it must never clobber futures registered
        # against the fresh agent (respawn race)
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):
                pass
            self._reader_task = None
        env = dict(self._env or os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        self._proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "glusterfs_tpu.mgmt.repce",
            "--secondary", self.secondary,
            stdin=asyncio.subprocess.PIPE, stdout=asyncio.subprocess.PIPE,
            env=env)
        self._reader_task = asyncio.create_task(
            self._read_loop(self._proc.stdout))
        log.info(2, "georep agent spawned (pid %d) for %s",
                 self._proc.pid, self.secondary)

    async def _read_loop(self, reader) -> None:
        try:
            while True:
                rec = await wire.read_frame(reader)
                xid, mtype, payload = wire.unpack(rec)
                fut = self._pending.pop(xid, None)
                if fut is None or fut.done():
                    continue
                if mtype == wire.MT_ERROR:
                    fut.set_exception(
                        payload if isinstance(payload, FopError)
                        else FopError(errno.EIO, str(payload)))
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        FopError(errno.ENOTCONN, "georep agent died"))
            self._pending.clear()

    # methods a transient-failure retry cannot double-apply: reads,
    # absolute-state writes (pwrite at an offset, truncate-to-size,
    # setattr, setxattr) and probes.  create/mkdir/rename/unlink stay
    # single-shot — a retry after an already-applied call would surface
    # EEXIST/ENOENT the callers treat as real state.
    _RETRY_SAFE = frozenset((
        "__ping__", "fread", "fwrite", "truncate", "stat", "lookup",
        "exists", "listdir", "listdir_with_stat", "getxattr", "setxattr",
        "setattr", "readlink", "statvfs",
    ))
    #: transient classes worth retrying: the RPC deadline raced a loaded
    #: host (ETIMEDOUT — the georep inodelk flake, VERDICT r5 weak #5)
    #: or the agent died mid-call (ENOTCONN; _ensure respawns it)
    _RETRY_ERRS = (errno.ETIMEDOUT, errno.ENOTCONN)
    _RETRY_MAX = 3

    async def _call(self, method: str, *args, **kwargs):
        """One agent RPC, with bounded retry-with-backoff for idempotent
        methods on transient failures.  Scaled deadlines alone (the
        r5 deflake) still lose the race on a pathologically loaded
        host; the retry converts the residual flake into latency."""
        last: FopError | None = None
        for attempt in range(self._RETRY_MAX):
            if attempt:
                # exponential backoff off the contended window
                await asyncio.sleep(0.2 * (2 ** (attempt - 1)))
            try:
                return await self._call_once(method, *args, **kwargs)
            except FopError as e:
                if e.err not in self._RETRY_ERRS or \
                        method not in self._RETRY_SAFE:
                    raise
                last = e
                log.warning(3, "georep %s transient failure "
                            "(attempt %d/%d): %s", method, attempt + 1,
                            self._RETRY_MAX, e)
        raise last

    async def _call_once(self, method: str, *args, **kwargs):
        await self._ensure()
        xid = next(self._xid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        try:
            self._proc.stdin.write(wire.pack(
                xid, wire.MT_CALL, [method, list(args), kwargs or {}]))
            await self._proc.stdin.drain()
        except (ConnectionError, RuntimeError, BrokenPipeError):
            self._pending.pop(xid, None)
            raise FopError(errno.ENOTCONN, "georep agent pipe") from None
        return await fut

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        if self._proc is not None and self._proc.returncode is None:
            self._proc.terminate()
            try:
                await asyncio.wait_for(self._proc.wait(), 5)
            except asyncio.TimeoutError:
                self._proc.kill()
        self._proc = None

    # -- the Client surface the worker drives ------------------------------

    async def open(self, path, flags=os.O_RDWR):
        out = await self._call("open", path, flags)
        return _RemoteFile(self, out["fd"])

    async def create(self, path, flags=os.O_RDWR, mode=0o644):
        out = await self._call("create", path, flags, mode)
        return _RemoteFile(self, out["fd"])

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def proxied(*args, **kwargs):
            return await self._call(name, *args, **kwargs)

        proxied.__name__ = name
        return proxied


def main(argv=None) -> int:
    return agent_main(argv)


if __name__ == "__main__":
    sys.exit(main())
