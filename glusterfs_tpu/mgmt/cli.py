"""``gftpu`` — the gluster CLI analog.

Reference: cli/ (30k LoC — readline shell, parser, RPC to glusterd).
Command surface kept (cli-cmd-volume.c vocabulary):

    gftpu volume create NAME [disperse N | replica N] BRICK...
    gftpu volume start|stop|delete NAME
    gftpu volume info [NAME]
    gftpu volume status NAME [detail|clients|fds|inodes|callpool|mem]
    gftpu volume set NAME KEY VALUE
    gftpu volume heal NAME [info] [PATH] | statistics heal-count
    gftpu volume clear-locks NAME PATH kind {blocked|granted|all}
    gftpu volume quota NAME enable|disable|list|limit-usage PATH BYTES|remove PATH
    gftpu volume rebalance NAME start [fix-layout]|status|stop
    gftpu volume profile NAME
    gftpu volume metrics NAME
    gftpu volume gateway NAME start|stop|status
    gftpu volume incident NAME capture|list|show [BUNDLE]
    gftpu volume alerts NAME list|history|rules
    gftpu peer probe HOST:PORT | peer status

Talks to glusterd over the mgmt wire RPC (--server host:port, default
127.0.0.1:24007).  ``--json`` prints machine-readable output (the
reference's --xml).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import Any

from ..protocol.server import STATUS_KINDS
from .glusterd import MgmtClient, mount_volume


def _fmt(v: Any, as_json: bool, as_xml: bool = False) -> str:
    if as_xml:
        return _xml_output(v)
    if as_json:
        return json.dumps(v, indent=1, default=repr)
    return _pretty(v)


def _table(headers: list[str], rows: list[list]) -> str:
    """Fixed-width text table (cli-cmd-volume.c's human status
    rendering analog)."""
    cells = [[str(c) for c in r] for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in cells)) if cells
              else len(h) for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths))
              for r in cells]
    return "\n".join(lines)


def _human_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _status_human(what: str, out: dict) -> str:
    """Human tables for the deep-status kinds that are naturally
    tabular; the rest fall back to the generic tree rendering."""
    parts = []
    if out.get("partial"):
        parts.append("WARNING: partial answer — missing nodes: "
                     + ", ".join(out["partial"]))
    bricks = out.get("bricks", {})
    if what == "clients":
        rows = []
        for bname in sorted(bricks):
            payload = bricks[bname] or {}
            for c in payload.get("clients", ()):
                qos = c.get("qos") or {}
                if not qos.get("enabled"):
                    shaped = "-"
                elif qos.get("shaped"):
                    # inside a throttle window right now (reason =
                    # rate / soft-quota), with the lifetime shed count
                    shaped = (f"{qos.get('reason', '')}"
                              f"({qos.get('shed_fops', 0)})")
                else:
                    shaped = "no"
                rows.append([bname, c["client"][:16], c["addr"],
                             f"{c['uptime']:.0f}s",
                             _human_bytes(c["bytes_rx"]),
                             _human_bytes(c["bytes_tx"]),
                             c["fops"], c["opened_fds"], shaped,
                             "mgmt" if c.get("mgmt") else
                             f"op-{c.get('op_version', 0)}"])
            if payload.get("offline"):
                rows.append([bname, "-", "-", "-", "-", "-", "-", "-",
                             "-", "OFFLINE"])
        parts.append(_table(["BRICK", "CLIENT", "ADDR", "UPTIME", "RX",
                             "TX", "FOPS", "FDS", "SHAPED", "KIND"],
                            rows))
        return "\n".join(parts)
    if what == "fds":
        rows = []
        for bname in sorted(bricks):
            payload = bricks[bname] or {}
            for tab in payload.get("fd_tables", ()):
                for fd in tab["fds"]:
                    rows.append([bname, tab["client"][:16], fd["fd"],
                                 fd["path"] or fd["gfid"][:16],
                                 fd["flags"]])
            if payload.get("offline"):
                rows.append([bname, "-", "-", "OFFLINE", "-"])
        parts.append(_table(["BRICK", "CLIENT", "FD", "PATH", "FLAGS"],
                            rows))
        return "\n".join(parts)
    if what == "detail":
        rows = []
        for bname in sorted(bricks):
            payload = bricks[bname] or {}
            for be in payload.get("backends", ()):
                bs = be.get("block_size", 0)
                rows.append([
                    bname, be["path"], be["health"],
                    _human_bytes(be.get("blocks_avail", 0) * bs),
                    _human_bytes(be.get("blocks_total", 0) * bs),
                    be.get("inodes_free", "-"),
                    "yes" if be.get("reserve_limited") else "no"])
            if payload.get("offline"):
                rows.append([bname, "-", "OFFLINE", "-", "-", "-", "-"])
        parts.append(_table(["BRICK", "PATH", "HEALTH", "FREE", "TOTAL",
                             "INODES-FREE", "RESERVE-LIMITED"], rows))
        return "\n".join(parts)
    parts.append(_pretty(out))
    return "\n".join(parts)


_NCNAME = None


def _xml_output(v: Any, op_ret: int = 0, op_errno: int = 0,
                op_errstr: str = "") -> str:
    """Machine-readable XML in the reference's cli-xml-output.c
    envelope: <cliOutput><opRet/><opErrno/><opErrstr/>payload."""
    import re
    import xml.etree.ElementTree as ET

    global _NCNAME
    if _NCNAME is None:
        _NCNAME = re.compile(r"^[A-Za-z_][\w.-]*$")

    def build(parent, val, key=None):
        if key is None or not _NCNAME.match(str(key)):
            el = ET.SubElement(parent, "entry")
            if key is not None:
                el.set("name", str(key))
        else:
            el = ET.SubElement(parent, str(key))
        if isinstance(val, dict):
            for k, x in val.items():
                build(el, x, k)
        elif isinstance(val, (list, tuple)):
            for x in val:
                build(el, x, "item")
        elif val is not None:
            el.text = str(val)
        return el

    root = ET.Element("cliOutput")
    ET.SubElement(root, "opRet").text = str(op_ret)
    ET.SubElement(root, "opErrno").text = str(op_errno)
    ET.SubElement(root, "opErrstr").text = op_errstr
    if isinstance(v, dict):
        for k, x in v.items():
            build(root, x, k)
    elif v is not None:
        build(root, v, "output")
    ET.indent(root)
    return ET.tostring(root, encoding="unicode",
                       xml_declaration=True)


def _pretty(v: Any, indent: int = 0) -> str:
    pad = "  " * indent
    if isinstance(v, dict):
        return "\n".join(f"{pad}{k}: " + (
            "\n" + _pretty(val, indent + 1)
            if isinstance(val, (dict, list)) and val else _pretty(val))
            for k, val in v.items())
    if isinstance(v, list):
        return "\n".join(f"{pad}- " + (_pretty(x).lstrip()
                                       if not isinstance(x, (dict, list))
                                       else "\n" + _pretty(x, indent + 1))
                         for x in v)
    return f"{pad}{v}" if indent else str(v)


async def _run(args) -> Any:
    host, _, port = args.server.partition(":")
    port = int(port or 24007)

    if args.cmd == "peer":
        async with MgmtClient(host, port) as c:
            if args.sub == "probe":
                ph, _, pp = args.target.partition(":")
                return await c.call("peer-probe", host=ph, port=int(pp))
            return await c.call("peer-status")

    if args.cmd == "eventsapi":
        async with MgmtClient(host, port) as c:
            return await c.call("eventsapi", action=args.sub,
                                url=args.args[0] if args.args else "")

    if args.cmd == "georep":
        # georep PRIMARY create SECONDARY | start|stop|status PRIMARY
        async with MgmtClient(host, port) as c:
            if args.sub == "create":
                if not args.args:
                    raise SystemExit("usage: georep NAME create "
                                     "host:port:volume")
                return await c.call("georep-create", name=args.name,
                                    secondary=args.args[0])
            return await c.call(f"georep-{args.sub}", name=args.name)

    if args.cmd == "snapshot":
        # snapshot create NAME VOLUME | list [VOLUME] |
        #          clone CLONENAME SNAPNAME |
        #          delete|restore|activate|deactivate NAME
        need = {"create": 2, "clone": 2, "list": 0}.get(args.sub, 1)
        if len(args.args) < need:
            raise SystemExit(
                "usage: snapshot create NAME VOLUME | list [VOLUME] | "
                "clone CLONENAME SNAPNAME | "
                "delete|restore|activate|deactivate NAME")
        async with MgmtClient(host, port) as c:
            if args.sub == "create":
                return await c.call("snapshot-create", name=args.args[0],
                                    volume=args.args[1])
            if args.sub == "clone":
                return await c.call("snapshot-clone",
                                    clonename=args.args[0],
                                    snapname=args.args[1])
            if args.sub == "list":
                return await c.call(
                    "snapshot-list",
                    volume=args.args[0] if args.args else None)
            return await c.call(f"snapshot-{args.sub}",
                                name=args.args[0])

    if args.cmd == "volume":
        sub = args.sub
        if sub == "create":
            vtype = "distribute"
            redundancy = 0
            group = 0
            rest = list(args.args)
            if rest and rest[0] == "disperse":
                vtype = "disperse"
                redundancy = int(rest[1])
                rest = rest[2:]
            elif rest and rest[0] == "replica":
                vtype = "replicate"
                group = int(rest[1])
                rest = rest[2:]
            arbiter = thin = 0
            systematic = -1  # unset: disperse defaults systematic at
            # cluster op-version >= 12 (explicit opt-out below)
            if rest and rest[0] == "arbiter":
                arbiter = int(rest[1])
                rest = rest[2:]
            if rest and rest[0] == "thin-arbiter":
                thin = int(rest[1])
                rest = rest[2:]
            if rest and rest[0] == "systematic":
                # fragment format flag (create-time only; see
                # cluster/disperse "systematic")
                systematic = 1
                rest = rest[1:]
            elif rest and rest[0] == "non-systematic":
                # explicit opt-out of the systematic default (the
                # mesh codec tier has no systematic mode yet)
                systematic = 0
                rest = rest[1:]
            bricks = [{"path": b.split(":", 1)[-1],
                       "host": "127.0.0.1"} for b in rest]
            async with MgmtClient(host, port) as c:
                return await c.call("volume-create", name=args.name,
                                    vtype=vtype, bricks=bricks,
                                    redundancy=redundancy,
                                    group_size=group, arbiter=arbiter,
                                    thin_arbiter=thin,
                                    systematic=systematic)
        if sub == "status":
            # volume status NAME [detail|clients|fds|inodes|callpool|mem]
            what = args.args[0] if args.args else ""
            async with MgmtClient(host, port) as c:
                if not what:
                    return await c.call("volume-status", name=args.name)
                if what not in STATUS_KINDS:
                    raise SystemExit(
                        "usage: volume status NAME "
                        "[detail|clients|fds|inodes|callpool|mem]")
                return await c.call("volume-status-deep",
                                    name=args.name, what=what)
        if sub in ("start", "stop", "delete"):
            async with MgmtClient(host, port) as c:
                return await c.call(f"volume-{sub}", name=args.name)
        if sub == "info":
            async with MgmtClient(host, port) as c:
                return await c.call("volume-info",
                                    name=args.name or None)
        if sub == "set":
            async with MgmtClient(host, port) as c:
                return await c.call("volume-set", name=args.name,
                                    key=args.args[0], value=args.args[1])
        if sub == "heal":
            if args.args and args.args[0] == "statistics":
                # volume heal NAME statistics heal-count — answered
                # from the bricks' index counters through glusterd, no
                # temporary client graph mounted
                if len(args.args) > 1 and args.args[1] != "heal-count":
                    raise SystemExit("usage: volume heal NAME "
                                     "statistics heal-count")
                async with MgmtClient(host, port) as c:
                    return await c.call("volume-heal-count",
                                        name=args.name)
            client = await mount_volume(host, port, args.name)
            try:
                top = _find_cluster_layer(client.graph)
                from ..core.layer import Loc

                if args.args and args.args[0] == "split-brain":
                    # heal NAME split-brain bigger-file|latest-mtime PATH
                    #                      |source-brick IDX PATH
                    usage = ("usage: volume heal NAME split-brain "
                             "{bigger-file|latest-mtime} PATH | "
                             "source-brick IDX PATH")
                    rest = args.args[1:]
                    if not rest:
                        raise SystemExit(usage)
                    policy = rest[0]
                    if not hasattr(top, "split_brain_resolve"):
                        raise SystemExit(
                            "split-brain resolution is a replicate-"
                            "volume operation")
                    if policy == "source-brick":
                        if len(rest) < 3:
                            raise SystemExit(usage)
                        return await top.split_brain_resolve(
                            rest[2], policy, int(rest[1]))
                    if len(rest) < 2:
                        raise SystemExit(usage)
                    return await top.split_brain_resolve(rest[1], policy)
                path = args.args[1] if len(args.args) > 1 else \
                    (args.args[0] if args.args and
                     args.args[0] != "info" else "/")
                if args.args and args.args[0] == "info":
                    if path == "/":
                        return await _heal_info_all(client, top)
                    return await top.heal_info(Loc(path))
                if path == "/":
                    return await _heal_all(client, top)
                return await top.heal_file(path)
            finally:
                await client.unmount()
        if sub == "clear-locks":
            # volume clear-locks NAME PATH kind {blocked|granted|all}
            # (the literal "kind" keyword mirrors the reference's
            # syntax; tolerated absent).  Rides the brick-side
            # revocation machinery; --json prints the per-brick
            # cleared counts
            usage = ("usage: volume clear-locks NAME PATH kind "
                     "{blocked|granted|all}")
            rest = list(args.args)
            if not rest:
                raise SystemExit(usage)
            path = rest.pop(0)
            if rest and rest[0] == "kind":
                rest.pop(0)
            kind = rest.pop(0) if rest else "all"
            if kind not in ("blocked", "granted", "all") or rest:
                raise SystemExit(usage)
            async with MgmtClient(host, port) as c:
                return await c.call("volume-clear-locks",
                                    name=args.name, path=path,
                                    kind=kind)
        if sub == "quota":
            # gftpu volume quota NAME enable|disable|list
            #                        |limit-usage PATH BYTES|remove PATH
            action = args.args[0] if args.args else "list"
            kw = {"name": args.name, "action": action}
            if action == "limit-usage":
                kw.update(path=args.args[1], limit=int(args.args[2]))
            elif action == "remove":
                kw.update(path=args.args[1])
            async with MgmtClient(host, port) as c:
                return await c.call("volume-quota", **kw)
        if sub == "add-brick":
            # raw "node:path" (or bare path) strings: glusterd's
            # _parse_new_bricks resolves the node part
            bricks = [b if ":" in b else {"path": b, "host": "127.0.0.1"}
                      for b in args.args]
            async with MgmtClient(host, port) as c:
                return await c.call("volume-add-brick", name=args.name,
                                    bricks=bricks)
        if sub == "remove-brick":
            # volume remove-brick NAME BRICK...
            #                     start|status|stop|commit|force
            actions = ("start", "status", "stop", "commit", "force")
            action = args.args[-1] if args.args and \
                args.args[-1] in actions else "start"
            named = [a for a in args.args if a not in actions]
            async with MgmtClient(host, port) as c:
                return await c.call("volume-remove-brick",
                                    name=args.name, bricks=named,
                                    action=action)
        if sub == "replace-brick":
            if len(args.args) < 2:
                raise SystemExit("usage: volume replace-brick NAME "
                                 "BRICK NEWPATH [commit force]")
            async with MgmtClient(host, port) as c:
                return await c.call("volume-replace-brick",
                                    name=args.name, brick=args.args[0],
                                    new_path=args.args[1])
        if sub == "bitrot":
            action = args.args[0] if args.args else "status"
            async with MgmtClient(host, port) as c:
                return await c.call("volume-bitrot", name=args.name,
                                    action=action)
        if sub == "rebalance":
            # volume rebalance NAME start [fix-layout] | status | stop
            # — the glusterd-managed per-volume daemon (checkpointed,
            # throttleable, resumable; op-version 13).  Legacy direct
            # forms stay: `fix-layout [child=weight ...]` rewrites the
            # persisted hash ranges in-process; bare `rebalance NAME`
            # runs the one-shot in-process walk.
            if args.args and args.args[0] in ("start", "status",
                                              "stop"):
                action = args.args[0]
                flavor = args.args[1] if len(args.args) > 1 else ""
                async with MgmtClient(host, port) as c:
                    return await c.call("volume-rebalance",
                                        name=args.name, action=action,
                                        flavor=flavor)
            # the daemon's temp handling assumes it is the volume's
            # ONLY migrator (both walks target the same deterministic
            # `.NAME.rebalance~` temps) — refuse the legacy in-process
            # forms while a managed run is live
            async with MgmtClient(host, port) as c:
                info = await c.call("volume-info", name=args.name)
            if (info.get(args.name, {}).get("rebalance") or {}) \
                    .get("status") == "started":
                return {"error": "a managed rebalance is running on "
                                 f"{args.name}; the in-process walk "
                                 "would race its migrator (`volume "
                                 f"rebalance {args.name} stop` first)"}
            client = await mount_volume(host, port, args.name)
            try:
                from ..cluster.dht import DistributeLayer

                dht = _find_layer(client.graph, DistributeLayer)
                if dht is None:
                    return {"error": "not a distributed volume"}
                if args.args and args.args[0] == "fix-layout":
                    weights = {}
                    for spec in args.args[1:]:
                        child, sep, w = spec.partition("=")
                        try:
                            if not sep:
                                raise ValueError
                            weights[child] = float(w)
                        except ValueError:
                            return {"error": f"bad weight {spec!r} "
                                             "(want child=NUMBER)"}
                    return await dht.fix_layout("/", weights or None)
                return await dht.rebalance("/")
            finally:
                await client.unmount()
        if sub == "profile":
            # BRICK-side cumulative stats (volume profile info): the
            # bricks have been counting since they started — a freshly
            # mounted client's own io-stats would be empty
            async with MgmtClient(host, port) as c:
                return await c.call("volume-profile", name=args.name)
        if sub == "metrics":
            # per-brick unified-registry scrape (counters/gauges/
            # histograms from every subsystem; core/metrics.py)
            async with MgmtClient(host, port) as c:
                return await c.call("volume-metrics", name=args.name)
        if sub == "gateway":
            # volume gateway NAME start|stop|status — the HTTP object
            # front door (gateway/); status reports pid + bound port
            action = args.args[0] if args.args else "status"
            async with MgmtClient(host, port) as c:
                return await c.call("volume-gateway", name=args.name,
                                    action=action)
        if sub == "top":
            # volume top NAME [open|read|write|read-bytes|write-bytes]
            # [COUNT] — ranked per-path counters from each BRICK's
            # io-stats layer (gluster volume top)
            metric = args.args[0] if args.args else "open"
            cnt = int(args.args[1]) if len(args.args) > 1 else 10
            async with MgmtClient(host, port) as c:
                return await c.call("volume-top", name=args.name,
                                    metric=metric, count=cnt)
        if sub == "incident":
            # volume incident NAME capture|list|show [BUNDLE] — the
            # flight-recorder plane: capture fans a snapshot across
            # bricks + gateway + service daemons into one cluster
            # bundle; list/show read the incident dir
            action = args.args[0] if args.args else "list"
            if action not in ("capture", "list", "show"):
                raise SystemExit("usage: volume incident NAME "
                                 "capture|list|show [BUNDLE]")
            async with MgmtClient(host, port) as c:
                if action == "show":
                    bundle = args.args[1] if len(args.args) > 1 else ""
                    return await c.call("volume-incident-show",
                                        name=args.name, bundle=bundle)
                return await c.call(f"volume-incident-{action}",
                                    name=args.name)
        if sub == "alerts":
            # volume alerts NAME list|history|rules — the SLO plane:
            # list unions every process's currently-raised alerts,
            # history shows recent RAISED/CLEARED transition edges,
            # rules echoes the configured diagnostics.slo-rules set
            # (with validation errors)
            action = args.args[0] if args.args else "list"
            if action not in ("list", "history", "rules"):
                raise SystemExit("usage: volume alerts NAME "
                                 "list|history|rules")
            async with MgmtClient(host, port) as c:
                return await c.call("volume-alerts", name=args.name,
                                    action=action)
    raise SystemExit(f"unknown command {args.cmd} {args.sub}")


def _find_layer(graph, klass):
    for layer in graph.by_name.values():
        if isinstance(layer, klass):
            return layer
    return None


def _find_cluster_layer(graph):
    from ..cluster.afr import ReplicateLayer
    from ..cluster.ec import DisperseLayer

    for klass in (DisperseLayer, ReplicateLayer):
        layer = _find_layer(graph, klass)
        if layer is not None:
            return layer
    raise SystemExit("volume has no replicate/disperse layer to heal")


async def _walk_files(client, path="/"):
    out = []
    for name, ia in await client.listdir_with_stat(path):
        child = path.rstrip("/") + "/" + name
        if ia is not None and ia.is_dir():
            out.extend(await _walk_files(client, child))
        else:
            out.append(child)
    return out


async def _heal_info_all(client, top):
    from ..core.layer import Loc

    out = {}
    for f in await _walk_files(client):
        info = await top.heal_info(Loc(f))
        if info["bad"]:
            out[f] = info["bad"]
    return {"files_needing_heal": out, "count": len(out)}


async def _heal_all(client, top):
    healed = {}
    for f in await _walk_files(client):
        res = await top.heal_file(f)
        if res.get("healed"):
            healed[f] = res["healed"]
    return {"healed": healed, "count": len(healed)}


def _shell(server: str, flags: list[str]) -> int:
    """Interactive command shell (the reference's readline UI,
    cli-rl.c): `gftpu` with no command drops into `gftpu> ` and runs
    each line through the normal parser against --server, keeping the
    outer --json/--xml formatting."""
    import shlex

    try:
        import readline  # noqa: F401  (line editing + history)
    except ImportError:
        pass
    print("gftpu interactive shell — 'exit' to quit")
    while True:
        try:
            line = input("gftpu> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not line:
            continue
        if line in ("exit", "quit", "q"):
            return 0
        try:
            words = shlex.split(line)  # quoted args survive
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            continue
        if not any(not w.startswith("-") for w in words) and \
                not {"-h", "--help"} & set(words):
            # flag-only line would recurse into a nested shell
            # (--help is fine: argparse SystemExits before the shell)
            print("error: missing command", file=sys.stderr)
            continue
        try:
            main(["--server", server, *flags, *words])
        except SystemExit:
            pass  # argparse usage error: printed; the shell continues
        except KeyboardInterrupt:
            print()  # Ctrl-C aborts the command, not the shell


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu")
    p.add_argument("--server", default="127.0.0.1:24007")
    p.add_argument("--json", action="store_true")
    p.add_argument("--xml", action="store_true",
                   help="cli-xml-output.c style machine output")
    sp = p.add_subparsers(dest="cmd")  # no cmd -> interactive shell

    vol = sp.add_parser("volume")
    vol.add_argument("sub", choices=["create", "start", "stop", "delete",
                                     "info", "status", "set", "heal",
                                     "rebalance", "profile", "metrics",
                                     "quota", "bitrot", "add-brick",
                                     "remove-brick", "replace-brick",
                                     "top", "gateway", "clear-locks",
                                     "incident", "alerts"])
    vol.add_argument("name", nargs="?", default="")
    vol.add_argument("args", nargs="*")

    geo = sp.add_parser("georep")
    geo.add_argument("name")
    geo.add_argument("sub", choices=["create", "start", "stop",
                                     "status", "checkpoint"])
    geo.add_argument("args", nargs="*")

    snap = sp.add_parser("snapshot")
    snap.add_argument("sub", choices=["create", "clone", "list",
                                      "delete", "restore", "activate",
                                      "deactivate"])
    snap.add_argument("args", nargs="*")

    peer = sp.add_parser("peer")
    peer.add_argument("sub", choices=["probe", "status"])
    peer.add_argument("target", nargs="?", default="")

    ev = sp.add_parser("eventsapi")
    ev.add_argument("sub", choices=["webhook-add", "webhook-del",
                                    "status"])
    ev.add_argument("args", nargs="*")

    args = p.parse_args(argv)
    if args.cmd is None:
        if not sys.stdin.isatty():
            # scripts/cron piping into `gftpu` must get the usage
            # error they always got, not an accidental shell
            p.error("a command is required (interactive shell needs "
                    "a tty)")
        flags = [f for f, on in (("--json", args.json),
                                 ("--xml", args.xml)) if on]
        return _shell(args.server, flags)
    try:
        out = asyncio.run(_run(args))
    except Exception as e:
        if args.xml:
            err = getattr(e, "err", 1)
            print(_xml_output(None, op_ret=-1, op_errno=int(err),
                              op_errstr=str(e)))
        else:
            print(f"error: {e}", file=sys.stderr)
        return 1
    if not args.json and not args.xml and args.cmd == "volume" and \
            args.sub == "status" and args.args and \
            args.args[0] in STATUS_KINDS and isinstance(out, dict):
        print(_status_human(args.args[0], out))
        return 0
    print(_fmt(out, args.json, args.xml))
    return 0


if __name__ == "__main__":
    sys.exit(main())
