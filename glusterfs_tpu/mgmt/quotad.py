"""quotad — the quota aggregator daemon.

Reference: xlators/features/quota/src/quotad.c + quotad-aggregator.c:
one daemon aggregates per-brick marker sizes so 'quota list' (and soft
limit alerting) can report volume-wide usage.  Here: a per-volume
process (spawned by glusterd when features.quota is on, like bitd) that
polls every brick's quota layer over the brick RPC (``quota_usage``
extra), aggregates, persists a statusfile, and answers ``quota-list``
queries on its own wire port.

Aggregation is **sum over groups of max within a group**: bricks in
one replica/disperse group all hold the same logical files (each
already reports logical bytes — the layer scales fragments by K), so
within a group the max is the truth; distinct DHT groups hold disjoint
subtrees, so groups add.  glusterd tags each brick with its group in
``--bricks name:port:group``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from ..core import gflog
from ..rpc import wire

log = gflog.get_logger("mgmt.quotad")


class Quotad:
    def __init__(self, layers, groups, interval: float = 2.0):
        self.layers = layers
        self.groups = groups  # layer -> group id
        self.interval = interval
        self.usage: dict[str, dict] = {}  # path -> {used, limit}

    async def poll_once(self) -> dict:
        # path -> group -> max logical bytes seen in that group
        per_group: dict[str, dict[int, int]] = {}
        limits: dict[str, int] = {}
        for l in self.layers:
            if not l.connected:
                continue
            try:
                per = await l.remote("quota_usage")
            except Exception as e:
                log.debug(1, "quota_usage from %s failed: %r", l.name, e)
                continue
            grp = self.groups.get(l.name, 0)
            for d, ent in (per or {}).items():
                g = per_group.setdefault(d, {})
                g[grp] = max(g.get(grp, 0), ent["used"])
                limits[d] = ent["limit"]
        agg = {d: {"used": sum(groups.values()), "limit": limits[d],
                   "available": max(0, limits[d] - sum(groups.values()))}
               for d, groups in per_group.items()}
        self.usage = agg
        return agg

    async def serve(self, reader, writer) -> None:
        try:
            while True:
                try:
                    rec = await wire.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                xid, _, payload = wire.unpack(rec)
                method = payload[0] if isinstance(payload, list) else payload
                if method == "quota-list":
                    await self.poll_once()  # serve fresh numbers
                    resp = self.usage
                else:
                    resp = {"error": f"unknown {method!r}"}
                writer.write(wire.pack(xid, wire.MT_REPLY, resp))
                await writer.drain()
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def _amain(args) -> None:
    from ..protocol.client import ClientLayer
    from . import svcutil

    layers = []
    groups = {}
    for spec in args.bricks.split(","):
        parts = spec.rsplit(":", 2)
        if len(parts) == 2:
            parts.append("0")
        name, port, group = parts
        lname = f"quotad-{name}"
        layers.append(ClientLayer(lname, svcutil.client_opts(
            args, "GFTPU_QUOTAD", args.host, int(port), name)))
        groups[lname] = int(group)
    for l in layers:
        await l.init()
    qd = Quotad(layers, groups, args.interval)
    server = await asyncio.start_server(qd.serve, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    if args.portfile:
        tmp = args.portfile + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.portfile)
    log.info(2, "quotad serving on %d over %d bricks", port, len(layers))
    while True:
        try:
            await qd.poll_once()
        except Exception as e:
            log.error(3, "quotad poll failed: %r", e)
        if args.statusfile:
            tmp = args.statusfile + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"pid": os.getpid(), "usage": qd.usage}, f)
            os.replace(tmp, args.statusfile)
        await asyncio.sleep(args.interval)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-quotad")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--bricks", required=True,
                   help="comma list of brickname:port")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--portfile", default="")
    p.add_argument("--statusfile", default="")
    from . import svcutil
    svcutil.add_ssl_args(p)
    args = p.parse_args(argv)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
