"""Volfile generation — the glusterd-volgen analog.

Reference: xlators/mgmt/glusterd/src/glusterd-volgen.c (build_client_graph
:71, server_graph_table :2526, volgen_write_volfile :986) and the option
map glusterd-volume-set.c: ``gluster volume set`` keys map to layer
options, and volgen assembles the brick-side and client-side graphs from
volinfo.

Graph shapes produced (mirroring the reference's defaults):

brick volfile:   posix -> locks -> [io-stats] (served by the brick daemon)
client volfile:  protocol/client per brick -> cluster layer (disperse /
                 replicate / distribute / distributed-X) -> performance
                 layers (per options) -> [io-stats top]
"""

from __future__ import annotations

from typing import Any

# Options introduced after op-version 1 (glusterd-volume-set.c's
# .op_version fields): a mixed-version cluster may only set keys every
# member understands — the cluster op-version is the MINIMUM any
# member advertises (xlator.h:758 op_version model).
OPTION_MIN_OPVERSION = {
    "cluster.brick-multiplex": 2,
    "cluster.nufa": 2,
    "cluster.nufa-local-volume-name": 2,
    "cluster.switch-pattern": 2,
    "cluster.server-quorum-type": 2,
    "cluster.server-quorum-ratio": 2,
    "features.simple-quota": 2,
    "bitrot.scrub-throttle": 2,
    "storage.health-check-interval": 2,
    "disperse.stripe-cache": 2,
    "disperse.stripe-cache-window": 2,
    "disperse.stripe-cache-min-batch": 2,
}

# volume-set key -> (layer type, option name)  (glusterd-volume-set.c map)
OPTION_MAP = {
    "auth.allow": ("protocol/server", "auth-allow"),
    "server.outstanding-rpc-limit": ("protocol/server",
                                     "outstanding-rpc-limit"),
    "auth.reject": ("protocol/server", "auth-reject"),
    "server.ssl": ("protocol/server", "ssl"),
    "client.ssl": ("protocol/client", "ssl"),
    # cert/key/ca paths feed both transport ends (socket.c ssl_setup)
    "ssl.cert": ("__ssl__", "ssl-cert"),
    "ssl.key": ("__ssl__", "ssl-key"),
    "ssl.ca": ("__ssl__", "ssl-ca"),
    "disperse.cpu-extensions": ("cluster/disperse", "cpu-extensions"),
    # stripe-cache (ec.c:286): the TPU batching window over the codec
    "disperse.stripe-cache": ("cluster/disperse", "stripe-cache"),
    "disperse.stripe-cache-window": ("cluster/disperse",
                                     "stripe-cache-window"),
    "disperse.stripe-cache-min-batch": ("cluster/disperse",
                                        "stripe-cache-min-batch"),
    "disperse.read-policy": ("cluster/disperse", "read-policy"),
    "disperse.quorum-count": ("cluster/disperse", "quorum-count"),
    "disperse.eager-lock": ("cluster/disperse", "eager-lock"),
    "disperse.other-eager-lock": ("cluster/disperse",
                                  "other-eager-lock"),
    "disperse.eager-lock-timeout": ("cluster/disperse",
                                    "eager-lock-timeout"),
    "disperse.self-heal-window-size": ("cluster/disperse",
                                       "self-heal-window-size"),
    "disperse.ec-read-mask": ("cluster/disperse", "ec-read-mask"),
    "disperse.parallel-writes": ("cluster/disperse", "parallel-writes"),
    "cluster.quorum-count": ("cluster/replicate", "quorum-count"),
    # consumed by glusterd's shd spawner, not a graph layer
    "cluster.heal-timeout": ("mgmt/shd", "interval"),
    "cluster.read-hash-mode": ("cluster/replicate", "read-hash-mode"),
    "cluster.favorite-child-policy": ("cluster/replicate",
                                      "favorite-child-policy"),
    "cluster.lookup-unhashed": ("cluster/distribute", "lookup-unhashed"),
    "cluster.min-free-disk": ("cluster/distribute", "min-free-disk"),
    "cluster.rebal-throttle": ("cluster/distribute", "rebal-throttle"),
    "network.ping-timeout": ("protocol/client", "ping-timeout"),
    "storage.health-check-interval": ("storage/posix",
                                      "health-check-interval"),
    "performance.write-behind": ("performance/write-behind", "__enable__"),
    "performance.write-behind-window-size": ("performance/write-behind",
                                             "window-size"),
    "performance.io-cache": ("performance/io-cache", "__enable__"),
    "performance.cache-size": ("performance/io-cache", "cache-size"),
    "performance.read-ahead": ("performance/read-ahead", "__enable__"),
    "performance.read-ahead-page-count": ("performance/read-ahead",
                                          "page-count"),
    "performance.md-cache": ("performance/md-cache", "__enable__"),
    "performance.md-cache-timeout": ("performance/md-cache", "timeout"),
    "performance.quick-read": ("performance/quick-read", "__enable__"),
    "performance.open-behind": ("performance/open-behind", "__enable__"),
    "performance.nl-cache": ("performance/nl-cache", "__enable__"),
    "performance.readdir-ahead": ("performance/readdir-ahead", "__enable__"),
    "performance.io-thread-count": ("performance/io-threads",
                                    "thread-count"),
    "diagnostics.latency-measurement": ("debug/io-stats",
                                        "latency-measurement"),
    "changelog.changelog": ("features/changelog", "__enable__"),
    # consumed by glusterd's gsyncd spawner, not a graph layer
    "georep.sync-interval": ("mgmt/gsyncd", "interval"),
    "changelog.rollover-time": ("features/changelog", "rollover-time"),
    "features.barrier": ("features/barrier", "barrier"),
    "features.barrier-timeout": ("features/barrier", "barrier-timeout"),
    "features.bitrot": ("features/bit-rot-stub", "__enable__"),
    # consumed by glusterd's bitd spawner, not a graph layer
    "bitrot.scrub-interval": ("mgmt/bitd", "scrub-interval"),
    "bitrot.signer-quiesce": ("mgmt/bitd", "quiesce"),
    "bitrot.scrub-throttle": ("mgmt/bitd", "scrub-throttle"),
    "features.cache-invalidation": ("features/upcall", "__enable__"),
    "features.cache-invalidation-timeout": ("features/upcall",
                                            "cache-invalidation-timeout"),
    "features.read-only": ("features/read-only", "__enable__"),
    "features.worm": ("features/worm", "__enable__"),
    "features.quota": ("features/quota", "__enable__"),
    "features.simple-quota": ("features/simple-quota", "__enable__"),
    "features.trash": ("features/trash", "__enable__"),
    "features.shard": ("features/shard", "__enable__"),
    "features.shard-block-size": ("features/shard", "shard-block-size"),
    "features.leases": ("features/leases", "__enable__"),
    "features.lease-recall-timeout": ("features/leases",
                                      "recall-timeout"),
    "features.quiesce": ("features/quiesce", "quiesce"),
    "features.gfid-access": ("features/gfid-access", "__enable__"),
    "features.acl": ("system/posix-acl", "__enable__"),
    "features.sdfs": ("features/sdfs", "__enable__"),
    "features.namespace": ("features/namespace", "__enable__"),
    "features.utime": ("features/utime", "__enable__"),
    "features.selinux": ("features/selinux", "__enable__"),
    "network.compression": ("protocol/client", "compression"),
    "network.compression-min-size": ("protocol/client",
                                     "compression-min-size"),
    # consumed by glusterd itself (glusterd-server-quorum.c): when the
    # mgmt cluster loses quorum, bricks of enforcing volumes are killed
    "cluster.server-quorum-type": ("mgmt/glusterd", "server-quorum-type"),
    "cluster.server-quorum-ratio": ("mgmt/glusterd",
                                    "server-quorum-ratio"),
    # consumed by glusterd's brick spawner: attach bricks into one
    # shared daemon process (glusterfsd-mgmt.c ATTACH, brick-mux)
    "cluster.brick-multiplex": ("mgmt/glusterd", "brick-multiplex"),
    # distribute variants (nufa.c / switch.c): swap the dht layer type
    "cluster.nufa": ("cluster/nufa", "__enable__"),
    "cluster.nufa-local-volume-name": ("cluster/nufa",
                                       "local-volume-name"),
    "cluster.switch-pattern": ("cluster/switch", "pattern-switch-case"),
    # ------------------------------------------------------------------
    # the operable long tail (glusterd-volume-set.c maps ~400 keys; the
    # framework half — typed options, live reconfigure, op-version
    # gating — existed before this block, which adds the tunables an
    # operator actually turns: cache geometry, thread counts, timeouts,
    # heal/lock behavior, debug injection).  Every key lands on a real
    # consumed Option of a real layer.
    # distribute
    "cluster.lookup-optimize": ("cluster/distribute", "lookup-optimize"),
    # disperse
    "disperse.eager-lock-max-hold": ("cluster/disperse",
                                     "eager-lock-max-hold"),
    # replicate (favorite-child-policy already mapped above)
    "cluster.data-self-heal-window-size": ("cluster/replicate",
                                           "self-heal-window-size"),
    # locks
    "features.locks-trace": ("features/locks", "trace"),
    "features.locks-lock-timeout": ("features/locks", "lock-timeout"),
    # quota tuning
    "features.default-soft-limit": ("features/quota",
                                    "default-soft-limit"),
    "features.hard-timeout": ("features/quota", "hard-timeout"),
    "features.quota-usage-scale": ("features/quota", "usage-scale"),
    "features.simple-quota-flush-interval": ("features/simple-quota",
                                             "flush-interval"),
    # trash (enable keys for read-only/worm/leases/upcall exist above)
    "features.trash-max-filesize": ("features/trash",
                                    "trash-max-filesize"),
    # snapview / uss
    "features.snapshot-directory-refresh": ("features/snapview",
                                            "refresh-interval"),
    # changelog
    "changelog.changelog-dir": ("features/changelog", "changelog-dir"),
    # io-stats diagnostics
    "diagnostics.count-fop-hits": ("debug/io-stats", "count-fop-hits"),
    "diagnostics.fd-hard-limit": ("debug/io-stats", "fd-hard-limit"),
    # debug fault injection (tests/operators drive these live); the
    # presence keys insert the layer, the -fops keys pick which fops
    # it bites ('enable' is a comma fop list in both layers)
    "debug.error-gen": ("debug/error-gen", "__enable__"),
    "debug.error-fops": ("debug/error-gen", "enable"),
    "debug.error-failure": ("debug/error-gen", "failure"),
    "debug.error-number": ("debug/error-gen", "error-no"),
    "debug.random-failure-seed": ("debug/error-gen", "seed"),
    "debug.delay-gen": ("debug/delay-gen", "__enable__"),
    "debug.delay-fops": ("debug/delay-gen", "enable"),
    "debug.delay-duration": ("debug/delay-gen", "delay-duration"),
    "debug.delay-percent": ("debug/delay-gen", "delay-percentage"),
    "debug.trace": ("debug/trace", "__enable__"),
    "debug.trace-log-history": ("debug/trace", "log-history"),
    "debug.trace-exclude-ops": ("debug/trace", "exclude-ops"),
    # io-threads queue geometry
    "performance.high-prio-threads": ("performance/io-threads",
                                      "high-prio-threads"),
    "performance.low-prio-threads": ("performance/io-threads",
                                     "low-prio-threads"),
    "performance.least-prio-threads": ("performance/io-threads",
                                       "least-prio-threads"),
    # client-side cache geometry
    "performance.cache-timeout": ("performance/io-cache",
                                  "cache-timeout"),
    "performance.io-cache-page-size": ("performance/io-cache",
                                       "page-size"),
    "performance.read-ahead-page-size": ("performance/read-ahead",
                                         "page-size"),
    "performance.md-cache-cache-xattrs": ("performance/md-cache",
                                          "cache-xattrs"),
    "performance.nl-cache-timeout": ("performance/nl-cache",
                                     "nl-cache-timeout"),
    "performance.nl-cache-limit": ("performance/nl-cache",
                                   "nl-cache-limit"),
    "performance.lazy-open": ("performance/open-behind", "lazy-open"),
    "performance.use-anonymous-fd": ("performance/open-behind",
                                     "use-anonymous-fd"),
    "performance.quick-read-max-file-size": ("performance/quick-read",
                                             "max-file-size"),
    "performance.quick-read-cache-size": ("performance/quick-read",
                                          "cache-size"),
    "performance.quick-read-cache-timeout": ("performance/quick-read",
                                             "cache-timeout"),
    "performance.rda-request-size": ("performance/readdir-ahead",
                                     "rda-request-size"),
    "performance.flush-behind": ("performance/write-behind",
                                 "flush-behind"),
    "performance.trickling-writes": ("performance/write-behind",
                                     "trickling-writes"),
    # network
    "network.ping-interval": ("protocol/client", "ping-interval"),
    # storage
    "storage.o-direct": ("storage/posix", "o-direct"),
    "storage.update-link-count-parent": ("storage/posix",
                                         "update-link-count-parent"),
}

# the option long tail above shipped at op-version 3: an older member
# would store these keys but build volfiles without their effect (the
# exact divergence the gate exists to prevent)
_V3_KEYS = (
    "cluster.lookup-optimize", "disperse.eager-lock",
    "disperse.other-eager-lock", "disperse.eager-lock-timeout",
    "disperse.eager-lock-max-hold", "cluster.rebal-throttle",
    "cluster.data-self-heal-window-size", "features.locks-trace",
    "features.locks-lock-timeout", "features.default-soft-limit",
    "features.hard-timeout", "features.quota-usage-scale",
    "features.simple-quota-flush-interval", "features.trash-max-filesize",
    "features.snapshot-directory-refresh", "changelog.changelog-dir",
    "diagnostics.count-fop-hits", "diagnostics.fd-hard-limit",
    "debug.error-gen", "debug.error-fops", "debug.error-failure",
    "debug.error-number", "debug.random-failure-seed",
    "debug.delay-gen", "debug.delay-fops", "debug.delay-duration",
    "debug.delay-percent", "debug.trace", "debug.trace-log-history",
    "debug.trace-exclude-ops", "performance.high-prio-threads",
    "performance.low-prio-threads", "performance.least-prio-threads",
    "performance.cache-timeout", "performance.io-cache-page-size",
    "performance.read-ahead-page-size",
    "performance.md-cache-cache-xattrs", "performance.nl-cache-timeout",
    "performance.nl-cache-limit", "performance.lazy-open",
    "performance.use-anonymous-fd",
    "performance.quick-read-max-file-size",
    "performance.quick-read-cache-size",
    "performance.quick-read-cache-timeout",
    "performance.rda-request-size", "performance.flush-behind",
    "performance.trickling-writes", "network.ping-interval",
    "storage.o-direct", "storage.update-link-count-parent",
)
OPTION_MIN_OPVERSION.update({k: 3 for k in _V3_KEYS})

# round-5 additions ship at op-version 4
_V4_KEYS = (
    "disperse.ec-read-mask", "disperse.parallel-writes",
    "server.outstanding-rpc-limit",
)
OPTION_MIN_OPVERSION.update({k: 4 for k in _V4_KEYS})

# default client-side performance stack, bottom -> top (volgen's
# perfxl_option_handlers order); each gated by its enable key
DEFAULT_PERF_STACK = [
    # reference defaults (glusterd-volume-set.c): write-behind,
    # read-ahead, io-cache, quick-read, open-behind and stat-prefetch
    # (md-cache) all default ON; readdir-ahead and nl-cache are opt-in
    ("performance/write-behind", "performance.write-behind", True),
    ("performance/read-ahead", "performance.read-ahead", True),
    ("performance/readdir-ahead", "performance.readdir-ahead", False),
    ("performance/io-cache", "performance.io-cache", True),
    ("performance/quick-read", "performance.quick-read", True),
    ("performance/open-behind", "performance.open-behind", True),
    ("performance/md-cache", "performance.md-cache", True),
    ("performance/nl-cache", "performance.nl-cache", False),
]


def _bool(v: Any) -> bool:
    return str(v).lower() in ("1", "on", "yes", "true", "enable", "enabled")


def _emit(name: str, type_name: str, options: dict[str, Any],
          subvols: list[str]) -> str:
    out = [f"volume {name}", f"    type {type_name}"]
    for k, v in options.items():
        out.append(f"    option {k} {v}")
    if subvols:
        out.append(f"    subvolumes {' '.join(subvols)}")
    out.append("end-volume\n")
    return "\n".join(out)


def layer_options(volinfo: dict, layer_type: str) -> dict[str, Any]:
    """Options set on the volume that target layer_type."""
    out = {}
    for key, val in volinfo.get("options", {}).items():
        m = OPTION_MAP.get(key)
        if m and m[0] == layer_type and m[1] != "__enable__":
            out[m[1]] = val
    return out


def _enabled(volinfo: dict, enable_key: str, default: bool) -> bool:
    val = volinfo.get("options", {}).get(enable_key)
    return default if val is None else _bool(val)


def build_brick_volfile(volinfo: dict, brick: dict) -> str:
    """posix -> locks -> io-stats (server_graph_table order, trimmed)."""
    name = brick["name"]
    popts = {"directory": brick["path"]}
    popts.update(layer_options(volinfo, "storage/posix"))
    out = [_emit(f"{name}-posix", "storage/posix", popts, [])]
    top = f"{name}-posix"
    # metadata-only witness brick: last of each replica group when the
    # volume was created with `arbiter 1` (arbiter.c sits above posix)
    if volinfo.get("arbiter"):
        g = volinfo.get("group-size") or len(volinfo["bricks"])
        if brick["index"] % g == g - 1:
            out.append(_emit(f"{name}-arbiter", "features/arbiter", {},
                             [top]))
            top = f"{name}-arbiter"
    # fop journal directly above posix (server_graph_table order);
    # geo-rep create enables it (default off: no consumer, no journal)
    if _enabled(volinfo, "changelog.changelog", False):
        out.append(_emit(f"{name}-changelog", "features/changelog",
                         layer_options(volinfo, "features/changelog"),
                         [top]))
        top = f"{name}-changelog"
    # corruption fencing (bitd's quarantine marker enforcement)
    if _enabled(volinfo, "features.bitrot", False):
        out.append(_emit(f"{name}-bitrot-stub", "features/bit-rot-stub",
                         {}, [top]))
        top = f"{name}-bitrot-stub"
    if _enabled(volinfo, "features.selinux", False):
        out.append(_emit(f"{name}-selinux", "features/selinux", {},
                         [top]))
        top = f"{name}-selinux"
    if _enabled(volinfo, "features.sdfs", False):
        out.append(_emit(f"{name}-sdfs", "features/sdfs", {}, [top]))
        top = f"{name}-sdfs"
    out.append(_emit(f"{name}-locks", "features/locks",
                     layer_options(volinfo, "features/locks"), [top]))
    top = f"{name}-locks"
    if _enabled(volinfo, "features.leases", False):
        out.append(_emit(f"{name}-leases", "features/leases",
                         layer_options(volinfo, "features/leases"),
                         [top]))
        top = f"{name}-leases"
    if _enabled(volinfo, "features.namespace", False):
        out.append(_emit(f"{name}-namespace", "features/namespace", {},
                         [top]))
        top = f"{name}-namespace"
    # pending-heal index on every brick (server_graph_table puts index
    # above locks; index-base defaults under the posix root)
    out.append(_emit(f"{name}-index", "features/index", {}, [top]))
    top = f"{name}-index"
    if _enabled(volinfo, "features.cache-invalidation", True):
        out.append(_emit(f"{name}-upcall", "features/upcall",
                         layer_options(volinfo, "features/upcall"), [top]))
        top = f"{name}-upcall"
    # worker threads so blocking disk syscalls never stall the brick's
    # event engine (server graph always carries io-threads)
    out.append(_emit(f"{name}-io-threads", "performance/io-threads",
                     layer_options(volinfo, "performance/io-threads"),
                     [top]))
    top = f"{name}-io-threads"
    # snapshot quiesce gate — ALWAYS present (arming rides live
    # reconfigure; a gated layer would force a brick respawn to arm)
    out.append(_emit(f"{name}-barrier", "features/barrier",
                     layer_options(volinfo, "features/barrier"), [top]))
    top = f"{name}-barrier"
    if _enabled(volinfo, "features.quota", False):
        import json as _json

        qopts = layer_options(volinfo, "features/quota")
        # #-escape '#': the volfile parser strips comments, and a
        # limited path containing '#' must not truncate the JSON
        qopts["limits"] = _json.dumps(
            volinfo.get("quota", {}).get("limits", {}),
            separators=(",", ":")).replace("#", "\\u0023")
        if volinfo["type"] == "disperse" and "usage-scale" not in qopts:
            # a disperse brick holds 1/K of every file: scale backend
            # bytes to logical so limits are volume-type independent.
            # An explicit features.quota-usage-scale wins (the operator
            # override must not be silently clobbered).
            g = volinfo.get("group-size") or len(volinfo["bricks"])
            qopts["usage-scale"] = g - volinfo.get("redundancy", 2)
        out.append(_emit(f"{name}-quota", "features/quota", qopts, [top]))
        top = f"{name}-quota"
    if _enabled(volinfo, "features.simple-quota", False):
        sqopts = layer_options(volinfo, "features/simple-quota")
        if volinfo["type"] == "disperse":
            g = volinfo.get("group-size") or len(volinfo["bricks"])
            sqopts["usage-scale"] = g - volinfo.get("redundancy", 2)
        out.append(_emit(f"{name}-simple-quota", "features/simple-quota",
                         sqopts, [top]))
        top = f"{name}-simple-quota"
    if _enabled(volinfo, "features.read-only", False):
        out.append(_emit(f"{name}-ro", "features/read-only", {}, [top]))
        top = f"{name}-ro"
    if _enabled(volinfo, "features.worm", False):
        out.append(_emit(f"{name}-worm", "features/worm", {}, [top]))
        top = f"{name}-worm"
    if _enabled(volinfo, "features.trash", False):
        out.append(_emit(f"{name}-trash", "features/trash",
                         layer_options(volinfo, "features/trash"), [top]))
        top = f"{name}-trash"
    # fault injection on demand (debug.error-gen / debug.delay-gen:
    # the reference volgen inserts these the same way for its .t tests
    # and operators debugging latency/fault behavior live)
    if _enabled(volinfo, "debug.error-gen", False):
        out.append(_emit(f"{name}-error-gen", "debug/error-gen",
                         layer_options(volinfo, "debug/error-gen"),
                         [top]))
        top = f"{name}-error-gen"
    if _enabled(volinfo, "debug.delay-gen", False):
        out.append(_emit(f"{name}-delay-gen", "debug/delay-gen",
                         layer_options(volinfo, "debug/delay-gen"),
                         [top]))
        top = f"{name}-delay-gen"
    if _enabled(volinfo, "debug.trace", False):
        out.append(_emit(f"{name}-trace", "debug/trace",
                         layer_options(volinfo, "debug/trace"), [top]))
        top = f"{name}-trace"
    out.append(_emit(name, "debug/io-stats",
                     layer_options(volinfo, "debug/io-stats"), [top]))
    top = name
    # protocol/server top carries transport auth: per-volume generated
    # credentials (trusted-volfile model) + admin auth.allow/reject +
    # TLS (server xlator at the top of every reference brick volfile)
    sopts = dict(layer_options(volinfo, "protocol/server"))
    sopts.update(_ssl_options(volinfo))
    auth = volinfo.get("auth") or {}
    if auth:
        sopts["auth-user"] = auth["username"]
        sopts["auth-password"] = auth["password"]
        if auth.get("mgmt-username"):
            sopts["auth-mgmt-user"] = auth["mgmt-username"]
            sopts["auth-mgmt-password"] = auth["mgmt-password"]
    out.append(_emit(f"{name}-server", "protocol/server", sopts, [top]))
    return "\n".join(out)


def _ssl_options(volinfo: dict) -> dict[str, Any]:
    """ssl.cert/key/ca volume keys -> layer ssl-* options (both ends)."""
    out = {}
    for key, val in volinfo.get("options", {}).items():
        m = OPTION_MAP.get(key)
        if m and m[0] == "__ssl__":
            out[m[1]] = val
    return out


def build_client_volfile(volinfo: dict,
                         ports: dict[str, int] | None = None,
                         mgmt: str | None = None) -> str:
    """protocol/client fan-in -> cluster layer(s) -> perf stack
    (build_client_graph analog).  mgmt (glusterd host:port) enables the
    snapview layer so the mount serves /.snaps — omitted for snapshot
    volfiles themselves (no .snaps inside a snapshot)."""
    vtype = volinfo["type"]
    bricks = volinfo["bricks"]
    ports = ports or {}
    out = []
    names = []
    for b in bricks:
        cname = f"{volinfo['name']}-client-{b['index']}"
        opts = {"remote-host": b["host"],
                "remote-port": ports.get(b["name"], b.get("port", 0)),
                "remote-subvolume": b["name"]}
        auth = volinfo.get("auth") or {}
        if auth:
            opts["username"] = auth["username"]
            opts["password"] = auth["password"]
        opts.update(layer_options(volinfo, "protocol/client"))
        opts.update(_ssl_options(volinfo))
        # a TLS brick implies TLS clients (admins set server.ssl once)
        if _enabled(volinfo, "server.ssl", False):
            opts["ssl"] = "on"
        out.append(_emit(cname, "protocol/client", opts, []))
        names.append(cname)

    def cluster_over(children: list[str], idx: int = 0) -> str:
        vname = volinfo["name"]
        if vtype == "disperse":
            lname = f"{vname}-disperse-{idx}"
            opts = {"redundancy": volinfo.get("redundancy", 2)}
            if volinfo.get("systematic"):
                # fragment format, chosen at volume-create (immutable
                # live — see cluster/disperse "systematic")
                opts["systematic"] = "on"
            opts.update(layer_options(volinfo, "cluster/disperse"))
            out.append(_emit(lname, "cluster/disperse", opts, children))
        elif vtype == "replicate":
            lname = f"{vname}-replicate-{idx}"
            opts = layer_options(volinfo, "cluster/replicate")
            if volinfo.get("arbiter"):
                opts["arbiter-count"] = volinfo["arbiter"]
            if volinfo.get("thin-arbiter"):
                # single group: the volume's LAST brick is the
                # tie-breaker child (thin-arbiter.rc layout)
                opts["thin-arbiter"] = "on"
            out.append(_emit(lname, "cluster/replicate", opts, children))
        else:
            raise ValueError(vtype)
        return lname

    def _dht_type(volinfo: dict) -> str:
        """Plain dht, or a variant (nufa.c / switch.c volgen swap)."""
        if _enabled(volinfo, "cluster.nufa", False):
            return "cluster/nufa"
        if volinfo.get("options", {}).get("cluster.switch-pattern"):
            return "cluster/switch"
        return "cluster/distribute"

    def _leaving() -> set:
        """Brick names being drained by remove-brick start (excluded
        from the dht layout until commit)."""
        rb = volinfo.get("remove-brick") or {}
        if rb.get("status") in ("started", "completed"):
            return set(rb.get("bricks") or ())
        return set()

    if vtype == "distribute":
        dtype = _dht_type(volinfo)
        opts = layer_options(volinfo, "cluster/distribute")
        opts.update(layer_options(volinfo, dtype))
        leaving = _leaving()
        if leaving:
            opts["decommissioned"] = ",".join(
                f"{volinfo['name']}-client-{b['index']}"
                for b in bricks if b["name"] in leaving)
        top = f"{volinfo['name']}-dht"
        out.append(_emit(top, dtype, opts, names))
    elif vtype in ("disperse", "replicate"):
        group = volinfo.get("group-size", len(names))
        if volinfo.get("thin-arbiter"):
            group = len(names)  # 2 data + tie-breaker, one group
        if len(names) > group:  # distributed-disperse / -replicate
            subs = [cluster_over(names[i:i + group], i // group)
                    for i in range(0, len(names), group)]
            top = f"{volinfo['name']}-dht"
            dtype = _dht_type(volinfo)  # nufa/switch apply here too
            dopts = layer_options(volinfo, "cluster/distribute")
            dopts.update(layer_options(volinfo, dtype))
            leaving = _leaving()
            if leaving:
                # remove-brick drains whole groups: a group layer is
                # decommissioned when every brick in it is leaving
                gone = []
                for j in range(0, len(bricks), group):
                    if all(b["name"] in leaving
                           for b in bricks[j:j + group]):
                        gone.append(subs[j // group])
                dopts["decommissioned"] = ",".join(gone)
            out.append(_emit(top, dtype, dopts, subs))
        else:
            top = cluster_over(names)
    else:
        raise ValueError(f"unknown volume type {vtype!r}")

    if _enabled(volinfo, "features.shard", False):
        out.append(_emit(f"{volinfo['name']}-shard", "features/shard",
                         layer_options(volinfo, "features/shard"), [top]))
        top = f"{volinfo['name']}-shard"

    vname = volinfo["name"]
    if _enabled(volinfo, "features.gfid-access", False):
        out.append(_emit(f"{vname}-gfid-access", "features/gfid-access",
                         {}, [top]))
        top = f"{vname}-gfid-access"
    if _enabled(volinfo, "features.utime", False):
        out.append(_emit(f"{vname}-utime", "features/utime", {}, [top]))
        top = f"{vname}-utime"
    if _enabled(volinfo, "features.acl", False):
        out.append(_emit(f"{vname}-acl", "system/posix-acl", {}, [top]))
        top = f"{vname}-acl"

    for ltype, key, default in DEFAULT_PERF_STACK:
        if _enabled(volinfo, key, default):
            lname = f"{volinfo['name']}-{ltype.split('/')[1]}"
            out.append(_emit(lname, ltype, layer_options(volinfo, ltype),
                             [top]))
            top = lname

    # pause gate ALWAYS present: arming rides live reconfigure
    # (features.quiesce), like the brick-side barrier
    out.append(_emit(f"{vname}-quiesce", "features/quiesce",
                     layer_options(volinfo, "features/quiesce"), [top]))
    top = f"{vname}-quiesce"
    out.append(_emit(f"{volinfo['name']}-io-stats", "debug/io-stats",
                     layer_options(volinfo, "debug/io-stats"), [top]))
    top = f"{volinfo['name']}-io-stats"
    if mgmt:
        # user-serviceable snapshots: /.snaps browse (snapview-client)
        out.append(_emit(f"{volinfo['name']}-snapview",
                         "features/snapview",
                         {**layer_options(volinfo, "features/snapview"),
                          "mgmt-server": mgmt,
                          "volume": volinfo["name"]}, [top]))
        top = f"{volinfo['name']}-snapview"
    # virtual /.meta introspection at the very top (the reference
    # autoloads meta on every fuse graph; tests read it like statedump)
    out.append(_emit(volinfo["name"], "meta", {}, [top]))
    return "\n".join(out)


def options_doc() -> str:
    """The docs/volume_options.md content, generated from OPTION_MAP.
    test_option_map_integrity pins the committed file to this output,
    so the operator-facing table cannot drift from the map."""
    lines = [
        "# `volume set` options",
        "",
        "Generated from `mgmt/volgen.py`'s OPTION_MAP (the",
        "glusterd-volume-set.c analog) by `volgen.options_doc()`; the",
        "committed file is pinned to that output by",
        "`tests/test_reconfigure.py::test_option_map_integrity`.  Every",
        "key lands on a declared, consumed option of a live layer;",
        "`(enable)` keys insert/remove the layer in the generated",
        "graphs.  Keys with an op-version need the whole cluster at",
        "that version (mixed-version skew guard).",
        "",
        "| key | target | option | op-ver |",
        "|---|---|---|---|",
    ]
    for key in sorted(OPTION_MAP):
        ltype, opt = OPTION_MAP[key]
        ver = OPTION_MIN_OPVERSION.get(key, 1)
        o = "(enable)" if opt == "__enable__" else opt
        lines.append(f"| {key} | {ltype} | {o} | {ver} |")
    lines.append("")
    lines.append(f"{len(OPTION_MAP)} keys total.")
    return "\n".join(lines) + "\n"
