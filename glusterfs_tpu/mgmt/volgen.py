"""Volfile generation — the glusterd-volgen analog.

Reference: xlators/mgmt/glusterd/src/glusterd-volgen.c (build_client_graph
:71, server_graph_table :2526, volgen_write_volfile :986) and the option
map glusterd-volume-set.c: ``gluster volume set`` keys map to layer
options, and volgen assembles the brick-side and client-side graphs from
volinfo.

Graph shapes produced (mirroring the reference's defaults):

brick volfile:   posix -> locks -> [io-stats] (served by the brick daemon)
client volfile:  protocol/client per brick -> cluster layer (disperse /
                 replicate / distribute / distributed-X) -> performance
                 layers (per options) -> [io-stats top]
"""

from __future__ import annotations

from typing import Any

# Options introduced after op-version 1 (glusterd-volume-set.c's
# .op_version fields): a mixed-version cluster may only set keys every
# member understands — the cluster op-version is the MINIMUM any
# member advertises (xlator.h:758 op_version model).
OPTION_MIN_OPVERSION = {
    "cluster.brick-multiplex": 2,
    "cluster.nufa": 2,
    "cluster.nufa-local-volume-name": 2,
    "cluster.switch-pattern": 2,
    "cluster.server-quorum-type": 2,
    "cluster.server-quorum-ratio": 2,
    "features.simple-quota": 2,
    "bitrot.scrub-throttle": 2,
    "storage.health-check-interval": 2,
    "disperse.stripe-cache": 2,
    "disperse.stripe-cache-window": 2,
    "disperse.stripe-cache-min-batch": 2,
}

# volume-set key -> (layer type, option name)  (glusterd-volume-set.c map)
OPTION_MAP = {
    "auth.allow": ("protocol/server", "auth-allow"),
    "auth.ssl-allow": ("protocol/server", "ssl-allow"),
    # compound fop chains (rpc/compound.py): one key arms all four
    # ends — protocol/client (wire fusion), performance/write-behind
    # (window flush chains), performance/read-ahead (demand+prefetch
    # read chains) and protocol/server (serve + advertise)
    "cluster.use-compound-fops": ("__compound__", "compound-fops"),
    # zero-copy read pipeline (ISSUE 3): scatter-gather reply frames on
    # both transport ends — client requests at SETVOLUME, server
    # honors per-connection
    "network.zero-copy-reads": ("__sg__", "sg-replies"),
    # same-host shared-memory bulk lane (rpc/shm, ISSUE 18): one key
    # arms both transport ends — the client asks at SETVOLUME and the
    # brick advertises + serves the memfd arena exchange
    "network.shm-transport": ("__shm__", "shm-transport"),
    "network.shm-arena-size": ("protocol/server", "shm-arena-size"),
    # end-to-end trace propagation (core/tracing.py): one key arms both
    # transport ends — the client ships the trailing trace-id frame
    # field, the server advertises + re-arms it for the brick graph
    "diagnostics.trace-propagation": ("__trace__", "trace-fops"),
    "diagnostics.slow-fop-threshold": ("debug/io-stats",
                                       "slow-fop-threshold"),
    "diagnostics.span-ring-size": ("debug/io-stats", "span-ring-size"),
    # incident plane (op-version 18): io-stats pushes the keys
    # process-wide (core/flight.py) on both graph ends, so bricks AND
    # clients/gateway-workers auto-capture into the same directory
    "diagnostics.incident-dir": ("debug/io-stats", "incident-dir"),
    "diagnostics.incident-max-bytes": ("debug/io-stats",
                                       "incident-max-bytes"),
    "diagnostics.incident-min-interval": ("debug/io-stats",
                                          "incident-min-interval"),
    "diagnostics.flight-ring-size": ("debug/io-stats",
                                     "flight-ring-size"),
    "diagnostics.access-log": ("debug/io-stats", "access-log"),
    # history + SLO plane (op-version 19): io-stats pushes these
    # process-wide too — every process that mounts the volume samples
    # its registry into the history ring and evaluates the same rules
    "diagnostics.history-interval": ("debug/io-stats",
                                     "history-interval"),
    "diagnostics.history-retention": ("debug/io-stats",
                                      "history-retention"),
    "diagnostics.slo-rules": ("debug/io-stats", "slo-rules"),
    "client.strict-locks": ("protocol/client", "strict-locks"),
    # failure containment (ISSUE 9): per-brick circuit breaking, the
    # idempotent-retry knobs, the call-timeout transport bail, and
    # deadline-budget propagation (client ships, brick arms — the
    # server half is capability-advertised, not option-gated)
    "client.circuit-breaker": ("protocol/client", "circuit-breaker"),
    "client.circuit-failure-threshold": ("protocol/client",
                                         "circuit-failure-threshold"),
    "client.circuit-reset-interval": ("protocol/client",
                                      "circuit-reset-interval"),
    "client.failfast": ("protocol/client", "failfast"),
    "client.idempotent-retries": ("protocol/client",
                                  "idempotent-retries"),
    "client.retry-backoff-max": ("protocol/client", "retry-backoff-max"),
    "network.deadline-propagation": ("protocol/client",
                                     "deadline-propagation"),
    # concurrent event plane (ISSUE 7; the multithreaded-epoll knobs,
    # event-epoll.c): frame-turning worker pools on both transport
    # ends, live-reconfigurable
    "server.event-threads": ("protocol/server", "event-threads"),
    "client.event-threads": ("protocol/client", "event-threads"),
    "performance.read-ahead-adaptive": ("performance/read-ahead",
                                        "adaptive-window"),
    "server.outstanding-rpc-limit": ("protocol/server",
                                     "outstanding-rpc-limit"),
    # multi-tenant QoS plane (features/qos, op-version 16): per-client
    # token buckets + priority lanes enforced at the brick's frame
    # admission; the same rates reach the gateway door via glusterd's
    # spawner.  client.qos-backoff is the mount-side half: re-send a
    # shed frame after the advertised retry-after
    "server.qos": ("protocol/server", "qos"),
    "server.qos-fops-per-sec": ("protocol/server", "qos-fops-per-sec"),
    "server.qos-bytes-per-sec": ("protocol/server",
                                 "qos-bytes-per-sec"),
    "server.qos-burst": ("protocol/server", "qos-burst"),
    "server.qos-shaped-window": ("protocol/server",
                                 "qos-shaped-window"),
    "server.qos-soft-quota-delay": ("protocol/server",
                                    "qos-soft-quota-delay"),
    "client.qos-backoff": ("protocol/client", "qos-backoff"),
    "auth.reject": ("protocol/server", "auth-reject"),
    "server.ssl": ("protocol/server", "ssl"),
    "client.ssl": ("protocol/client", "ssl"),
    # cert/key/ca paths feed both transport ends (socket.c ssl_setup)
    "ssl.cert": ("__ssl__", "ssl-cert"),
    "ssl.key": ("__ssl__", "ssl-key"),
    "ssl.ca": ("__ssl__", "ssl-ca"),
    "disperse.cpu-extensions": ("cluster/disperse", "cpu-extensions"),
    # stripe-cache (ec.c:286): the TPU batching window over the codec
    "disperse.stripe-cache": ("cluster/disperse", "stripe-cache"),
    "disperse.stripe-cache-window": ("cluster/disperse",
                                     "stripe-cache-window"),
    "disperse.stripe-cache-min-batch": ("cluster/disperse",
                                        "stripe-cache-min-batch"),
    # mesh-sharded codec data plane (ISSUE 8): coalesced stripe
    # batches ride the (dp, frag) device mesh when >1 device is up
    "cluster.mesh-codec": ("cluster/disperse", "mesh-codec"),
    # parity-delta sub-stripe writes (ISSUE 10): healthy systematic
    # volumes update small writes as touched-data writev + parity xorv
    "cluster.delta-writes": ("cluster/disperse", "delta-writes"),
    "disperse.read-policy": ("cluster/disperse", "read-policy"),
    "disperse.quorum-count": ("cluster/disperse", "quorum-count"),
    "disperse.eager-lock": ("cluster/disperse", "eager-lock"),
    "disperse.other-eager-lock": ("cluster/disperse",
                                  "other-eager-lock"),
    "disperse.eager-lock-timeout": ("cluster/disperse",
                                    "eager-lock-timeout"),
    "disperse.self-heal-window-size": ("cluster/disperse",
                                       "self-heal-window-size"),
    "disperse.ec-read-mask": ("cluster/disperse", "ec-read-mask"),
    "disperse.parallel-writes": ("cluster/disperse", "parallel-writes"),
    "cluster.quorum-count": ("cluster/replicate", "quorum-count"),
    # consumed by glusterd's shd spawner, not a graph layer
    "cluster.heal-timeout": ("mgmt/shd", "interval"),
    "cluster.read-hash-mode": ("cluster/replicate", "read-hash-mode"),
    "cluster.favorite-child-policy": ("cluster/replicate",
                                      "favorite-child-policy"),
    "cluster.lookup-unhashed": ("cluster/distribute", "lookup-unhashed"),
    "cluster.min-free-disk": ("cluster/distribute", "min-free-disk"),
    "cluster.rebal-throttle": ("cluster/distribute", "rebal-throttle"),
    "cluster.rebal-migrate-window": ("cluster/distribute",
                                     "rebal-migrate-window"),
    # consumed by the glusterd-spawned rebalance daemon, not a graph
    # layer (mgmt/rebalanced.py reads it out of the volinfo like the
    # gateway daemon reads gateway.*)
    "rebalance.checkpoint-interval": ("mgmt/rebalanced",
                                      "checkpoint-interval"),
    # multi-process data plane (ISSUE 12): the gateway worker-pool
    # width (consumed by glusterd's gateway spawner) and the
    # jax.distributed brick mesh (consumed by the brick spawner: each
    # brick daemon joins the coordinator as one mesh process)
    "gateway.workers": ("mgmt/gateway", "workers"),
    "cluster.mesh-distributed": ("mgmt/glusterd", "mesh-distributed"),
    "network.ping-timeout": ("protocol/client", "ping-timeout"),
    "storage.health-check-interval": ("storage/posix",
                                      "health-check-interval"),
    "performance.write-behind": ("performance/write-behind", "__enable__"),
    "performance.write-behind-window-size": ("performance/write-behind",
                                             "window-size"),
    "performance.io-cache": ("performance/io-cache", "__enable__"),
    "performance.cache-size": ("performance/io-cache", "cache-size"),
    "performance.read-ahead": ("performance/read-ahead", "__enable__"),
    "performance.read-ahead-page-count": ("performance/read-ahead",
                                          "page-count"),
    "performance.md-cache": ("performance/md-cache", "__enable__"),
    "performance.md-cache-timeout": ("performance/md-cache", "timeout"),
    "performance.quick-read": ("performance/quick-read", "__enable__"),
    "performance.open-behind": ("performance/open-behind", "__enable__"),
    "performance.nl-cache": ("performance/nl-cache", "__enable__"),
    "performance.readdir-ahead": ("performance/readdir-ahead", "__enable__"),
    "performance.io-thread-count": ("performance/io-threads",
                                    "thread-count"),
    "diagnostics.latency-measurement": ("debug/io-stats",
                                        "latency-measurement"),
    "changelog.changelog": ("features/changelog", "__enable__"),
    # consumed by glusterd's gsyncd spawner, not a graph layer
    "georep.sync-interval": ("mgmt/gsyncd", "interval"),
    "changelog.rollover-time": ("features/changelog", "rollover-time"),
    "features.barrier": ("features/barrier", "barrier"),
    "features.barrier-timeout": ("features/barrier", "barrier-timeout"),
    "features.bitrot": ("features/bit-rot-stub", "__enable__"),
    # consumed by glusterd's bitd spawner, not a graph layer
    "bitrot.scrub-interval": ("mgmt/bitd", "scrub-interval"),
    "bitrot.signer-quiesce": ("mgmt/bitd", "quiesce"),
    "bitrot.scrub-throttle": ("mgmt/bitd", "scrub-throttle"),
    "features.cache-invalidation": ("features/upcall", "__enable__"),
    "features.cache-invalidation-timeout": ("features/upcall",
                                            "cache-invalidation-timeout"),
    "features.read-only": ("features/read-only", "__enable__"),
    "features.worm": ("features/worm", "__enable__"),
    "features.quota": ("features/quota", "__enable__"),
    "features.simple-quota": ("features/simple-quota", "__enable__"),
    "features.trash": ("features/trash", "__enable__"),
    "features.shard": ("features/shard", "__enable__"),
    "features.shard-block-size": ("features/shard", "shard-block-size"),
    "features.leases": ("features/leases", "__enable__"),
    "features.lease-recall-timeout": ("features/leases",
                                      "recall-timeout"),
    "features.quiesce": ("features/quiesce", "quiesce"),
    "features.gfid-access": ("features/gfid-access", "__enable__"),
    "features.acl": ("system/posix-acl", "__enable__"),
    "features.sdfs": ("features/sdfs", "__enable__"),
    "features.namespace": ("features/namespace", "__enable__"),
    "features.utime": ("features/utime", "__enable__"),
    "features.selinux": ("features/selinux", "__enable__"),
    "network.compression": ("protocol/client", "compression"),
    "network.compression-min-size": ("protocol/client",
                                     "compression-min-size"),
    # consumed by glusterd itself (glusterd-server-quorum.c): when the
    # mgmt cluster loses quorum, bricks of enforcing volumes are killed
    "cluster.server-quorum-type": ("mgmt/glusterd", "server-quorum-type"),
    "cluster.server-quorum-ratio": ("mgmt/glusterd",
                                    "server-quorum-ratio"),
    # consumed by glusterd's brick spawner: attach bricks into one
    # shared daemon process (glusterfsd-mgmt.c ATTACH, brick-mux)
    "cluster.brick-multiplex": ("mgmt/glusterd", "brick-multiplex"),
    # distribute variants (nufa.c / switch.c): swap the dht layer type
    "cluster.nufa": ("cluster/nufa", "__enable__"),
    "cluster.nufa-local-volume-name": ("cluster/nufa",
                                       "local-volume-name"),
    "cluster.switch-pattern": ("cluster/switch", "pattern-switch-case"),
    # ------------------------------------------------------------------
    # the operable long tail (glusterd-volume-set.c maps ~400 keys; the
    # framework half — typed options, live reconfigure, op-version
    # gating — existed before this block, which adds the tunables an
    # operator actually turns: cache geometry, thread counts, timeouts,
    # heal/lock behavior, debug injection).  Every key lands on a real
    # consumed Option of a real layer.
    # distribute
    "cluster.lookup-optimize": ("cluster/distribute", "lookup-optimize"),
    # disperse
    "disperse.eager-lock-max-hold": ("cluster/disperse",
                                     "eager-lock-max-hold"),
    # replicate (favorite-child-policy already mapped above)
    "cluster.data-self-heal-window-size": ("cluster/replicate",
                                           "self-heal-window-size"),
    # locks
    "features.locks-trace": ("features/locks", "trace"),
    "features.locks-lock-timeout": ("features/locks", "lock-timeout"),
    "features.locks-notify-contention": ("features/locks",
                                         "notify-contention"),
    "features.locks-notify-contention-delay": ("features/locks",
                                               "notify-contention-delay"),
    # lock revocation (failure containment, op-version 11)
    "features.locks-revocation-secs": ("features/locks",
                                       "revocation-secs"),
    "features.locks-revocation-clear-all": ("features/locks",
                                            "revocation-clear-all"),
    "features.locks-revocation-max-blocked": ("features/locks",
                                              "revocation-max-blocked"),
    # quota tuning
    "features.default-soft-limit": ("features/quota",
                                    "default-soft-limit"),
    "features.hard-timeout": ("features/quota", "hard-timeout"),
    "features.quota-usage-scale": ("features/quota", "usage-scale"),
    "features.simple-quota-flush-interval": ("features/simple-quota",
                                             "flush-interval"),
    # trash (enable keys for read-only/worm/leases/upcall exist above)
    "features.trash-max-filesize": ("features/trash",
                                    "trash-max-filesize"),
    # snapview / uss
    "features.snapshot-directory-refresh": ("features/snapview",
                                            "refresh-interval"),
    # changelog
    "changelog.changelog-dir": ("features/changelog", "changelog-dir"),
    # io-stats diagnostics
    "diagnostics.count-fop-hits": ("debug/io-stats", "count-fop-hits"),
    "diagnostics.fd-hard-limit": ("debug/io-stats", "fd-hard-limit"),
    # debug fault injection (tests/operators drive these live); the
    # presence keys insert the layer, the -fops keys pick which fops
    # it bites ('enable' is a comma fop list in both layers)
    "debug.error-gen": ("debug/error-gen", "__enable__"),
    "debug.error-fops": ("debug/error-gen", "enable"),
    "debug.error-failure": ("debug/error-gen", "failure"),
    "debug.error-failure-count": ("debug/error-gen", "failure-count"),
    "debug.error-number": ("debug/error-gen", "error-no"),
    "debug.random-failure-seed": ("debug/error-gen", "seed"),
    "debug.delay-gen": ("debug/delay-gen", "__enable__"),
    "debug.delay-fops": ("debug/delay-gen", "enable"),
    "debug.delay-duration": ("debug/delay-gen", "delay-duration"),
    "debug.delay-percent": ("debug/delay-gen", "delay-percentage"),
    "debug.trace": ("debug/trace", "__enable__"),
    "debug.trace-log-history": ("debug/trace", "log-history"),
    "debug.trace-exclude-ops": ("debug/trace", "exclude-ops"),
    # io-threads queue geometry
    "performance.high-prio-threads": ("performance/io-threads",
                                      "high-prio-threads"),
    "performance.low-prio-threads": ("performance/io-threads",
                                     "low-prio-threads"),
    "performance.least-prio-threads": ("performance/io-threads",
                                       "least-prio-threads"),
    # client-side cache geometry
    "performance.cache-timeout": ("performance/io-cache",
                                  "cache-timeout"),
    "performance.io-cache-page-size": ("performance/io-cache",
                                       "page-size"),
    "performance.read-ahead-page-size": ("performance/read-ahead",
                                         "page-size"),
    "performance.md-cache-cache-xattrs": ("performance/md-cache",
                                          "cache-xattrs"),
    "performance.nl-cache-timeout": ("performance/nl-cache",
                                     "nl-cache-timeout"),
    "performance.nl-cache-limit": ("performance/nl-cache",
                                   "nl-cache-limit"),
    "performance.lazy-open": ("performance/open-behind", "lazy-open"),
    "performance.use-anonymous-fd": ("performance/open-behind",
                                     "use-anonymous-fd"),
    "performance.quick-read-max-file-size": ("performance/quick-read",
                                             "max-file-size"),
    "performance.quick-read-cache-size": ("performance/quick-read",
                                          "cache-size"),
    "performance.quick-read-cache-timeout": ("performance/quick-read",
                                             "cache-timeout"),
    "performance.rda-request-size": ("performance/readdir-ahead",
                                     "rda-request-size"),
    "performance.flush-behind": ("performance/write-behind",
                                 "flush-behind"),
    "performance.trickling-writes": ("performance/write-behind",
                                     "trickling-writes"),
    # network
    "network.ping-interval": ("protocol/client", "ping-interval"),
    # storage
    "storage.o-direct": ("storage/posix", "o-direct"),
    "storage.update-link-count-parent": ("storage/posix",
                                         "update-link-count-parent"),
    # ------------------------------------------------------------------
    # round-5 long tail (op-version 4): the next ~100 operable keys —
    # transport/socket knobs, posix policy, AFR/EC heal behavior, shd
    # sizing, the perf-layer pass-throughs and cache families, dht
    # placement tuning, retention/trash/changelog/diagnostics.  The
    # deliberately-skipped remainder is enumerated with reasons at the
    # bottom of docs/volume_options.md (options_doc emits it).
    # transport / socket (socket.c option surface via rpc/socktune.py)
    "client.tcp-user-timeout": ("protocol/client", "tcp-user-timeout"),
    "client.keepalive-time": ("protocol/client", "keepalive-time"),
    "client.keepalive-interval": ("protocol/client",
                                  "keepalive-interval"),
    "client.keepalive-count": ("protocol/client", "keepalive-count"),
    "network.frame-timeout": ("protocol/client", "call-timeout"),
    "network.tcp-window-size": ("__transport__", "tcp-window-size"),
    "server.tcp-user-timeout": ("protocol/server", "tcp-user-timeout"),
    "server.keepalive-time": ("protocol/server", "keepalive-time"),
    "server.keepalive-interval": ("protocol/server",
                                  "keepalive-interval"),
    "server.keepalive-count": ("protocol/server", "keepalive-count"),
    "transport.listen-backlog": ("protocol/server", "listen-backlog"),
    "transport.address-family": ("protocol/server", "address-family"),
    "server.allow-insecure": ("protocol/server", "allow-insecure"),
    "network.compression.compression-level": ("protocol/client",
                                              "compression-level"),
    "network.compression.min-size": ("protocol/client",
                                     "compression-min-size"),
    # storage/posix policy
    "storage.create-mask": ("storage/posix", "create-mask"),
    "storage.create-directory-mask": ("storage/posix",
                                      "create-directory-mask"),
    "storage.force-create-mode": ("storage/posix", "force-create-mode"),
    "storage.force-directory-mode": ("storage/posix",
                                     "force-directory-mode"),
    "storage.max-hardlinks": ("storage/posix", "max-hardlinks"),
    "storage.reserve": ("storage/posix", "reserve"),
    "storage.owner-uid": ("storage/posix", "owner-uid"),
    "storage.owner-gid": ("storage/posix", "owner-gid"),
    "storage.health-check-timeout": ("storage/posix",
                                     "health-check-timeout"),
    "storage.fips-mode-rchecksum": ("storage/posix",
                                    "fips-mode-rchecksum"),
    # AFR behavior
    "cluster.quorum-type": ("cluster/replicate", "quorum-type"),
    "cluster.quorum-reads": ("cluster/replicate", "quorum-reads"),
    "cluster.data-self-heal": ("cluster/replicate", "data-self-heal"),
    "cluster.metadata-self-heal": ("cluster/replicate",
                                   "metadata-self-heal"),
    "cluster.entry-self-heal": ("cluster/replicate", "entry-self-heal"),
    "cluster.data-self-heal-algorithm": ("cluster/replicate",
                                         "data-self-heal-algorithm"),
    "cluster.ensure-durability": ("cluster/replicate",
                                  "ensure-durability"),
    "cluster.choose-local": ("cluster/replicate", "choose-local"),
    "cluster.read-subvolume": ("cluster/replicate", "read-subvolume"),
    "cluster.read-subvolume-index": ("cluster/replicate",
                                     "read-subvolume-index"),
    # self-heal daemon sizing (consumed by glusterd's shd spawner +
    # mgmt/shd crawl concurrency)
    "cluster.self-heal-daemon": ("mgmt/shd", "enabled"),
    "cluster.disperse-self-heal-daemon": ("mgmt/shd", "enabled"),
    "cluster.shd-max-threads": ("mgmt/shd", "max-heals"),
    "cluster.shd-wait-qlength": ("mgmt/shd", "wait-qlength"),
    "cluster.background-self-heal-count": ("mgmt/shd", "max-heals"),
    "cluster.heal-wait-queue-length": ("mgmt/shd", "wait-qlength"),
    "disperse.shd-max-threads": ("mgmt/shd", "max-heals"),
    "disperse.shd-wait-qlength": ("mgmt/shd", "wait-qlength"),
    "disperse.background-heals": ("mgmt/shd", "max-heals"),
    "disperse.heal-wait-qlength": ("mgmt/shd", "wait-qlength"),
    # EC
    "disperse.other-eager-lock-timeout": ("cluster/disperse",
                                          "other-eager-lock-timeout"),
    # dht placement
    "cluster.min-free-inodes": ("cluster/distribute", "min-free-inodes"),
    "cluster.readdir-optimize": ("cluster/distribute",
                                 "readdir-optimize"),
    "cluster.rsync-hash-regex": ("cluster/distribute",
                                 "rsync-hash-regex"),
    "cluster.extra-hash-regex": ("cluster/distribute",
                                 "extra-hash-regex"),
    "cluster.subvols-per-directory": ("cluster/distribute",
                                      "subvols-per-directory"),
    "cluster.weighted-rebalance": ("cluster/distribute",
                                   "weighted-rebalance"),
    "cluster.rebalance-stats": ("cluster/distribute", "rebalance-stats"),
    # io-threads
    "performance.normal-prio-threads": ("performance/io-threads",
                                        "normal-prio-threads"),
    "performance.enable-least-priority": ("performance/io-threads",
                                          "enable-least-priority"),
    "performance.client-io-threads": ("performance/io-threads",
                                      "__enable__"),
    # pass-throughs: structural (volgen omits the layer; hot graph swap
    # applies it live) — xlator pass_through analog
    "performance.write-behind-pass-through": ("performance/write-behind",
                                              "__passthrough__"),
    "performance.read-ahead-pass-through": ("performance/read-ahead",
                                            "__passthrough__"),
    "performance.readdir-ahead-pass-through": (
        "performance/readdir-ahead", "__passthrough__"),
    "performance.io-cache-pass-through": ("performance/io-cache",
                                          "__passthrough__"),
    "performance.open-behind-pass-through": ("performance/open-behind",
                                             "__passthrough__"),
    "performance.md-cache-pass-through": ("performance/md-cache",
                                          "__passthrough__"),
    "performance.nl-cache-pass-through": ("performance/nl-cache",
                                          "__passthrough__"),
    "performance.iot-pass-through": ("performance/io-threads",
                                     "__passthrough__"),
    # io-cache
    "performance.cache-max-file-size": ("performance/io-cache",
                                        "max-file-size"),
    "performance.cache-min-file-size": ("performance/io-cache",
                                        "min-file-size"),
    "performance.cache-priority": ("performance/io-cache", "priority"),
    "performance.cache-refresh-timeout": ("performance/io-cache",
                                          "cache-timeout"),
    "performance.io-cache-size": ("performance/io-cache", "cache-size"),
    # write-behind
    "performance.aggregate-size": ("performance/write-behind",
                                   "aggregate-size"),
    "performance.strict-o-direct": ("performance/write-behind",
                                    "strict-o-direct"),
    "performance.strict-write-ordering": ("performance/write-behind",
                                          "strict-write-ordering"),
    "performance.write-behind-trickling-writes": (
        "performance/write-behind", "trickling-writes"),
    # md-cache
    "performance.stat-prefetch": ("performance/md-cache", "__enable__"),
    "performance.cache-swift-metadata": ("performance/md-cache",
                                         "cache-swift-metadata"),
    "performance.cache-samba-metadata": ("performance/md-cache",
                                         "cache-samba-metadata"),
    "performance.cache-capability-xattrs": ("performance/md-cache",
                                            "cache-capability-xattrs"),
    "performance.cache-ima-xattrs": ("performance/md-cache",
                                     "cache-ima-xattrs"),
    "performance.xattr-cache-list": ("performance/md-cache",
                                     "xattr-cache-list"),
    "performance.md-cache-statfs": ("performance/md-cache",
                                    "md-cache-statfs"),
    "performance.cache-invalidation": ("performance/md-cache",
                                       "cache-invalidation"),
    # quick-read / open-behind / rda / nl-cache
    "performance.qr-cache-timeout": ("performance/quick-read",
                                     "cache-timeout"),
    "performance.quick-read-cache-invalidation": (
        "performance/quick-read", "cache-invalidation"),
    "performance.read-after-open": ("performance/open-behind",
                                    "read-after-open"),
    "performance.rda-cache-limit": ("performance/readdir-ahead",
                                    "rda-cache-limit"),
    "performance.nl-cache-positive-entry": ("performance/nl-cache",
                                            "positive-entry"),
    # worm retention
    "features.worm-file-level": ("features/worm", "worm-file-level"),
    "features.worm-files-deletable": ("features/worm",
                                      "worm-files-deletable"),
    "features.default-retention-period": ("features/worm",
                                          "default-retention-period"),
    "features.auto-commit-period": ("features/worm",
                                    "auto-commit-period"),
    "features.retention-mode": ("features/worm", "retention-mode"),
    # trash
    "features.trash-dir": ("features/trash", "trash-dir"),
    "features.trash-eliminate-path": ("features/trash",
                                      "eliminate-path"),
    "features.trash-internal-op": ("features/trash", "internal-op"),
    # changelog
    "changelog.fsync-interval": ("features/changelog", "fsync-interval"),
    "changelog.capture-del-path": ("features/changelog",
                                   "capture-del-path"),
    "changelog.encoding": ("features/changelog", "encoding"),
    # quota
    "features.soft-timeout": ("features/quota", "soft-timeout"),
    "features.alert-time": ("features/quota", "alert-time"),
    "features.quota-deem-statfs": ("features/quota", "deem-statfs"),
    # shard
    "features.shard-lru-limit": ("features/shard", "shard-lru-limit"),
    "features.shard-deletion-rate": ("features/shard",
                                     "shard-deletion-rate"),
    # USS / snapview
    "features.uss": ("features/snapview", "__enable__"),
    "features.snapshot-directory": ("features/snapview",
                                    "snapshot-directory"),
    "features.show-snapshot-directory": ("features/snapview",
                                         "show-snapshot-directory"),
    # ctime / utime
    "features.ctime": ("features/utime", "ctime"),
    "ctime.noatime": ("features/utime", "noatime"),
    # locks
    "features.locks-monkey-unlocking": ("features/locks",
                                        "monkey-unlocking"),
    "locks.trace": ("features/locks", "trace"),
    "locks.mandatory-locking": ("features/locks", "mandatory-locking"),
    # diagnostics
    "diagnostics.brick-log-level": ("debug/io-stats", "log-level"),
    "diagnostics.client-log-level": ("debug/io-stats", "log-level"),
    "diagnostics.dump-fd-stats": ("debug/io-stats", "dump-fd-stats"),
    "diagnostics.stats-dump-interval": ("debug/io-stats",
                                        "ios-dump-interval"),
    "diagnostics.fop-sample-interval": ("debug/io-stats",
                                        "fop-sample-interval"),
    "diagnostics.fop-sample-buf-size": ("debug/io-stats",
                                        "fop-sample-buf-size"),
    "diagnostics.latency-measurement": ("debug/io-stats",
                                        "latency-measurement"),
    # bitrot (consumed by the bitd daemon spawner)
    "features.scrub": ("mgmt/bitd", "scrub"),
    "features.scrub-freq": ("mgmt/bitd", "scrub-freq"),
    "features.expiry-time": ("mgmt/bitd", "expiry-time"),
    "features.scrub-throttle": ("mgmt/bitd", "throttle"),
    # misc aliases the reference also carries
    "cluster.local-volume-name": ("cluster/nufa", "local-volume-name"),
    "config.transport": ("mgmt/glusterd", "transport"),
    # ------------------------------------------------------------------
    # S3-flavored HTTP object gateway (gateway/, ISSUE 6): keys
    # consumed by glusterd's gateway spawner (a per-volume service
    # daemon like bitd/quotad), not a graph layer.  Lifecycle is
    # `gftpu volume gateway NAME start|stop|status`.
    "gateway.port": ("mgmt/gateway", "port"),
    "gateway.listen-host": ("mgmt/gateway", "listen-host"),
    "gateway.pool-size": ("mgmt/gateway", "pool-size"),
    "gateway.max-clients": ("mgmt/gateway", "max-clients"),
    "gateway.metrics-port": ("mgmt/gateway", "metrics-port"),
    # lease-driven hot-object serving (ISSUE 16): the gateway's
    # lease-held object cache budget (spawner arg, per worker) and the
    # brick-side idle-lease expiry
    "gateway.object-cache-size": ("mgmt/gateway", "object-cache-size"),
    "features.lease-timeout": ("features/leases", "lease-timeout"),
}

# the option long tail above shipped at op-version 3: an older member
# would store these keys but build volfiles without their effect (the
# exact divergence the gate exists to prevent)
_V3_KEYS = (
    "cluster.lookup-optimize", "disperse.eager-lock",
    "disperse.other-eager-lock", "disperse.eager-lock-timeout",
    "disperse.eager-lock-max-hold", "cluster.rebal-throttle",
    "cluster.data-self-heal-window-size", "features.locks-trace",
    "features.locks-lock-timeout", "features.default-soft-limit",
    "features.hard-timeout", "features.quota-usage-scale",
    "features.simple-quota-flush-interval", "features.trash-max-filesize",
    "features.snapshot-directory-refresh", "changelog.changelog-dir",
    "diagnostics.count-fop-hits", "diagnostics.fd-hard-limit",
    "debug.error-gen", "debug.error-fops", "debug.error-failure",
    "debug.error-number", "debug.random-failure-seed",
    "debug.delay-gen", "debug.delay-fops", "debug.delay-duration",
    "debug.delay-percent", "debug.trace", "debug.trace-log-history",
    "debug.trace-exclude-ops", "performance.high-prio-threads",
    "performance.low-prio-threads", "performance.least-prio-threads",
    "performance.cache-timeout", "performance.io-cache-page-size",
    "performance.read-ahead-page-size",
    "performance.md-cache-cache-xattrs", "performance.nl-cache-timeout",
    "performance.nl-cache-limit", "performance.lazy-open",
    "performance.use-anonymous-fd",
    "performance.quick-read-max-file-size",
    "performance.quick-read-cache-size",
    "performance.quick-read-cache-timeout",
    "performance.rda-request-size", "performance.flush-behind",
    "performance.trickling-writes", "network.ping-interval",
    "storage.o-direct", "storage.update-link-count-parent",
)
OPTION_MIN_OPVERSION.update({k: 3 for k in _V3_KEYS})

# round-5 additions ship at op-version 4 (every key in the round-5
# block above plus the EC/server/locks keys that opened the round)
_V4_KEYS = (
    "disperse.ec-read-mask", "disperse.parallel-writes",
    "server.outstanding-rpc-limit", "features.locks-notify-contention",
    "features.locks-notify-contention-delay",
    "client.tcp-user-timeout", "client.keepalive-time",
    "client.keepalive-interval", "client.keepalive-count",
    "network.frame-timeout", "network.tcp-window-size",
    "server.tcp-user-timeout", "server.keepalive-time",
    "server.keepalive-interval", "server.keepalive-count",
    "transport.listen-backlog", "transport.address-family",
    "server.allow-insecure", "network.compression.compression-level",
    "network.compression.min-size",
    "storage.create-mask", "storage.create-directory-mask",
    "storage.force-create-mode", "storage.force-directory-mode",
    "storage.max-hardlinks", "storage.reserve", "storage.owner-uid",
    "storage.owner-gid", "storage.health-check-timeout",
    "storage.fips-mode-rchecksum",
    "cluster.quorum-type", "cluster.quorum-reads",
    "cluster.data-self-heal", "cluster.metadata-self-heal",
    "cluster.entry-self-heal", "cluster.data-self-heal-algorithm",
    "cluster.ensure-durability", "cluster.choose-local",
    "cluster.read-subvolume", "cluster.read-subvolume-index",
    "cluster.self-heal-daemon", "cluster.disperse-self-heal-daemon",
    "cluster.shd-max-threads", "cluster.shd-wait-qlength",
    "cluster.background-self-heal-count",
    "cluster.heal-wait-queue-length",
    "disperse.shd-max-threads", "disperse.shd-wait-qlength",
    "disperse.background-heals", "disperse.heal-wait-qlength",
    "disperse.other-eager-lock-timeout",
    "cluster.min-free-inodes", "cluster.readdir-optimize",
    "cluster.rsync-hash-regex", "cluster.extra-hash-regex",
    "cluster.subvols-per-directory", "cluster.weighted-rebalance",
    "cluster.rebalance-stats",
    "performance.normal-prio-threads",
    "performance.enable-least-priority",
    "performance.client-io-threads",
    "performance.write-behind-pass-through",
    "performance.read-ahead-pass-through",
    "performance.readdir-ahead-pass-through",
    "performance.io-cache-pass-through",
    "performance.open-behind-pass-through",
    "performance.md-cache-pass-through",
    "performance.nl-cache-pass-through", "performance.iot-pass-through",
    "performance.cache-max-file-size",
    "performance.cache-min-file-size", "performance.cache-priority",
    "performance.cache-refresh-timeout", "performance.io-cache-size",
    "performance.aggregate-size", "performance.strict-o-direct",
    "performance.strict-write-ordering",
    "performance.write-behind-trickling-writes",
    "performance.stat-prefetch", "performance.cache-swift-metadata",
    "performance.cache-samba-metadata",
    "performance.cache-capability-xattrs",
    "performance.cache-ima-xattrs", "performance.xattr-cache-list",
    "performance.md-cache-statfs", "performance.cache-invalidation",
    "performance.qr-cache-timeout",
    "performance.quick-read-cache-invalidation",
    "performance.read-after-open", "performance.rda-cache-limit",
    "performance.nl-cache-positive-entry",
    "features.worm-file-level", "features.worm-files-deletable",
    "features.default-retention-period", "features.auto-commit-period",
    "features.retention-mode",
    "features.trash-dir", "features.trash-eliminate-path",
    "features.trash-internal-op",
    "changelog.fsync-interval", "changelog.capture-del-path",
    "changelog.encoding",
    "features.soft-timeout", "features.alert-time",
    "features.quota-deem-statfs",
    "features.shard-lru-limit", "features.shard-deletion-rate",
    "features.uss", "features.snapshot-directory",
    "features.show-snapshot-directory",
    "features.ctime", "ctime.noatime",
    "features.locks-monkey-unlocking", "locks.trace",
    "locks.mandatory-locking",
    "diagnostics.brick-log-level", "diagnostics.client-log-level",
    "diagnostics.dump-fd-stats", "diagnostics.stats-dump-interval",
    "diagnostics.fop-sample-interval",
    "diagnostics.fop-sample-buf-size",
    "diagnostics.latency-measurement",
    "features.scrub", "features.scrub-freq", "features.expiry-time",
    "features.scrub-throttle",
    "cluster.local-volume-name", "config.transport",
)
OPTION_MIN_OPVERSION.update({k: 4 for k in _V4_KEYS})

# round-6 additions ship at op-version 5: compound fop chains and TLS
# CN allow-listing — both change what peers must understand (a v4
# member would neither serve chains nor enforce CN lists)
_V5_KEYS = (
    "cluster.use-compound-fops",
    "auth.ssl-allow",
)
OPTION_MIN_OPVERSION.update({k: 5 for k in _V5_KEYS})

# round-7 additions ship at op-version 6: the zero-copy read pipeline
# (scatter-gather frames change what peers must decode) plus the
# read-side knobs that ride it
_V6_KEYS = (
    "network.zero-copy-reads",
    "client.strict-locks",
    "performance.read-ahead-adaptive",
)
OPTION_MIN_OPVERSION.update({k: 6 for k in _V6_KEYS})

# round-8 additions ship at op-version 7: the observability layer —
# trace propagation adds a wire-frame field peers must tolerate, and
# the span/slow-fop knobs ride it
_V7_KEYS = (
    "diagnostics.trace-propagation",
    "diagnostics.slow-fop-threshold",
    "diagnostics.span-ring-size",
)
OPTION_MIN_OPVERSION.update({k: 7 for k in _V7_KEYS})

# round-9 additions ship at op-version 8: the HTTP object gateway —
# a v7 member would store the keys but its glusterd has no gateway
# spawner to consume them (and no `volume gateway` op to start one)
_V8_KEYS = (
    "gateway.port",
    "gateway.listen-host",
    "gateway.pool-size",
    "gateway.max-clients",
    "gateway.metrics-port",
)
OPTION_MIN_OPVERSION.update({k: 8 for k in _V8_KEYS})

# round-10 additions ship at op-version 9: the concurrent event plane
# (server/client frame-turning pools + the reader/writer-split fuse
# bridge) — a v8 member would store the keys but its transports have
# no pool to size
_V9_KEYS = (
    "server.event-threads",
    "client.event-threads",
)
OPTION_MIN_OPVERSION.update({k: 9 for k in _V9_KEYS})

# round-11 addition ships at op-version 10: the mesh-sharded codec
# data plane — a v9 member's BatchingCodec has no mesh tier to route
# coalesced stripe batches onto, so the key must not reach it
_V10_KEYS = (
    "cluster.mesh-codec",
)
OPTION_MIN_OPVERSION.update({k: 10 for k in _V10_KEYS})

# round-12 additions ship at op-version 11: the failure-containment
# plane — lock revocation (a v10 brick has no monitor to arm), the
# client circuit/retry/failfast knobs, deadline propagation (a v10
# brick would pass the reserved request field into fop signatures),
# and error-gen's deterministic failure-count chaos mode
_V11_KEYS = (
    "features.locks-revocation-secs",
    "features.locks-revocation-clear-all",
    "features.locks-revocation-max-blocked",
    "client.circuit-breaker",
    "client.circuit-failure-threshold",
    "client.circuit-reset-interval",
    "client.failfast",
    "client.idempotent-retries",
    "client.retry-backoff-max",
    "network.deadline-propagation",
    "debug.error-failure-count",
)
OPTION_MIN_OPVERSION.update({k: 11 for k in _V11_KEYS})

# round-13 addition ships at op-version 12: the parity-delta write
# plane — the key routes sub-stripe writes through the xorv fop, which
# a v11 brick does not serve (the client's capability gate would fall
# back per write, wasting the advertisement round trip), and op-version
# 12 is also the cluster floor for volgen's systematic-by-default
# disperse layout (an older peer's volgen would hand out
# non-systematic volfiles for the same volume)
_V12_KEYS = (
    "cluster.delta-writes",
)
OPTION_MIN_OPVERSION.update({k: 12 for k in _V12_KEYS})

# round-14 additions ship at op-version 13: the managed rebalance
# daemon — a v12 glusterd has no rebalanced spawner, no
# rebalance-update RPC and no checkpoint slot in its volinfo, so both
# the daemon knob and the migration window key must not reach it (the
# `volume rebalance` ops themselves are gated on 13 in glusterd)
_V13_KEYS = (
    "rebalance.checkpoint-interval",
    "cluster.rebal-migrate-window",
)
OPTION_MIN_OPVERSION.update({k: 13 for k in _V13_KEYS})

# round-15 additions ship at op-version 14: the multi-process data
# plane — a v13 glusterd has no worker-pool spawner arm (the key would
# store and silently serve single-process) and no mesh-distributed
# coordinator plumbing in its brick spawner, so neither key may reach
# one; 14 is also the floor for lifting the mesh-codec-vs-systematic
# refusal (an older peer's BatchingCodec has no systematic mesh tier)
_V14_KEYS = (
    "gateway.workers",
    "cluster.mesh-distributed",
)
OPTION_MIN_OPVERSION.update({k: 14 for k in _V14_KEYS})

# round-16 additions ship at op-version 15: the lease plane — a v14
# brick neither advertises "leases" at SETVOLUME nor serves idle
# expiry, and a v14 glusterd's gateway spawner has no --object-cache
# arm (the key would store and silently serve uncached), so neither
# key may reach an older peer
_V15_KEYS = (
    "features.lease-timeout",
    "gateway.object-cache-size",
)
OPTION_MIN_OPVERSION.update({k: 15 for k in _V15_KEYS})

# round-17 additions ship at op-version 16: the multi-tenant QoS plane
# — a v15 brick's admission path has no QosEngine (the keys would
# store and silently not shed/shape), a v15 client doesn't understand
# the EAGAIN + qos-throttle notice as a backoff signal (it would
# surface spurious EAGAINs to callers instead of re-sending), and a
# v15 glusterd's gateway spawner has no --qos-* arm
_V16_KEYS = (
    "server.qos",
    "server.qos-fops-per-sec",
    "server.qos-bytes-per-sec",
    "server.qos-burst",
    "server.qos-shaped-window",
    "server.qos-soft-quota-delay",
    "client.qos-backoff",
)
OPTION_MIN_OPVERSION.update({k: 16 for k in _V16_KEYS})

# round-18 additions ship at op-version 17: the same-host shared-memory
# bulk lane — a v16 brick has no fd side-channel (the client key would
# store and never arm), a v16 client can't decode FL_SHM records (the
# brick must not advertise to it), and a v16 glusterd doesn't emit
# the keys to either transport end
_V17_KEYS = (
    "network.shm-transport",
    "network.shm-arena-size",
)
OPTION_MIN_OPVERSION.update({k: 17 for k in _V17_KEYS})

# round-19 additions ship at op-version 18: the incident plane — a v17
# io-stats has no flight-recorder push for these keys (they would
# store and silently never capture), and a v17 glusterd has neither
# the __incident__ fan-out nor the gateway --incident-dir spawner arm
_V18_KEYS = (
    "diagnostics.incident-dir",
    "diagnostics.incident-max-bytes",
    "diagnostics.incident-min-interval",
    "diagnostics.flight-ring-size",
    "diagnostics.access-log",
)
OPTION_MIN_OPVERSION.update({k: 18 for k in _V18_KEYS})

# round-20 additions ship at op-version 19: the history/SLO plane — a
# v18 io-stats stores these keys without pushing them (no sampler to
# retune, no engine to install), so a mixed cluster would silently
# diverge on what "the volume's alert rules" even are
_V19_KEYS = (
    "diagnostics.history-interval",
    "diagnostics.history-retention",
    "diagnostics.slo-rules",
)
OPTION_MIN_OPVERSION.update({k: 19 for k in _V19_KEYS})

# default client-side performance stack, bottom -> top (volgen's
# perfxl_option_handlers order); each gated by its enable key
DEFAULT_PERF_STACK = [
    # reference defaults (glusterd-volume-set.c): write-behind,
    # read-ahead, io-cache, quick-read, open-behind and stat-prefetch
    # (md-cache) all default ON; readdir-ahead and nl-cache are opt-in
    ("performance/write-behind", "performance.write-behind", True),
    ("performance/read-ahead", "performance.read-ahead", True),
    ("performance/readdir-ahead", "performance.readdir-ahead", False),
    ("performance/io-cache", "performance.io-cache", True),
    ("performance/quick-read", "performance.quick-read", True),
    ("performance/open-behind", "performance.open-behind", True),
    ("performance/md-cache", "performance.md-cache", True),
    ("performance/nl-cache", "performance.nl-cache", False),
]


def _bool(v: Any) -> bool:
    return str(v).lower() in ("1", "on", "yes", "true", "enable", "enabled")


def _emit(name: str, type_name: str, options: dict[str, Any],
          subvols: list[str]) -> str:
    out = [f"volume {name}", f"    type {type_name}"]
    for k, v in options.items():
        out.append(f"    option {k} {v}")
    if subvols:
        out.append(f"    subvolumes {' '.join(subvols)}")
    out.append("end-volume\n")
    return "\n".join(out)


def layer_options(volinfo: dict, layer_type: str) -> dict[str, Any]:
    """Options set on the volume that target layer_type."""
    out = {}
    for key, val in volinfo.get("options", {}).items():
        m = OPTION_MAP.get(key)
        if m and m[0] == layer_type and m[1] != "__enable__":
            out[m[1]] = val
    return out


def _enabled(volinfo: dict, enable_key: str, default: bool) -> bool:
    val = volinfo.get("options", {}).get(enable_key)
    return default if val is None else _bool(val)


def build_brick_volfile(volinfo: dict, brick: dict) -> str:
    """posix -> locks -> io-stats (server_graph_table order, trimmed)."""
    name = brick["name"]
    popts = {"directory": brick["path"]}
    popts.update(layer_options(volinfo, "storage/posix"))
    out = [_emit(f"{name}-posix", "storage/posix", popts, [])]
    top = f"{name}-posix"
    # metadata-only witness brick: last of each replica group when the
    # volume was created with `arbiter 1` (arbiter.c sits above posix)
    if volinfo.get("arbiter"):
        g = volinfo.get("group-size") or len(volinfo["bricks"])
        if brick["index"] % g == g - 1:
            out.append(_emit(f"{name}-arbiter", "features/arbiter", {},
                             [top]))
            top = f"{name}-arbiter"
    # fop journal directly above posix (server_graph_table order);
    # geo-rep create enables it (default off: no consumer, no journal)
    if _enabled(volinfo, "changelog.changelog", False):
        out.append(_emit(f"{name}-changelog", "features/changelog",
                         layer_options(volinfo, "features/changelog"),
                         [top]))
        top = f"{name}-changelog"
    # corruption fencing (bitd's quarantine marker enforcement)
    if _enabled(volinfo, "features.bitrot", False):
        out.append(_emit(f"{name}-bitrot-stub", "features/bit-rot-stub",
                         {}, [top]))
        top = f"{name}-bitrot-stub"
    if _enabled(volinfo, "features.selinux", False):
        out.append(_emit(f"{name}-selinux", "features/selinux", {},
                         [top]))
        top = f"{name}-selinux"
    if _enabled(volinfo, "features.sdfs", False):
        out.append(_emit(f"{name}-sdfs", "features/sdfs", {}, [top]))
        top = f"{name}-sdfs"
    out.append(_emit(f"{name}-locks", "features/locks",
                     layer_options(volinfo, "features/locks"), [top]))
    top = f"{name}-locks"
    if _enabled(volinfo, "features.leases", False):
        out.append(_emit(f"{name}-leases", "features/leases",
                         layer_options(volinfo, "features/leases"),
                         [top]))
        top = f"{name}-leases"
    if _enabled(volinfo, "features.namespace", False):
        out.append(_emit(f"{name}-namespace", "features/namespace", {},
                         [top]))
        top = f"{name}-namespace"
    # pending-heal index on every brick (server_graph_table puts index
    # above locks; index-base defaults under the posix root)
    out.append(_emit(f"{name}-index", "features/index", {}, [top]))
    top = f"{name}-index"
    if _enabled(volinfo, "features.cache-invalidation", True):
        out.append(_emit(f"{name}-upcall", "features/upcall",
                         layer_options(volinfo, "features/upcall"), [top]))
        top = f"{name}-upcall"
    # worker threads so blocking disk syscalls never stall the brick's
    # event engine (server graph carries io-threads unless
    # performance.iot-pass-through drops it)
    if not _enabled(volinfo, "performance.iot-pass-through", False):
        out.append(_emit(f"{name}-io-threads", "performance/io-threads",
                         layer_options(volinfo, "performance/io-threads"),
                         [top]))
        top = f"{name}-io-threads"
    # snapshot quiesce gate — ALWAYS present (arming rides live
    # reconfigure; a gated layer would force a brick respawn to arm)
    out.append(_emit(f"{name}-barrier", "features/barrier",
                     layer_options(volinfo, "features/barrier"), [top]))
    top = f"{name}-barrier"
    if _enabled(volinfo, "features.quota", False):
        import json as _json

        qopts = layer_options(volinfo, "features/quota")
        # #-escape '#': the volfile parser strips comments, and a
        # limited path containing '#' must not truncate the JSON
        qopts["limits"] = _json.dumps(
            volinfo.get("quota", {}).get("limits", {}),
            separators=(",", ":")).replace("#", "\\u0023")
        if volinfo["type"] == "disperse" and "usage-scale" not in qopts:
            # a disperse brick holds 1/K of every file: scale backend
            # bytes to logical so limits are volume-type independent.
            # An explicit features.quota-usage-scale wins (the operator
            # override must not be silently clobbered).
            g = volinfo.get("group-size") or len(volinfo["bricks"])
            qopts["usage-scale"] = g - volinfo.get("redundancy", 2)
        out.append(_emit(f"{name}-quota", "features/quota", qopts, [top]))
        top = f"{name}-quota"
    if _enabled(volinfo, "features.simple-quota", False):
        sqopts = layer_options(volinfo, "features/simple-quota")
        if volinfo["type"] == "disperse":
            g = volinfo.get("group-size") or len(volinfo["bricks"])
            sqopts["usage-scale"] = g - volinfo.get("redundancy", 2)
        out.append(_emit(f"{name}-simple-quota", "features/simple-quota",
                         sqopts, [top]))
        top = f"{name}-simple-quota"
    if _enabled(volinfo, "features.read-only", False):
        out.append(_emit(f"{name}-ro", "features/read-only", {}, [top]))
        top = f"{name}-ro"
    if _enabled(volinfo, "features.worm", False):
        out.append(_emit(f"{name}-worm", "features/worm", {}, [top]))
        top = f"{name}-worm"
    if _enabled(volinfo, "features.trash", False):
        out.append(_emit(f"{name}-trash", "features/trash",
                         layer_options(volinfo, "features/trash"), [top]))
        top = f"{name}-trash"
    # fault injection on demand (debug.error-gen / debug.delay-gen:
    # the reference volgen inserts these the same way for its .t tests
    # and operators debugging latency/fault behavior live)
    if _enabled(volinfo, "debug.error-gen", False):
        out.append(_emit(f"{name}-error-gen", "debug/error-gen",
                         layer_options(volinfo, "debug/error-gen"),
                         [top]))
        top = f"{name}-error-gen"
    if _enabled(volinfo, "debug.delay-gen", False):
        out.append(_emit(f"{name}-delay-gen", "debug/delay-gen",
                         layer_options(volinfo, "debug/delay-gen"),
                         [top]))
        top = f"{name}-delay-gen"
    if _enabled(volinfo, "debug.trace", False):
        out.append(_emit(f"{name}-trace", "debug/trace",
                         layer_options(volinfo, "debug/trace"), [top]))
        top = f"{name}-trace"
    out.append(_emit(name, "debug/io-stats",
                     layer_options(volinfo, "debug/io-stats"), [top]))
    top = name
    # protocol/server top carries transport auth: per-volume generated
    # credentials (trusted-volfile model) + admin auth.allow/reject +
    # TLS (server xlator at the top of every reference brick volfile)
    sopts = dict(layer_options(volinfo, "protocol/server"))
    sopts.update(_ssl_options(volinfo))
    sopts.update(_compound_options(volinfo))
    sopts.update(_sg_options(volinfo))
    sopts.update(_trace_options(volinfo))
    sopts.update(_shm_options(volinfo))
    # the QoS rebalance lane inherits the operator's ONE throttle word:
    # cluster.rebal-throttle already sizes the daemon's client-side
    # migration wave, and the same lazy/normal/aggressive mode sizes
    # the brick-side paced lane for origin="rebalance" traffic — two
    # expressions of one knob, never two knobs
    rebal = volinfo.get("options", {}).get("cluster.rebal-throttle")
    if rebal is not None:
        sopts["qos-rebalance-throttle"] = rebal
    auth = volinfo.get("auth") or {}
    if auth:
        sopts["auth-user"] = auth["username"]
        sopts["auth-password"] = auth["password"]
        if auth.get("mgmt-username"):
            sopts["auth-mgmt-user"] = auth["mgmt-username"]
            sopts["auth-mgmt-password"] = auth["mgmt-password"]
    out.append(_emit(f"{name}-server", "protocol/server", sopts, [top]))
    return "\n".join(out)


def _ssl_options(volinfo: dict) -> dict[str, Any]:
    """ssl.cert/key/ca and both-end transport keys -> layer options
    applied to protocol/client AND protocol/server alike."""
    out = {}
    for key, val in volinfo.get("options", {}).items():
        m = OPTION_MAP.get(key)
        if m and m[0] in ("__ssl__", "__transport__"):
            out[m[1]] = val
    return out


def _compound_options(volinfo: dict) -> dict[str, Any]:
    """cluster.use-compound-fops lands on every fusion end: the wire
    client, the window flusher, the read-ahead chain issuer, and the
    serving brick top."""
    val = volinfo.get("options", {}).get("cluster.use-compound-fops")
    return {} if val is None else {"compound-fops": val}


def _sg_options(volinfo: dict) -> dict[str, Any]:
    """network.zero-copy-reads lands on both transport ends (client
    requests scatter-gather replies at SETVOLUME, server honors)."""
    val = volinfo.get("options", {}).get("network.zero-copy-reads")
    return {} if val is None else {"sg-replies": val}


def _trace_options(volinfo: dict) -> dict[str, Any]:
    """diagnostics.trace-propagation lands on both transport ends (the
    server advertises + re-arms, the client ships the frame field)."""
    val = volinfo.get("options", {}).get("diagnostics.trace-propagation")
    return {} if val is None else {"trace-fops": val}


def _shm_options(volinfo: dict) -> dict[str, Any]:
    """network.shm-transport lands on both transport ends (the client
    asks for the bulk lane at SETVOLUME, the brick advertises + hands
    out arena fds)."""
    val = volinfo.get("options", {}).get("network.shm-transport")
    return {} if val is None else {"shm-transport": val}


def build_client_volfile(volinfo: dict,
                         ports: dict[str, int] | None = None,
                         mgmt: str | None = None) -> str:
    """protocol/client fan-in -> cluster layer(s) -> perf stack
    (build_client_graph analog).  mgmt (glusterd host:port) enables the
    snapview layer so the mount serves /.snaps — omitted for snapshot
    volfiles themselves (no .snaps inside a snapshot)."""
    vtype = volinfo["type"]
    bricks = volinfo["bricks"]
    ports = ports or {}
    out = []
    names = []
    for b in bricks:
        cname = f"{volinfo['name']}-client-{b['index']}"
        opts = {"remote-host": b["host"],
                "remote-port": ports.get(b["name"], b.get("port", 0)),
                "remote-subvolume": b["name"]}
        auth = volinfo.get("auth") or {}
        if auth:
            opts["username"] = auth["username"]
            opts["password"] = auth["password"]
        opts.update(layer_options(volinfo, "protocol/client"))
        opts.update(_ssl_options(volinfo))
        opts.update(_compound_options(volinfo))
        opts.update(_sg_options(volinfo))
        opts.update(_trace_options(volinfo))
        opts.update(_shm_options(volinfo))
        # a TLS brick implies TLS clients (admins set server.ssl once)
        if _enabled(volinfo, "server.ssl", False):
            opts["ssl"] = "on"
        out.append(_emit(cname, "protocol/client", opts, []))
        names.append(cname)

    def cluster_over(children: list[str], idx: int = 0) -> str:
        vname = volinfo["name"]
        if vtype == "disperse":
            lname = f"{vname}-disperse-{idx}"
            opts = {"redundancy": volinfo.get("redundancy", 2)}
            if volinfo.get("systematic"):
                # fragment format, chosen at volume-create (immutable
                # live — see cluster/disperse "systematic")
                opts["systematic"] = "on"
            opts.update(layer_options(volinfo, "cluster/disperse"))
            if _enabled(volinfo, "changelog.changelog", False) and \
                    "delta-writes" not in opts:
                # geo-rep tails ONE brick's changelog per disperse
                # group (gsyncd Active-worker election assumes every
                # brick journals the same logical ops) — a delta
                # wave's UNTOUCHED data bricks journal nothing, so the
                # tailed brick could silently miss writes.  Full RMW
                # journals on every brick; an explicit
                # cluster.delta-writes=on from the operator still wins
                opts["delta-writes"] = "off"
            out.append(_emit(lname, "cluster/disperse", opts, children))
        elif vtype == "replicate":
            lname = f"{vname}-replicate-{idx}"
            opts = layer_options(volinfo, "cluster/replicate")
            if volinfo.get("arbiter"):
                opts["arbiter-count"] = volinfo["arbiter"]
            if volinfo.get("thin-arbiter"):
                # single group: the volume's LAST brick is the
                # tie-breaker child (thin-arbiter.rc layout)
                opts["thin-arbiter"] = "on"
            out.append(_emit(lname, "cluster/replicate", opts, children))
        else:
            raise ValueError(vtype)
        return lname

    def _dht_type(volinfo: dict) -> str:
        """Plain dht, or a variant (nufa.c / switch.c volgen swap)."""
        if _enabled(volinfo, "cluster.nufa", False):
            return "cluster/nufa"
        if volinfo.get("options", {}).get("cluster.switch-pattern"):
            return "cluster/switch"
        return "cluster/distribute"

    def _leaving() -> set:
        """Brick names being drained by remove-brick start (excluded
        from the dht layout until commit)."""
        rb = volinfo.get("remove-brick") or {}
        if rb.get("status") in ("started", "completed"):
            return set(rb.get("bricks") or ())
        return set()

    if vtype == "distribute":
        dtype = _dht_type(volinfo)
        opts = layer_options(volinfo, "cluster/distribute")
        opts.update(layer_options(volinfo, dtype))
        leaving = _leaving()
        if leaving:
            opts["decommissioned"] = ",".join(
                f"{volinfo['name']}-client-{b['index']}"
                for b in bricks if b["name"] in leaving)
        top = f"{volinfo['name']}-dht"
        out.append(_emit(top, dtype, opts, names))
    elif vtype in ("disperse", "replicate"):
        group = volinfo.get("group-size", len(names))
        if volinfo.get("thin-arbiter"):
            group = len(names)  # 2 data + tie-breaker, one group
        if len(names) > group:  # distributed-disperse / -replicate
            subs = [cluster_over(names[i:i + group], i // group)
                    for i in range(0, len(names), group)]
            top = f"{volinfo['name']}-dht"
            dtype = _dht_type(volinfo)  # nufa/switch apply here too
            dopts = layer_options(volinfo, "cluster/distribute")
            dopts.update(layer_options(volinfo, dtype))
            leaving = _leaving()
            if leaving:
                # remove-brick drains whole groups: a group layer is
                # decommissioned when every brick in it is leaving
                gone = []
                for j in range(0, len(bricks), group):
                    if all(b["name"] in leaving
                           for b in bricks[j:j + group]):
                        gone.append(subs[j // group])
                dopts["decommissioned"] = ",".join(gone)
            out.append(_emit(top, dtype, dopts, subs))
        else:
            top = cluster_over(names)
    else:
        raise ValueError(f"unknown volume type {vtype!r}")

    if _enabled(volinfo, "features.shard", False):
        out.append(_emit(f"{volinfo['name']}-shard", "features/shard",
                         layer_options(volinfo, "features/shard"), [top]))
        top = f"{volinfo['name']}-shard"

    vname = volinfo["name"]
    if _enabled(volinfo, "features.gfid-access", False):
        out.append(_emit(f"{vname}-gfid-access", "features/gfid-access",
                         {}, [top]))
        top = f"{vname}-gfid-access"
    if _enabled(volinfo, "features.utime", False):
        out.append(_emit(f"{vname}-utime", "features/utime", {}, [top]))
        top = f"{vname}-utime"
    if _enabled(volinfo, "features.acl", False):
        out.append(_emit(f"{vname}-acl", "system/posix-acl", {}, [top]))
        top = f"{vname}-acl"

    # EC stripe geometry: page-granular read layers must issue their
    # windows in whole stripes, or every window edge pays a
    # partial-stripe decode (the read-side RMW analog, ISSUE 3)
    ec_stripe = 0
    if vtype == "disperse":
        g = volinfo.get("group-size") or len(bricks)
        ec_stripe = (g - volinfo.get("redundancy", 2)) * 512

    for ltype, key, default in DEFAULT_PERF_STACK:
        # performance.<x>-pass-through (the reference's per-xlator
        # pass_through flag): the layer is simply not built into the
        # graph — volume-set regenerates the volfile and the hot graph
        # swap drops/restores it live
        pt = f"{key}-pass-through"
        on = _enabled(volinfo, key, default)
        if ltype == "performance/md-cache":
            # the reference's historical alias for the same xlator
            on = on and _enabled(volinfo, "performance.stat-prefetch",
                                 True)
        if on and not _enabled(volinfo, pt, False):
            lname = f"{volinfo['name']}-{ltype.split('/')[1]}"
            lopts = layer_options(volinfo, ltype)
            if ltype in ("performance/write-behind",
                         "performance/read-ahead"):
                # window flusher + demand/prefetch reader are the
                # compound emission sites
                lopts.update(_compound_options(volinfo))
            if ec_stripe and ltype in ("performance/read-ahead",
                                       "performance/io-cache") and \
                    "page-size" not in lopts:
                # largest stripe multiple <= the 128KB default: windows
                # land on stripe boundaries, so EC decodes whole
                # stripes instead of partial edges
                lopts["page-size"] = str(
                    max(ec_stripe, (128 << 10) // ec_stripe * ec_stripe))
            if ec_stripe and ltype == "performance/write-behind" and \
                    "stripe-size" not in lopts:
                # the write-side twin (ISSUE 10): pressure drains cut
                # at stripe boundaries, so streamed (gateway
                # chunked-PUT) writes hit EC's aligned fast path
                # instead of paying head/tail RMW every chunk
                lopts["stripe-size"] = str(ec_stripe)
            out.append(_emit(lname, ltype, lopts, [top]))
            top = lname
    if _enabled(volinfo, "performance.client-io-threads", False) and \
            not _enabled(volinfo, "performance.iot-pass-through", False):
        # client-side io-threads (volgen client_graph_builder inserts
        # iot when performance.client-io-threads is on)
        lname = f"{volinfo['name']}-client-io-threads"
        out.append(_emit(lname, "performance/io-threads",
                         layer_options(volinfo,
                                       "performance/io-threads"),
                         [top]))
        top = lname

    # pause gate ALWAYS present: arming rides live reconfigure
    # (features.quiesce), like the brick-side barrier
    out.append(_emit(f"{vname}-quiesce", "features/quiesce",
                     layer_options(volinfo, "features/quiesce"), [top]))
    top = f"{vname}-quiesce"
    out.append(_emit(f"{volinfo['name']}-io-stats", "debug/io-stats",
                     layer_options(volinfo, "debug/io-stats"), [top]))
    top = f"{volinfo['name']}-io-stats"
    if mgmt:
        # user-serviceable snapshots: /.snaps browse (snapview-client)
        out.append(_emit(f"{volinfo['name']}-snapview",
                         "features/snapview",
                         {**layer_options(volinfo, "features/snapview"),
                          "mgmt-server": mgmt,
                          "volume": volinfo["name"]}, [top]))
        top = f"{volinfo['name']}-snapview"
    # virtual /.meta introspection at the very top (the reference
    # autoloads meta on every fuse graph; tests read it like statedump)
    out.append(_emit(volinfo["name"], "meta", {}, [top]))
    return "\n".join(out)


# glusterd-volume-set.c keys deliberately NOT mapped, each with its
# reason (VERDICT r4 #10 asked for the skip list to be explicit).
# Grouped reasons:
#   nfs.*            — gNFS is a declared descope (README): no gNFS server
#   cloudsync        — cloudsync/S3 tiering is a declared descope
#   halo             — latency-based replica selection needs per-brick
#                      RTT probes the asyncio transport doesn't collect yet
#   own-thread       — a dedicated thread per transport; the event pool
#                      (server/client.event-threads) already turns each
#                      connection's frames with per-connection keying
_NFS_WHY = "gNFS server is a declared descope (README)"
_CS_WHY = "cloudsync tiering is a declared descope (README)"
_HALO_WHY = "needs per-brick latency probes the transport does not " \
    "collect (halo descope)"
DESCOPED_KEYS = {
    **{k: _NFS_WHY for k in (
        "nfs.enable-ino32", "nfs.mem-factor", "nfs.export-dirs",
        "nfs.export-volumes", "nfs.addr-namelookup",
        "nfs.dynamic-volumes", "nfs.register-with-portmap",
        "nfs.outstanding-rpc-limit", "nfs.port", "nfs.rpc-auth-unix",
        "nfs.rpc-auth-null", "nfs.rpc-auth-allow", "nfs.rpc-auth-reject",
        "nfs.ports-insecure", "nfs.transport-type", "nfs.trusted-sync",
        "nfs.trusted-write", "nfs.volume-access", "nfs.export-dir",
        "nfs.nlm", "nfs.acl", "nfs.mount-udp", "nfs.mount-rmtab",
        "nfs.rpc-statd", "nfs.log-level", "nfs.server-aux-gids",
        "nfs.drc", "nfs.drc-size", "nfs.read-size", "nfs.write-size",
        "nfs.readdir-size", "nfs.rdirplus", "nfs.event-threads",
        "nfs.exports-auth-enable", "nfs.auth-refresh-interval-sec",
        "nfs.auth-cache-ttl-sec", "performance.nfs.flush-behind",
        "performance.nfs.write-behind-window-size",
        "performance.nfs.strict-o-direct",
        "performance.nfs.strict-write-ordering",
        "performance.nfs.write-behind-trickling-writes",
        "performance.nfs.write-behind", "performance.nfs.read-ahead",
        "performance.nfs.io-cache", "performance.nfs.quick-read",
        "performance.nfs.stat-prefetch", "performance.nfs.io-threads")},
    **{k: _CS_WHY for k in (
        "features.cloudsync", "features.cloudsync-storetype",
        "features.s3plugin-seckey", "features.s3plugin-keyid",
        "features.s3plugin-bucketid", "features.s3plugin-hostname",
        "features.cloudsync-remote-read", "features.cloudsync-store-id",
        "features.cloudsync-product-id")},
    **{k: _HALO_WHY for k in (
        "cluster.halo-enabled", "cluster.halo-shd-max-latency",
        "cluster.halo-nfsd-max-latency", "cluster.halo-max-latency",
        "cluster.halo-max-replicas", "cluster.halo-min-replicas")},
    "server.own-thread": "the event pool's per-connection keying is "
                         "the own-thread dispatch property; a "
                         "dedicated thread per transport adds "
                         "nothing (server.event-threads is mapped)",
    "client.own-thread": "see server.own-thread "
                         "(client.event-threads is mapped)",
    "config.memory-accounting": "Python heap — no mem-pool accounting "
                                "to toggle (mem-pool is a declared "
                                "descope)",
    "server.root-squash": "no per-request uid/gid credential model on "
                          "this wire (single-tenant trust domain)",
    "server.all-squash": "no per-request uid/gid credential model",
    "server.anonuid": "no per-request uid/gid credential model",
    "server.anongid": "no per-request uid/gid credential model",
    "server.manage-gids": "no per-request uid/gid credential model",
    "server.gid-timeout": "no per-request uid/gid credential model",
    "client.send-gids": "no per-request uid/gid credential model",
    "server.dynamic-auth": "auth re-checks at reconnect; live "
                           "disconnect-on-revoke not implemented",
    "client.bind-insecure": "clients always bind ephemeral ports; the "
                            "brick-side allow-insecure check is the "
                            "operative half",
    "client.strict-locks": "anonymous-fd lock bypass tracking not "
                           "implemented",
    "client.ta-brick-port": "thin-arbiter brick resolves through the "
                            "mgmt portmap like any brick",
    "transport.keepalive": "keepalive-time=0 disables; a separate bool "
                           "would alias it",
    "network.remote-dio": "O_DIRECT is propagated as-is to bricks "
                          "(storage.o-direct governs the backend)",
    "network.inode-lru-limit": "brick inode tables are per-connection "
                               "dicts reaped on disconnect, not a "
                               "global LRU",
    "cluster.rmdir-optimize": "rmdir already fans out once per child; "
                              "no hashed-only fast path to skip",
    "cluster.lock-migration": "rebalance drains files under the "
                              "cluster lock instead of migrating "
                              "posix-lock state",
    "cluster.force-migration": "rebalance never skips hardlinked files "
                               "(the unsafe case force-migration "
                               "exists to override)",
    "rebalance.ensure-durability": "migrations fsync the destination "
                                   "before the swap unconditionally",
    "cluster.randomize-hash-range-by-gfid": "layouts seed by path hash "
                                            "(subvols-per-directory); "
                                            "gfid seeding adds nothing "
                                            "on top",
    "cluster.switch": "cluster.switch-pattern selects the variant "
                      "already",
    "cluster.entry-change-log": "pending-counter scheme tracks entry "
                                "changes unconditionally",
    "cluster.data-change-log": "pending counters are not optional in "
                               "this design (heal correctness)",
    "cluster.metadata-change-log": "pending counters are not optional",
    "cluster.optimistic-change-log": "delayed dirty is the eager-window "
                                     "design already",
    "disperse.optimistic-change-log": "same: the eager window IS the "
                                      "optimistic change-log",
    "cluster.post-op-delay-secs": "AFR commits per-fop; EC carries the "
                                  "delayed post-op (eager-lock-timeout "
                                  "is that knob)",
    "cluster.self-heal-readdir-size": "entry heal unions full listings "
                                      "(no windowed readdir)",
    "cluster.strict-readdir": "dht readdir already merges per-child "
                              "listings strictly",
    "cluster.consistent-metadata": "reads already pick from "
                                   "version-consistent children only",
    "cluster.full-lock": "EC/AFR transactions lock the affected range; "
                         "full-file locking is the heal path's choice",
    "cluster.locking-scheme": "granular eager-lock is the only scheme "
                              "implemented",
    "cluster.granular-entry-heal": "entry heal diffs listings already "
                                   "(no full-crawl mode to upgrade "
                                   "from)",
    "cluster.heal-wait-queue-length/disperse": "mapped via mgmt/shd "
                                               "wait-qlength",
    "cluster.use-anonymous-inode": "heal resolves by gfid handle "
                                   "directly",
    "cluster.read-freq-threshold": "no tiering",
    "cluster.write-freq-threshold": "no tiering",
    "features.tag-namespaces": "namespace layer tags unconditionally",
    "features.timeout": "leases recall-timeout covers the lease knob",
    "features.failover-hosts": "ganesha descope",
    "ganesha.enable": "NFS-Ganesha integration is out of scope with "
                      "gNFS",
    "features.lease-lock-recall-timeout": "features/leases "
                                          "recall-timeout is the "
                                          "mapped spelling",
    "features.signer-threads": "bitd signs in one asyncio loop; "
                               "thread sizing has no analog",
    "features.enforce-mandatory-lock": "locks.mandatory-locking=forced "
                                       "is the mapped spelling",
    "features.locks-revocation-secs": "lock revocation not implemented "
                                      "(lock-timeout bounds waits; "
                                      "contention upcalls drain "
                                      "holders)",
    "features.locks-revocation-clear-all": "lock revocation not "
                                           "implemented",
    "features.locks-revocation-max-blocked": "lock revocation not "
                                             "implemented",
    "diagnostics.brick-sys-log-level": "no syslog sink; file/stderr "
                                       "logging only",
    "diagnostics.client-sys-log-level": "no syslog sink",
    "diagnostics.brick-logger": "one logger backend (gflog)",
    "diagnostics.client-logger": "one logger backend",
    "diagnostics.brick-log-format": "gflog's msgid format is fixed",
    "diagnostics.client-log-format": "gflog's msgid format is fixed",
    "diagnostics.brick-log-buf-size": "no log suppression ring",
    "diagnostics.client-log-buf-size": "no log suppression ring",
    "diagnostics.brick-log-flush-timeout": "line-buffered logging",
    "diagnostics.client-log-flush-timeout": "line-buffered logging",
    "diagnostics.stats-dump-format": "profile dumps are JSON only",
    "diagnostics.stats-dnscache-ttl-sec": "no DNS cache in io-stats",
    "storage.linux-aio": "declared descope (io_uring/aio; asyncio + "
                         "thread pool is the io engine)",
    "storage.linux-io_uring": "declared descope",
    "storage.batch-fsync-mode": "fsync batching rides the io-threads "
                                "pool; reverse-fsync heuristics not "
                                "ported",
    "storage.batch-fsync-delay-usec": "see storage.batch-fsync-mode",
    "storage.xattr-user-namespace-mode": "user.* xattrs pass through "
                                         "unmapped",
    "storage.node-uuid-pathinfo": "pathinfo xattr virtual not "
                                  "implemented",
    "storage.build-pgfid": "parent-gfid xattrs: the gfid handle farm "
                           "resolves parents already",
    "storage.gfid2path": "gfid->path resolution is served by the "
                         "handle farm natively",
    "storage.gfid2path-separator": "see storage.gfid2path",
    "storage.force-create-mode/directory": "mapped as storage.force-"
                                           "create-mode / -directory-"
                                           "mode",
    "features.cache-invalidation": "brick-side upcall is "
                                   "features.cache-invalidation in the "
                                   "map already (upcall enable)",
    "performance.global-cache-invalidation": "md-cache "
                                             "cache-invalidation is "
                                             "the per-volume switch",
    "performance.ctime-invalidation": "quick-read invalidates on "
                                      "upcall, not ctime compare",
    "performance.iot-watchdog-secs": "asyncio loop cannot wedge on one "
                                     "fop (cooperative scheduling)",
    "performance.iot-cleanup-disconnected-reqs": "server drops a dead "
                                                 "client's queued "
                                                 "frames at disconnect "
                                                 "already",
    "performance.resync-failed-syncs-after-fsync": "write-behind "
                                                   "surfaces flush "
                                                   "errors; no "
                                                   "resync queue",
    "performance.rda-low-wmark": "rda prefetches whole listings; "
                                 "watermark streaming not implemented "
                                 "(rda-cache-limit bounds memory)",
    "performance.rda-high-wmark": "see rda-low-wmark",
    "performance.parallel-readdir": "one rda instance above dht; "
                                    "per-child rda insertion not "
                                    "implemented",
    "performance.nl-cache-pass-through/quick-read": "quick-read has no "
                                                    "pass-through in "
                                                    "the reference "
                                                    "either",
    "performance.cache-size/io-cache vs quick-read": "both spellings "
                                                     "map per layer "
                                                     "already",
    "dht.force-readdirp": "readdirp is the only dht listing path (no "
                          "plain-readdir fallback to force away from)",
    "feature.simple-quota-pass-through": "features.simple-quota enable "
                                         "key inserts/removes the "
                                         "layer",
    "feature.simple-quota.use-backend": "one backend (xattr "
                                        "accounting)",
    "features.quota-timeout": "features.hard-timeout is the mapped "
                              "spelling",
    "features.ctime/utime": "mapped as features.ctime",
    "debug.log-history": "debug.trace-log-history is the mapped "
                         "spelling",
    "debug.log-file": "gflog writes the daemon's log file already",
    "debug.exclude-ops": "debug.trace-exclude-ops is the mapped "
                         "spelling",
    "debug.include-ops": "exclude-ops covers the trace filter "
                         "(include is its complement)",
    "debug.random-failure": "debug.error-failure percentage is the "
                            "mapped spelling",
    "delay-gen.delay-percentage": "debug.delay-percent is the mapped "
                                  "spelling",
    "delay-gen.delay-duration": "debug.delay-duration is the mapped "
                                "spelling",
    "delay-gen.enable": "debug.delay-gen + debug.delay-fops are the "
                        "mapped spellings",
    "locks.trace/features": "mapped as both locks.trace and "
                            "features.locks-trace",
}


def options_doc() -> str:
    """The docs/volume_options.md content, generated from OPTION_MAP.
    test_option_map_integrity pins the committed file to this output,
    so the operator-facing table cannot drift from the map."""
    lines = [
        "# `volume set` options",
        "",
        "Generated from `mgmt/volgen.py`'s OPTION_MAP (the",
        "glusterd-volume-set.c analog) by `volgen.options_doc()`; the",
        "committed file is pinned to that output by",
        "`tests/test_reconfigure.py::test_option_map_integrity`.  Every",
        "key lands on a declared, consumed option of a live layer;",
        "`(enable)` keys insert/remove the layer in the generated",
        "graphs.  Keys with an op-version need the whole cluster at",
        "that version (mixed-version skew guard).",
        "",
        "| key | target | option | op-ver |",
        "|---|---|---|---|",
    ]
    for key in sorted(OPTION_MAP):
        ltype, opt = OPTION_MAP[key]
        ver = OPTION_MIN_OPVERSION.get(key, 1)
        o = "(enable)" if opt == "__enable__" else \
            "(pass-through)" if opt == "__passthrough__" else opt
        lines.append(f"| {key} | {ltype} | {o} | {ver} |")
    lines.append("")
    lines.append(f"{len(OPTION_MAP)} keys total.")
    lines.append("")
    lines.append("## Deliberately unmapped reference keys")
    lines.append("")
    lines.append("glusterd-volume-set.c keys this build intentionally")
    lines.append("does not carry, with the reason (one line each):")
    lines.append("")
    for key, why in sorted(DESCOPED_KEYS.items()):
        lines.append(f"- `{key}` — {why}")
    return "\n".join(lines) + "\n"
