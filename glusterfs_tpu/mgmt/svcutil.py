"""Shared plumbing for per-volume service daemons (bitd, quotad, …):
credential/TLS wiring between glusterd's spawner and the daemon's
brick ClientLayers, and the migration-wave throttle both rebalance
walks share.  One copy, so an auth change lands everywhere
(glusterd-svc-mgmt.c is the reference's shared service layer)."""

from __future__ import annotations

import asyncio
import os
from typing import Any

from . import volgen


class ThrottleWave:
    """The ``cluster.rebal-throttle`` wave loop (dht-rebalance.c:3269
    migrator thread scaling) — ONE copy shared by the rebalance
    daemon's ``_migrate_dir`` and the legacy in-process
    ``DistributeLayer.rebalance`` walk: admit a migration task when the
    in-flight set drops below ``width``, track the peak, and (lazy
    mode) hand the loop back so serving fops interleave with the
    crawl.  Width/pause are passed PER ADMIT because both callers
    re-read the throttle option every wave — a live ``volume set``
    retunes a running migration."""

    def __init__(self) -> None:
        self.pending: list[asyncio.Task] = []
        self.max_inflight = 0

    async def admit(self, coro, width: int, pause: float = 0.0) -> None:
        """Wait for a slot under ``width``, launch ``coro``, then
        optionally yield (``pause`` — the lazy throttle's cooperative
        beat)."""
        while len(self.pending) >= max(1, int(width)):
            _done, rest = await asyncio.wait(
                self.pending, return_when=asyncio.FIRST_COMPLETED)
            self.pending = list(rest)
        self.pending.append(asyncio.ensure_future(coro))
        self.max_inflight = max(self.max_inflight, len(self.pending))
        if pause:
            await asyncio.sleep(pause)

    async def drain(self) -> None:
        """Await every in-flight migration (end of a directory wave).
        Tasks never re-raise here — both callers count failures inside
        the task body (an uncounted escape would report a clean run
        with files still misplaced)."""
        if self.pending:
            await asyncio.wait(self.pending)
        self.pending = []


def add_ssl_args(parser) -> None:
    parser.add_argument("--ssl", action="store_true")
    parser.add_argument("--ssl-ca", default="")
    parser.add_argument("--ssl-cert", default="")
    parser.add_argument("--ssl-key", default="")


def client_opts(args, env_prefix: str, host: str, port: int,
                subvol: str) -> dict[str, Any]:
    """ClientLayer options for a service daemon's brick connection:
    credentials from the environment (argv is world-readable), TLS from
    the spawner's flags."""
    copts: dict[str, Any] = {"remote-host": host, "remote-port": port,
                             "remote-subvolume": subvol}
    user = os.environ.get(f"{env_prefix}_USERNAME", "")
    if user:
        copts["username"] = user
        copts["password"] = os.environ.get(f"{env_prefix}_PASSWORD", "")
    if args.ssl:
        for k, v in (("ssl-ca", args.ssl_ca), ("ssl-cert", args.ssl_cert),
                     ("ssl-key", args.ssl_key)):
            if v:
                copts[k] = v
        copts["ssl"] = "on"
    return copts


def spawn_ssl_argv(opts: dict) -> list[str]:
    """argv TLS flags matching add_ssl_args, from volume options."""
    out: list[str] = []
    if volgen._bool(opts.get("server.ssl", "off")):
        out.append("--ssl")
    for volkey, flag in (("ssl.ca", "--ssl-ca"),
                         ("ssl.cert", "--ssl-cert"),
                         ("ssl.key", "--ssl-key")):
        if opts.get(volkey):
            out += [flag, opts[volkey]]
    return out


def spawn_env(vol: dict, env_prefix: str) -> dict[str, str]:
    """Subprocess environment for a service daemon: jax pinned to CPU
    plus the volume's mgmt credential pair under the given prefix."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    auth = vol.get("auth") or {}
    if auth:
        env[f"{env_prefix}_USERNAME"] = auth.get(
            "mgmt-username", auth.get("username", ""))
        env[f"{env_prefix}_PASSWORD"] = auth.get(
            "mgmt-password", auth.get("password", ""))
    return env


def brick_group(vol: dict, index: int) -> int:
    """Aggregation group of a brick: bricks in one replica/disperse
    group hold the same logical files (aggregate = max within group);
    distinct groups hold disjoint DHT subtrees (aggregate = sum across
    groups)."""
    n = len(vol["bricks"])
    if vol["type"] in ("disperse", "replicate"):
        g = vol.get("group-size") or n
        return index // g
    return index  # pure distribute: every brick its own group
