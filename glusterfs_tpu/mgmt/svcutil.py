"""Shared plumbing for per-volume service daemons (bitd, quotad, …):
credential/TLS wiring between glusterd's spawner and the daemon's
brick ClientLayers, the migration-wave throttle both rebalance walks
share, and the token-bucket rate limiter the scrubber and the QoS
plane share.  One copy, so an auth change lands everywhere
(glusterd-svc-mgmt.c is the reference's shared service layer)."""

from __future__ import annotations

import asyncio
import os
import time
from typing import Any

from . import volgen


class TokenBucket:
    """The libglusterfs throttle-tbf.c analog, generalized from the
    bitrot scrubber's bandwidth cap (mgmt/bitd.py) for the QoS plane
    (features/qos.py): ``rate`` tokens refill per second up to a
    ``burst`` ceiling.  ``take`` sleeps until the debit fits (shaping —
    the scrubber / rebalance-lane semantic); ``try_take`` never sleeps
    and instead reports how long the caller would have to wait (the
    admission-shed semantic: the brick answers a retryable errno
    carrying that wait instead of parking the connection).

    rate <= 0 disables — every take is free, every try_take admits.
    ``set_rate`` retunes a LIVE bucket (volume set): accumulated
    tokens are clamped to the new burst so a rate cut takes effect
    within one refill window instead of after the old burst drains."""

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else self.rate
        self.tokens = self.burst
        self._t = time.monotonic()

    def set_rate(self, rate: float, burst: float | None = None) -> None:
        rate = float(rate)
        if rate != self.rate or (burst is not None
                                 and float(burst) != self.burst):
            was_off = self.rate <= 0
            self._refill()
            self.rate = rate
            self.burst = float(burst) if burst is not None else rate
            # a bucket switching on starts FULL (a disabled bucket
            # accrued nothing — without this a client's first frame
            # after enable would shed); a live retune keeps the
            # accrued balance, clamped to the new burst
            self.tokens = self.burst if was_off \
                else min(self.tokens, self.burst)

    def _refill(self) -> None:
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now

    def level(self) -> float:
        """Current token balance (refilled to now) — the gauge probe."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        return self.tokens

    def debit(self, n: float) -> None:
        """Unconditional debit — the balance may go NEGATIVE
        (borrowing): reply bytes are charged after the send, and the
        debt delays the next admission instead of blocking this one."""
        if self.rate <= 0:
            return
        self._refill()
        self.tokens -= n

    def try_take(self, n: float) -> float:
        """Debit ``n`` tokens without ever sleeping.  Returns 0.0 on
        success; otherwise the seconds until ``n`` (clamped to one
        burst — a debit bigger than the bucket proceeds when it is
        full, the tbf never-starve rule) would be available."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        need = min(n, self.burst)
        if self.tokens >= need:
            self.tokens -= n  # may go negative: oversized debits owe
            return 0.0
        return (need - self.tokens) / self.rate

    async def take(self, n: float) -> None:
        if self.rate <= 0:
            return
        while True:
            self._refill()
            # an object bigger than one burst's budget proceeds when
            # the bucket is full (tbf_mod semantics: never starve)
            if self.tokens >= n or self.tokens >= self.burst:
                self.tokens -= n
                return
            await asyncio.sleep(
                min(1.0, (min(n, self.burst) - self.tokens) / self.rate))


class ThrottleWave:
    """The ``cluster.rebal-throttle`` wave loop (dht-rebalance.c:3269
    migrator thread scaling) — ONE copy shared by the rebalance
    daemon's ``_migrate_dir`` and the legacy in-process
    ``DistributeLayer.rebalance`` walk: admit a migration task when the
    in-flight set drops below ``width``, track the peak, and (lazy
    mode) hand the loop back so serving fops interleave with the
    crawl.  Width/pause are passed PER ADMIT because both callers
    re-read the throttle option every wave — a live ``volume set``
    retunes a running migration."""

    def __init__(self) -> None:
        self.pending: list[asyncio.Task] = []
        self.max_inflight = 0

    async def admit(self, coro, width: int, pause: float = 0.0) -> None:
        """Wait for a slot under ``width``, launch ``coro``, then
        optionally yield (``pause`` — the lazy throttle's cooperative
        beat)."""
        while len(self.pending) >= max(1, int(width)):
            _done, rest = await asyncio.wait(
                self.pending, return_when=asyncio.FIRST_COMPLETED)
            self.pending = list(rest)
        self.pending.append(asyncio.ensure_future(coro))
        self.max_inflight = max(self.max_inflight, len(self.pending))
        if pause:
            await asyncio.sleep(pause)

    async def drain(self) -> None:
        """Await every in-flight migration (end of a directory wave).
        Tasks never re-raise here — both callers count failures inside
        the task body (an uncounted escape would report a clean run
        with files still misplaced)."""
        if self.pending:
            await asyncio.wait(self.pending)
        self.pending = []


def add_ssl_args(parser) -> None:
    parser.add_argument("--ssl", action="store_true")
    parser.add_argument("--ssl-ca", default="")
    parser.add_argument("--ssl-cert", default="")
    parser.add_argument("--ssl-key", default="")


def client_opts(args, env_prefix: str, host: str, port: int,
                subvol: str) -> dict[str, Any]:
    """ClientLayer options for a service daemon's brick connection:
    credentials from the environment (argv is world-readable), TLS from
    the spawner's flags."""
    copts: dict[str, Any] = {"remote-host": host, "remote-port": port,
                             "remote-subvolume": subvol}
    user = os.environ.get(f"{env_prefix}_USERNAME", "")
    if user:
        copts["username"] = user
        copts["password"] = os.environ.get(f"{env_prefix}_PASSWORD", "")
    if args.ssl:
        for k, v in (("ssl-ca", args.ssl_ca), ("ssl-cert", args.ssl_cert),
                     ("ssl-key", args.ssl_key)):
            if v:
                copts[k] = v
        copts["ssl"] = "on"
    return copts


def spawn_ssl_argv(opts: dict) -> list[str]:
    """argv TLS flags matching add_ssl_args, from volume options."""
    out: list[str] = []
    if volgen._bool(opts.get("server.ssl", "off")):
        out.append("--ssl")
    for volkey, flag in (("ssl.ca", "--ssl-ca"),
                         ("ssl.cert", "--ssl-cert"),
                         ("ssl.key", "--ssl-key")):
        if opts.get(volkey):
            out += [flag, opts[volkey]]
    return out


def spawn_env(vol: dict, env_prefix: str) -> dict[str, str]:
    """Subprocess environment for a service daemon: jax pinned to CPU
    plus the volume's mgmt credential pair under the given prefix."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    auth = vol.get("auth") or {}
    if auth:
        env[f"{env_prefix}_USERNAME"] = auth.get(
            "mgmt-username", auth.get("username", ""))
        env[f"{env_prefix}_PASSWORD"] = auth.get(
            "mgmt-password", auth.get("password", ""))
    return env


def brick_group(vol: dict, index: int) -> int:
    """Aggregation group of a brick: bricks in one replica/disperse
    group hold the same logical files (aggregate = max within group);
    distinct groups hold disjoint DHT subtrees (aggregate = sum across
    groups)."""
    n = len(vol["bricks"])
    if vol["type"] in ("disperse", "replicate"):
        g = vol.get("group-size") or n
        return index // g
    return index  # pure distribute: every brick its own group
