"""Events daemon — the glustereventsd analog.

Reference: events/src/glustereventsd.py + eventsapiconf: a per-node UDP
listener collects gf_event datagrams and POSTs them as JSON to every
registered webhook; webhooks are managed via gluster-eventsapi.

TPU-build shape: an asyncio UDP endpoint plus a wire-framed TCP control
port (webhook-add / webhook-del / status / recent).  Webhook delivery is
a minimal HTTP/1.1 POST over asyncio streams — no external HTTP client,
zero-egress friendly.  Undeliverable webhooks are counted, never
retried into a queue explosion.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import sys
from collections import deque
from urllib.parse import urlparse

from ..core.fops import FopError
from ..core import gflog
from ..core import metrics as _metrics
from ..rpc import wire

log = gflog.get_logger("eventsd")

# event-plane health in the unified registry (weakref: a stopped
# daemon's families age out) — `eventsapi status` answers humans, these
# answer the scraper
_LIVE_EVENTSD = _metrics.REGISTRY.register_objects(
    "gftpu_events_received_total", "counter",
    "gf_event datagrams ingested by this eventsd",
    lambda d: [({}, d.received)])
_metrics.REGISTRY.register_objects(
    "gftpu_events_webhook_total", "counter",
    "webhook delivery outcomes per registered url",
    lambda d: [({"url": url, "result": k}, v)
               for url, st in d.webhooks.items()
               for k, v in st.items()],
    live=_LIVE_EVENTSD)
_metrics.REGISTRY.register_objects(
    "gftpu_events_webhook_retries_total", "counter",
    "webhook delivery attempts retried after a connect failure or 5xx",
    lambda d: [({"url": url}, n)
               for url, n in sorted(d.webhook_retries.items())],
    live=_LIVE_EVENTSD)

#: bounded retry: one retry (2 attempts total) with a short backoff —
#: enough to ride out a webhook restart, bounded enough that a dead
#: webhook can never queue-explode the delivery tasks (the reference's
#: glustereventsd never retries at all; one bounded retry keeps the
#: no-explosion property while surviving the common blip)
_WEBHOOK_ATTEMPTS = 2
_WEBHOOK_BACKOFF_CAP_S = 1.0


class _UdpSink(asyncio.DatagramProtocol):
    def __init__(self, daemon: "EventsDaemon"):
        self.daemon = daemon

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            event = json.loads(data.decode())
        except ValueError:
            return
        self.daemon._ingest(event)


class EventsDaemon:
    def __init__(self, host: str = "127.0.0.1", udp_port: int = 0,
                 ctl_port: int = 0, history: int = 256):
        self.host = host
        self.udp_port = udp_port
        self.ctl_port = ctl_port
        self.webhooks: dict[str, dict] = {}  # url -> delivery stats
        self.webhook_retries: dict[str, int] = {}  # url -> retry count
        self.recent: deque = deque(maxlen=history)
        self.received = 0
        self._transport = None
        self._ctl: asyncio.AbstractServer | None = None
        self._bg: set[asyncio.Task] = set()
        _LIVE_EVENTSD.add(self)

    async def start(self) -> tuple[int, int]:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpSink(self), local_addr=(self.host, self.udp_port))
        self.udp_port = self._transport.get_extra_info("sockname")[1]
        self._ctl = await asyncio.start_server(self._serve_ctl, self.host,
                                               self.ctl_port)
        self.ctl_port = self._ctl.sockets[0].getsockname()[1]
        log.info(1, "eventsd udp=%d ctl=%d", self.udp_port, self.ctl_port)
        return self.udp_port, self.ctl_port

    async def stop(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None
        if self._ctl is not None:
            self._ctl.close()
            await self._ctl.wait_closed()
            self._ctl = None
        for t in list(self._bg):
            t.cancel()

    # -- ingestion + fan-out ----------------------------------------------

    def _ingest(self, event: dict) -> None:
        self.received += 1
        self.recent.append(event)
        for url in list(self.webhooks):
            t = asyncio.get_event_loop().create_task(
                self._deliver(url, event))
            self._bg.add(t)
            t.add_done_callback(self._bg.discard)

    async def _deliver(self, url: str, event: dict) -> None:
        stats = self.webhooks.get(url)
        if stats is None:
            return
        for attempt in range(_WEBHOOK_ATTEMPTS):
            outcome = await self._post(url, event)
            if outcome == "ok":
                stats["delivered"] += 1
                return
            # a 4xx is the webhook REJECTING the event — retrying it
            # re-sends the same rejected payload; only transport blips
            # (connect failure / timeout) and 5xx earn the retry
            if outcome == "fatal" or attempt == _WEBHOOK_ATTEMPTS - 1:
                break
            self.webhook_retries[url] = \
                self.webhook_retries.get(url, 0) + 1
            await asyncio.sleep(min(_WEBHOOK_BACKOFF_CAP_S,
                                    0.25 * (2 ** attempt)))
        stats["failed"] += 1

    async def _post(self, url: str, event: dict) -> str:
        """One delivery attempt: ``ok`` (2xx), ``retryable`` (connect
        failure / timeout / 5xx) or ``fatal`` (any other status)."""
        u = urlparse(url)
        body = json.dumps(event).encode()
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(u.hostname, u.port or 80), 5)
        except Exception:
            return "retryable"
        try:
            req = (f"POST {u.path or '/'} HTTP/1.1\r\n"
                   f"Host: {u.hostname}\r\n"
                   f"Content-Type: application/json\r\n"
                   f"Content-Length: {len(body)}\r\n"
                   f"Connection: close\r\n\r\n").encode() + body
            writer.write(req)
            await writer.drain()
            status = await asyncio.wait_for(reader.readline(), 5)
            if b" 2" in status:
                return "ok"
            return "retryable" if b" 5" in status else "fatal"
        except Exception:
            return "retryable"
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # -- control port ------------------------------------------------------

    async def _serve_ctl(self, reader, writer) -> None:
        try:
            while True:
                try:
                    rec = await wire.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                xid, _, payload = wire.unpack(rec)
                try:
                    method, kwargs = payload
                    ret = self._ctl_op(method, kwargs or {})
                    resp = (wire.MT_REPLY, ret)
                except Exception as e:
                    resp = (wire.MT_ERROR, FopError(errno.EINVAL,
                                                    repr(e)))
                writer.write(wire.pack(xid, *resp))
                await writer.drain()
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _ctl_op(self, method: str, kwargs: dict):
        if method == "webhook-add":
            u = urlparse(kwargs["url"])
            if u.scheme != "http" or not u.hostname:
                # delivery is plaintext HTTP/1.1; silently degrading an
                # https:// registration to port-80 plaintext would leak
                # event payloads
                raise ValueError("only http:// webhook URLs are supported")
            self.webhooks.setdefault(kwargs["url"],
                                     {"delivered": 0, "failed": 0})
            return {"ok": True, "webhooks": sorted(self.webhooks)}
        if method == "webhook-del":
            self.webhooks.pop(kwargs["url"], None)
            self.webhook_retries.pop(kwargs["url"], None)
            return {"ok": True, "webhooks": sorted(self.webhooks)}
        if method == "status":
            return {"received": self.received,
                    "webhooks": dict(self.webhooks),
                    "udp_port": self.udp_port}
        if method == "recent":
            n = int(kwargs.get("count", 50))
            return {"events": list(self.recent)[-n:]}
        raise ValueError(f"unknown op {method!r}")


async def _amain(args) -> None:
    from ..core import flight, history
    from ..core.metrics import register_build_info

    flight.set_role("eventsd")
    register_build_info("eventsd")
    history.arm()
    d = EventsDaemon(args.host, args.udp_port, args.ctl_port)
    await d.start()
    metrics_srv = None
    if args.metrics_port:
        # the received/webhook counter families above, in Prometheus
        # text form (shares daemon.serve_metrics with brick processes)
        from ..daemon import serve_metrics

        metrics_srv = await serve_metrics(args.host, args.metrics_port)
    if args.portfile:
        with open(args.portfile + ".tmp", "w") as f:
            json.dump({"udp": d.udp_port, "ctl": d.ctl_port}, f)
        os.replace(args.portfile + ".tmp", args.portfile)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if metrics_srv is not None:
        metrics_srv.close()
    await d.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-eventsd")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--udp-port", type=int, default=24009)
    p.add_argument("--ctl-port", type=int, default=24010)
    p.add_argument("--portfile", default="")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve the unified metrics registry (event "
                        "received/delivered/failed counters) as a "
                        "Prometheus endpoint (0 = off)")
    args = p.parse_args(argv)
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
