"""Self-heal daemon — the glustershd analog.

Reference: glustershd is a glusterfsd process running the client graph
minus performance layers, with healer threads per subvolume that crawl
the brick-side pending index and heal by gfid
(xlators/cluster/ec/src/ec-heald.c:282 ec_shd_index_healer,
ec-heald.c:390 ec_shd_index_sweep; afr-self-heald.c similarly).

Same split here:

* :func:`crawl_once` — one index sweep over every heal-capable cluster
  layer in a mounted graph: list each brick's pending gfids through the
  index layer's virtual xattr, resolve gfid -> path through posix's
  ``glusterfs_tpu.gfid2path``, call the layer's ``heal_file`` /
  ``heal_entry``; entries whose gfid no longer resolves anywhere are
  pruned (the unlinked-while-pending case).
* :class:`SelfHealDaemon` — the crawl on a ``heal-timeout`` interval.
* :func:`main` — the process entry glusterd spawns per started volume
  (one shd per volume here; the reference multiplexes volumes into one
  shd per node).
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import signal
import sys

from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import Loc
from ..core import gflog
from ..features.index import XA_INDEX_LIST, XA_INDEX_PRUNE
from ..storage.posix import XA_GFID2PATH as GFID2PATH

log = gflog.get_logger("shd")


def _heal_layers(graph):
    """Cluster layers that know how to heal (disperse / replicate)."""
    return [l for l in graph.by_name.values()
            if callable(getattr(l, "heal_file", None))
            and callable(getattr(l, "heal_info", None))]


async def list_pending(layer) -> dict[str, list]:
    """gfid-hex -> [children that have it indexed] for one cluster layer."""
    pending: dict[str, list] = {}
    for child in layer.children:
        try:
            r = await child.getxattr(Loc("/"), XA_INDEX_LIST)
            hexes = r[XA_INDEX_LIST].decode().split()
        except FopError:
            continue
        for h in hexes:
            pending.setdefault(h, []).append(child)
    return pending


async def _resolve(layer, gfid: bytes) -> str | None:
    for child in layer.children:
        try:
            r = await child.getxattr(Loc("", gfid=gfid), GFID2PATH)
            return r[GFID2PATH].decode()
        except FopError:
            continue
    return None


async def full_crawl(client, max_heals: int = 1) -> dict:
    """``heal full``: walk the whole namespace and heal every entry —
    the reference's full sweep (ec-heald.c:418 ec_shd_full_sweep /
    afr full crawl).  Unlike the index sweep, this repairs bricks with
    NO pending record — a replaced (empty) brick, a wiped backend —
    because heal_info re-derives good/bad from the live lookups.

    ``max_heals`` file heals run CONCURRENTLY (the shd-max-threads
    analog the index sweep already honors): directory entry-heals
    happen in walk order (they create missing files on replaced
    bricks); file heals stream out under one semaphore as the walk
    discovers them, backlog-bounded.  On a
    ``cluster.mesh-codec`` volume this is the heal half of the mesh
    data plane — concurrent heals' window re-encodes coalesce in the
    stripe-cache batching window, so many files' dirty stripes land in
    ONE (dp, frag) mesh launch and heal throughput scales with the
    mesh instead of one device (ec-heal.c:2048's rebuild, batched)."""
    from ..cluster.dht import DistributeLayer

    report = {"healed": [], "skipped": [], "failed": []}
    layers = _heal_layers(client.graph)
    # distributed-X: a file lives in exactly ONE group — route its heal
    # to the owning group layer, or every group wastes a fan-out and
    # reports spurious failures for files it does not hold
    dht = next((l for l in client.graph.by_name.values()
                if isinstance(l, DistributeLayer)), None)

    async def owners(path: str) -> list:
        if dht is None or not all(l in dht.children for l in layers):
            return layers
        try:
            child = dht.children[await dht._cached_idx(Loc(path))]
        except FopError:
            return layers
        return [child] if child in layers else layers

    async def one(layer, path: str, is_dir: bool) -> None:
        try:
            if is_dir:
                if callable(getattr(layer, "heal_entry", None)):
                    await layer.heal_entry(path)
                return
            res = await layer.heal_file(path)
        except FopError as e:
            report["failed"].append({"path": path, "error": str(e)})
            return
        key = "skipped" if res.get("skipped") else "healed"
        report[key].append({"path": path,
                            "bricks": res.get("healed", [])})
        if key == "healed" and res.get("healed"):
            # the index sweep already announces its completions; the
            # full sweep repairs bricks with no pending record and must
            # show on the same event stream
            from ..core.events import gf_event

            gf_event("HEAL_COMPLETE", path=path,
                     bricks=res.get("healed", []))

    sem = asyncio.Semaphore(max(1, max_heals))
    # STREAMING dispatch, not collect-then-heal: file heals start while
    # the walk is still running (a multi-million-file namespace must
    # not buffer O(files) jobs — and a walk error must not zero out
    # heals already in flight), with the task backlog bounded so the
    # pending set stays O(max_heals)
    pending: set[asyncio.Task] = set()
    backlog = max(4, 2 * max(1, max_heals))

    async def one_file(layer, path: str) -> None:
        async with sem:
            await one(layer, path, False)

    async def walk(path: str) -> None:
        for layer in layers:  # directories exist in every group
            await one(layer, path, True)
        for name, ia in await client.listdir_with_stat(path):
            child = path.rstrip("/") + "/" + name
            if ia is not None and ia.is_dir():
                await walk(child)
            else:
                for layer in await owners(child):
                    t = asyncio.ensure_future(one_file(layer, child))
                    pending.add(t)
                    t.add_done_callback(pending.discard)
                while len(pending) > backlog:
                    await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED)

    try:
        await walk("/")
    finally:
        if pending:  # drain in-flight heals even when the walk errors
            await asyncio.gather(*pending, return_exceptions=True)
    return report


async def crawl_once(client, max_heals: int = 1,
                     wait_qlength: int = 1024) -> dict:
    """One full index sweep; returns a heal report.

    ``max_heals`` concurrent file heals (cluster/disperse
    shd-max-threads: the reference scales healer threads); entries past
    ``max_heals + wait_qlength`` defer to the next sweep
    (heal-wait-queue-length: bound the in-memory heal backlog)."""
    report = {"healed": [], "skipped": [], "failed": [], "pruned": [],
              "deferred": 0}
    sem = asyncio.Semaphore(max(1, max_heals))
    for layer in _heal_layers(client.graph):
        pending = await list_pending(layer)
        if pending:
            # events.h EVENT_HEAL_START: a sweep found damage to repair
            # (paired with the per-file HEAL_COMPLETE below)
            from ..core.events import gf_event

            gf_event("HEAL_START", layer=layer.name,
                     pending=len(pending))
        cap = max(1, max_heals) + max(0, wait_qlength)
        items = list(pending.items())
        if len(items) > cap:
            report["deferred"] += len(items) - cap
            items = items[:cap]
        tasks = []
        for hexgfid, holders in items:
            async def one(hexgfid=hexgfid, holders=holders,
                          layer=layer) -> None:
                async with sem:
                    gfid = bytes.fromhex(hexgfid)
                    path = await _resolve(layer, gfid)
                    if path is None:
                        # object is gone everywhere: stale entry, prune
                        for child in holders:
                            try:
                                await child.setxattr(
                                    Loc("/"),
                                    {XA_INDEX_PRUNE: hexgfid.encode()})
                            except FopError:
                                pass
                        report["pruned"].append(hexgfid)
                        return
                    try:
                        ia, _ = await layer.lookup(Loc(path))
                        if ia.ia_type is IAType.DIR and \
                                callable(getattr(layer, "heal_entry",
                                                 None)):
                            await layer.heal_entry(path)
                            res = {"healed": [], "skipped": False}
                        else:
                            res = await layer.heal_file(path)
                    except FopError as e:
                        report["failed"].append({"path": path,
                                                 "error": str(e)})
                        return
                    key = "skipped" if res.get("skipped") else "healed"
                    report[key].append({"path": path, "gfid": hexgfid,
                                        "bricks": res.get("healed", [])})
                    if key == "healed":
                        from ..core.events import gf_event

                        gf_event("HEAL_COMPLETE", path=path,
                                 gfid=hexgfid,
                                 bricks=res.get("healed", []))

            tasks.append(asyncio.ensure_future(one()))
        if tasks:
            await asyncio.gather(*tasks)
    return report


async def gather_heal_info(client) -> dict:
    """``volume heal <v> info``: pending entries with per-file status
    (heal info via the index, not a volume walk — glfs-heal.c analog)."""
    out = []
    for layer in _heal_layers(client.graph):
        pending = await list_pending(layer)
        for hexgfid in pending:
            gfid = bytes.fromhex(hexgfid)
            path = await _resolve(layer, gfid)
            entry = {"gfid": hexgfid, "path": path, "layer": layer.name}
            if path is not None:
                try:
                    info = await layer.heal_info(Loc(path))
                    entry["bad_bricks"] = info["bad"]
                    entry["dirty"] = info.get("dirty", False)
                except FopError as e:
                    entry["error"] = str(e)
            out.append(entry)
    return {"entries": out, "count": len(out)}


class SelfHealDaemon:
    """Periodic index healer over one mounted client graph."""

    def __init__(self, client, interval: float = 10.0,
                 max_heals: int = 1, wait_qlength: int = 1024):
        self.client = client
        self.interval = interval
        self.max_heals = max_heals
        self.wait_qlength = wait_qlength
        self.sweeps = 0
        self.last_report: dict = {}
        self._task: asyncio.Task | None = None
        self._wake = asyncio.Event()

    async def run(self) -> None:
        while True:
            # clear BEFORE the sweep: a poke() that lands mid-sweep must
            # not be lost — it means damage this sweep may have missed
            self._wake.clear()
            try:
                self.last_report = await crawl_once(
                    self.client, self.max_heals, self.wait_qlength)
            except Exception as e:  # a sweep must never kill the daemon
                log.error(1, "shd sweep failed: %r", e)
            self.sweeps += 1
            try:
                await asyncio.wait_for(self._wake.wait(), self.interval)
            except asyncio.TimeoutError:
                pass

    def poke(self) -> None:
        """Trigger an immediate sweep (heal <v> full analog)."""
        self._wake.set()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


async def _amain(args) -> None:
    from ..core import flight, history, slo
    from ..core.metrics import register_build_info
    from .glusterd import mount_volume

    flight.set_role("shd")
    register_build_info("shd")
    history.arm()
    host, _, port = args.glusterd.rpartition(":")
    client = None
    while client is None:
        try:
            client = await mount_volume(host, int(port), args.volname)
        except Exception as e:
            log.warning(2, "shd mount %s failed (%r), retrying", args.volname, e)
            await asyncio.sleep(1.0)
    if args.statefile:
        with open(args.statefile + ".tmp", "w") as f:
            json.dump({"pid": os.getpid(), "volume": args.volname}, f)
        os.replace(args.statefile + ".tmp", args.statefile)
        # incident capture door for a daemon with no inbound RPC:
        # SIGUSR2 writes the flight bundle beside the statefile, where
        # glusterd's incident fan-out polls for it
        flight.arm_signal_capture(args.statefile + ".incident")
        # alerts door, same shape: the local SLO engine's status is
        # mirrored beside the statefile on every sampler tick (only
        # once rules are configured), where glusterd's volume-alerts
        # fan-out reads it
        alerts_path = args.statefile + ".alerts"

        def _mirror_alerts() -> None:
            if not slo.ENGINE.rules:
                return
            try:
                with open(alerts_path + ".tmp", "w") as f:
                    json.dump(slo.ENGINE.status(), f, default=repr)
                os.replace(alerts_path + ".tmp", alerts_path)
            except OSError:
                pass

        history.add_tick_hook(_mirror_alerts)
    shd = SelfHealDaemon(client, args.interval,
                         args.max_heals, args.wait_qlength)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    shd.start()
    await stop.wait()
    await shd.stop()
    await client.unmount()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-shd")
    p.add_argument("--glusterd", required=True, help="host:port")
    p.add_argument("--volname", required=True)
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--max-heals", type=int, default=1)
    p.add_argument("--wait-qlength", type=int, default=1024)
    p.add_argument("--statefile", default="")
    args = p.parse_args(argv)
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
