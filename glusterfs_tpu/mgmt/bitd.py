"""Bit-rot daemon — the bitd signer + scrubber analog.

Reference: xlators/features/bit-rot/src/bitd (bit-rot.c signer,
bit-rot-scrub.c scrubber): one daemon per node signs quiescent objects
with a content checksum and periodically re-hashes them; a mismatch on
an object that has NOT changed since signing is silent disk corruption —
the object is quarantined (bad-file marker, enforced by the brick's
bit-rot-stub) and flagged for heal.

TPU-build shape: one worker per brick, talking to the brick over its
normal RPC port (any Layer works — tests drive in-process brick tops
directly).  Signing condition: no signature newer than mtime AND the
object has been quiet for ``signer-quiesce`` seconds.  Scrub condition:
a signature newer than mtime (content unchanged since signing) whose
hash no longer matches.  On corruption the worker also zeroes the
brick's cluster version xattr and raises its dirty marker, which drops
the brick out of the heal-source group and feeds the pending index —
the shd then rebuilds the object from the healthy bricks, and the
rewrite lifts the quarantine (stub writev path).
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import signal
import struct
import sys
import time

from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import Layer, Loc
from ..core import gflog
from ..features.bit_rot_stub import XA_BAD, XA_SIG

log = gflog.get_logger("bitd")

HASH_WINDOW = 1 << 20
# one default, referenced by glusterd's spawner and the argparse flag
DEFAULT_SCRUB_THROTTLE = 64 * (1 << 20)  # bytes/s


async def _release(layer: Layer, fd) -> None:
    rel = getattr(layer, "release", None)
    if rel is not None:
        try:
            await rel(fd)
        except Exception:
            pass


async def walk_files(layer: Layer, path: str = "/"):
    """Yield (path, iatt) for every regular file under path."""
    try:
        fd = await layer.opendir(Loc(path))
    except FopError:
        return
    try:
        entries = await layer.readdir(fd)
    except FopError:
        return
    finally:
        await _release(layer, fd)
    for name, _ in entries:
        child = (path.rstrip("/") + "/" + name)
        try:
            ia = await layer.stat(Loc(child))
        except FopError:
            continue
        if ia.ia_type is IAType.DIR:
            async for item in walk_files(layer, child):
                yield item
        elif ia.ia_type is IAType.REG:
            yield child, ia


async def content_hash(layer: Layer, path: str, gfid: bytes,
                       size: int) -> str:
    """sha256 of the object through ONE held fd (an anonymous fd per
    chunk would open/leak an OS fd per chunk brick-side)."""
    h = hashlib.sha256()
    fd = await layer.open(Loc(path, gfid=gfid), os.O_RDONLY)
    try:
        off = 0
        while off < size:
            chunk = await layer.readv(fd, min(HASH_WINDOW, size - off), off)
            if not chunk:
                break
            h.update(chunk)
            off += len(chunk)
    finally:
        await _release(layer, fd)
    return h.hexdigest()


# Scrub bandwidth cap: the shared throttle-tbf analog now lives in
# svcutil (the QoS plane uses the same bucket); re-exported here so
# `bitd.TokenBucket` keeps resolving for existing callers.
from .svcutil import TokenBucket  # noqa: E402


class BrickBitd:
    """Signer + scrubber over one brick graph top."""

    def __init__(self, layer: Layer, quiesce: float = 120.0,
                 throttle: float = DEFAULT_SCRUB_THROTTLE):
        self.layer = layer
        self.quiesce = quiesce
        self.tbf = TokenBucket(throttle)
        self.signed = 0
        self.scrubbed = 0
        self.corrupted: list[str] = []

    async def _xattrs(self, path: str) -> dict:
        try:
            return await self.layer.getxattr(Loc(path), None)
        except FopError:
            return {}

    def _sig(self, xattrs: dict) -> dict | None:
        raw = xattrs.get(XA_SIG)
        if not raw:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None

    async def sign_pass(self) -> int:
        """Sign quiescent objects lacking a current signature
        (bit-rot.c br_sign_object)."""
        n = 0
        now = time.time()
        async for path, ia in walk_files(self.layer):
            x = await self._xattrs(path)
            if XA_BAD in x:
                continue
            sig = self._sig(x)
            if sig is not None and sig.get("ts", 0) >= ia.mtime:
                continue  # signature current
            if now - ia.mtime < self.quiesce:
                continue  # still hot; sign once it goes quiet
            await self.tbf.take(ia.size)  # signer paces like the scrubber
            try:
                digest = await content_hash(self.layer, path, ia.gfid,
                                            ia.size)
                # re-stat: a write that landed mid-hash makes the digest
                # torn — signing it would fabricate corruption later
                ia2 = await self.layer.stat(Loc(path))
                if ia2.mtime != ia.mtime or ia2.size != ia.size:
                    continue
                await self.layer.setxattr(Loc(path), {XA_SIG: json.dumps(
                    {"sha256": digest, "ts": time.time()}).encode()})
                n += 1
            except FopError:
                continue
        self.signed += n
        return n

    async def scrub_pass(self) -> list[str]:
        """Re-hash signed, unmodified objects; mismatch = silent disk
        corruption -> quarantine + heal trigger (bit-rot-scrub.c
        br_scrubber_scrub_begin)."""
        bad: list[str] = []
        async for path, ia in walk_files(self.layer):
            x = await self._xattrs(path)
            if XA_BAD in x:
                continue
            sig = self._sig(x)
            if sig is None or sig.get("ts", 0) < ia.mtime:
                continue  # changed since signing: the signer's job
            await self.tbf.take(ia.size)  # throttle-tbf pacing
            try:
                digest = await content_hash(self.layer, path, ia.gfid,
                                            ia.size)
                # a write that landed mid-hash is a legitimate change,
                # not corruption — quarantining it would zero a healthy
                # brick's version
                ia2 = await self.layer.stat(Loc(path))
            except FopError:
                continue
            if ia2.mtime != ia.mtime or ia2.size != ia.size:
                continue
            self.scrubbed += 1
            if digest == sig.get("sha256"):
                continue
            marks: dict = {XA_BAD: b"1"}
            # feed the heal machinery: this brick must drop out of the
            # source group (zero version) and land in the pending index
            # (raise dirty)
            for ns in ("trusted.ec.", "trusted.afr."):
                # any counter in the namespace marks this as a cluster
                # object; with a delayed post-op the version xattr may
                # not exist YET (only the pre-op dirty does) — zero it
                # anyway so this brick can never join the source group
                if any(k.startswith(ns) for k in x):
                    marks[ns + "version"] = struct.pack(">QQ", 0, 0)
                    marks[ns + "dirty"] = struct.pack(">QQ", 1, 0)
            try:
                await self.layer.setxattr(Loc(path), marks)
            except FopError:
                continue
            bad.append(path)
            log.warning(3, "CORRUPTION on %s (%s)", path,
                        self.layer.name)
            from ..core.events import gf_event

            gf_event("BITROT_BAD_FILE", path=path,
                     brick=self.layer.name)
        self.corrupted += bad
        return bad

    def status(self) -> dict:
        return {"signed": self.signed, "scrubbed": self.scrubbed,
                "corrupted": list(self.corrupted)}


async def _amain(args) -> None:
    from ..protocol.client import ClientLayer
    from . import svcutil

    layers = []
    for spec in args.bricks.split(","):
        name, port = spec.rsplit(":", 1)
        layers.append(ClientLayer(f"bitd-{name}", svcutil.client_opts(
            args, "GFTPU_BITD", args.host, int(port), name)))
    for l in layers:
        await l.init()
    # the connect loop runs in the background; a pass against
    # unconnected bricks would silently no-op on ENOTCONN
    deadline = asyncio.get_running_loop().time() + 30
    while asyncio.get_running_loop().time() < deadline:
        if all(l.connected for l in layers):
            break
        await asyncio.sleep(0.1)
    workers = [BrickBitd(l, args.quiesce, args.scrub_throttle)
               for l in layers]

    async def loop_fn():
        while True:
            for w in workers:
                try:
                    await w.sign_pass()
                    if not args.no_scrub:
                        # features.scrub off/pause stops SCRUBBING only;
                        # signing continues so the pause window stays
                        # verifiable once scrubbing resumes
                        await w.scrub_pass()
                except Exception as e:
                    log.error(4, "bitd pass failed: %r", e)
            if args.statusfile:
                tmp = args.statusfile + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"pid": os.getpid(),
                               "bricks": {w.layer.name: w.status()
                                          for w in workers}}, f)
                os.replace(tmp, args.statusfile)
            await asyncio.sleep(args.scrub_interval)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    task = loop.create_task(loop_fn())
    await stop.wait()
    task.cancel()
    for l in layers:
        await l.fini()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-bitd")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--bricks", required=True,
                   help="comma-separated brickname:port")
    from . import svcutil
    svcutil.add_ssl_args(p)
    p.add_argument("--quiesce", type=float, default=120.0)
    p.add_argument("--no-scrub", action="store_true",
                   help="sign only (features.scrub off/pause)")
    p.add_argument("--scrub-interval", type=float, default=60.0)
    p.add_argument("--scrub-throttle", type=float,
                   default=DEFAULT_SCRUB_THROTTLE,
                   help="scrub bandwidth cap, bytes/s (0 = unlimited)")
    p.add_argument("--statusfile", default="")
    args = p.parse_args(argv)
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
