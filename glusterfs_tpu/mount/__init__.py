"""Kernel-facing access layers (reference xlators/mount/)."""
