"""FUSE kernel wire protocol: opcodes + struct codecs.

The reference talks to ``/dev/fuse`` raw (it does NOT use libfuse —
xlators/mount/fuse/src/fuse-bridge.c:6096 reads and decodes kernel
messages itself, with struct layouts vendored from the kernel headers in
contrib/fuse-include).  This module is the same idea for the TPU build:
the layouts below are the public Linux UAPI (``include/uapi/linux/fuse.h``)
for protocol 7.31, the minor we negotiate — fixed-version structs keep
every codec a static ``struct`` format string.

Only the subset of opcodes the bridge serves is defined; everything else
gets ENOSYS and the kernel stops sending it.
"""

from __future__ import annotations

import struct

#: Protocol version we speak (kernel adapts down during INIT).
FUSE_KERNEL_VERSION = 7
FUSE_KERNEL_MINOR_VERSION = 31

# -- opcodes (uapi fuse.h enum fuse_opcode) -------------------------------
LOOKUP = 1
FORGET = 2
GETATTR = 3
SETATTR = 4
READLINK = 5
SYMLINK = 6
MKNOD = 8
MKDIR = 9
UNLINK = 10
RMDIR = 11
RENAME = 12
LINK = 13
OPEN = 14
READ = 15
WRITE = 16
STATFS = 17
RELEASE = 18
FSYNC = 20
SETXATTR = 21
GETXATTR = 22
LISTXATTR = 23
REMOVEXATTR = 24
FLUSH = 25
INIT = 26
OPENDIR = 27
READDIR = 28
RELEASEDIR = 29
FSYNCDIR = 30
ACCESS = 34
CREATE = 35
INTERRUPT = 36
DESTROY = 38
BATCH_FORGET = 42
FALLOCATE = 43
READDIRPLUS = 44
RENAME2 = 45
LSEEK = 46

_OPCODES = (
    "LOOKUP", "FORGET", "GETATTR", "SETATTR", "READLINK", "SYMLINK",
    "MKNOD", "MKDIR", "UNLINK", "RMDIR", "RENAME", "LINK", "OPEN",
    "READ", "WRITE", "STATFS", "RELEASE", "FSYNC", "SETXATTR",
    "GETXATTR", "LISTXATTR", "REMOVEXATTR", "FLUSH", "INIT", "OPENDIR",
    "READDIR", "RELEASEDIR", "FSYNCDIR", "ACCESS", "CREATE",
    "INTERRUPT", "DESTROY", "BATCH_FORGET", "FALLOCATE", "READDIRPLUS",
    "RENAME2", "LSEEK",
)
OPCODE_NAMES = {globals()[k]: k for k in _OPCODES}

# -- INIT flags we care about ---------------------------------------------
FUSE_ASYNC_READ = 1 << 0
FUSE_BIG_WRITES = 1 << 5

# open_out.open_flags bits (include/uapi/linux/fuse.h)
FOPEN_DIRECT_IO = 1 << 0
FOPEN_KEEP_CACHE = 1 << 1
FUSE_DO_READDIRPLUS = 1 << 13
FUSE_READDIRPLUS_AUTO = 1 << 14
FUSE_PARALLEL_DIROPS = 1 << 18
FUSE_WRITEBACK_CACHE = 1 << 16
FUSE_MAX_PAGES = 1 << 22

# -- SETATTR valid bits ----------------------------------------------------
FATTR_MODE = 1 << 0
FATTR_UID = 1 << 1
FATTR_GID = 1 << 2
FATTR_SIZE = 1 << 3
FATTR_ATIME = 1 << 4
FATTR_MTIME = 1 << 5
FATTR_FH = 1 << 6
FATTR_ATIME_NOW = 1 << 7
FATTR_MTIME_NOW = 1 << 8

# -- notifications (reverse path: daemon -> kernel) ------------------------
NOTIFY_INVAL_INODE = 2
NOTIFY_INVAL_ENTRY = 3

# -- struct codecs ---------------------------------------------------------
# fuse_in_header: len, opcode, unique, nodeid, uid, gid, pid, padding
IN_HEADER = struct.Struct("<IIQQIIII")
# fuse_out_header: len, error, unique
OUT_HEADER = struct.Struct("<IiQ")
# fuse_attr: ino size blocks atime mtime ctime atimensec mtimensec
#            ctimensec mode nlink uid gid rdev blksize flags
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")
# fuse_entry_out prefix: nodeid generation entry_valid attr_valid
#                        entry_valid_nsec attr_valid_nsec  (+ ATTR)
ENTRY_OUT = struct.Struct("<QQQQII")
# fuse_attr_out prefix: attr_valid attr_valid_nsec dummy  (+ ATTR)
ATTR_OUT = struct.Struct("<QII")
# fuse_init_in prefix (7.36+ sends more; we parse the stable prefix)
INIT_IN = struct.Struct("<IIII")  # major minor max_readahead flags
# fuse_init_out (7.23+ layout, 64 bytes total with trailing unused[7])
INIT_OUT = struct.Struct("<IIIIHHIIHHI")  # major minor max_readahead flags
#                                   max_background congestion max_write
#                                   time_gran max_pages map_alignment flags2
INIT_OUT_PAD = 28
# fuse_getattr_in: getattr_flags dummy fh
GETATTR_IN = struct.Struct("<IIQ")
# fuse_setattr_in: valid padding fh size lock_owner atime mtime ctime
#                  atimensec mtimensec ctimensec mode unused4 uid gid unused5
SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")
# fuse_open_in: flags open_flags
OPEN_IN = struct.Struct("<II")
# fuse_open_out: fh open_flags padding
OPEN_OUT = struct.Struct("<QII")
# fuse_create_in: flags mode umask open_flags  (+ name)
CREATE_IN = struct.Struct("<IIII")
# fuse_mkdir_in: mode umask
MKDIR_IN = struct.Struct("<II")
# fuse_mknod_in: mode rdev umask padding
MKNOD_IN = struct.Struct("<IIII")
# fuse_rename_in / fuse_rename2_in
RENAME_IN = struct.Struct("<Q")
RENAME2_IN = struct.Struct("<QII")
# fuse_link_in: oldnodeid
LINK_IN = struct.Struct("<Q")
# fuse_read_in: fh offset size read_flags lock_owner flags padding
READ_IN = struct.Struct("<QQIIQII")
# fuse_write_in: fh offset size write_flags lock_owner flags padding
WRITE_IN = struct.Struct("<QQIIQII")
# fuse_write_out: size padding
WRITE_OUT = struct.Struct("<II")
# fuse_release_in: fh flags release_flags lock_owner
RELEASE_IN = struct.Struct("<QIIQ")
# fuse_flush_in: fh unused padding lock_owner
FLUSH_IN = struct.Struct("<QIIQ")
# fuse_fsync_in: fh fsync_flags padding
FSYNC_IN = struct.Struct("<QII")
# fuse_access_in: mask padding
ACCESS_IN = struct.Struct("<II")
# fuse_getxattr_in: size padding   (also used for listxattr)
GETXATTR_IN = struct.Struct("<II")
GETXATTR_OUT = struct.Struct("<II")  # size padding
# fuse_setxattr_in (pre-SETXATTR_EXT): size flags
SETXATTR_IN = struct.Struct("<II")
# fuse_forget_in: nlookup
FORGET_IN = struct.Struct("<Q")
# fuse_batch_forget_in: count dummy  (+ count * {nodeid nlookup})
BATCH_FORGET_IN = struct.Struct("<II")
FORGET_ONE = struct.Struct("<QQ")
# fuse_interrupt_in: unique
INTERRUPT_IN = struct.Struct("<Q")
# fuse_fallocate_in: fh offset length mode padding
FALLOCATE_IN = struct.Struct("<QQQII")
# fuse_lseek_in: fh offset whence padding ; fuse_lseek_out: offset
LSEEK_IN = struct.Struct("<QQII")
LSEEK_OUT = struct.Struct("<Q")
# fuse_kstatfs: blocks bfree bavail files ffree bsize namelen frsize
#               padding spare[6]
KSTATFS = struct.Struct("<QQQQQIIII24x")
# fuse_dirent prefix: ino off namelen type  (+ name, 8-aligned)
DIRENT = struct.Struct("<QQII")
# fuse_notify_inval_inode_out: ino off len
NOTIFY_INVAL_INODE_OUT = struct.Struct("<Qqq")
# fuse_notify_inval_entry_out: parent namelen padding (+ name NUL)
NOTIFY_INVAL_ENTRY_OUT = struct.Struct("<QII")


def pack_dirent(ino: int, off: int, dtype: int, name: bytes) -> bytes:
    """One fuse_dirent, name 8-byte aligned (uapi FUSE_DIRENT_ALIGN)."""
    ent = DIRENT.pack(ino, off, len(name), dtype) + name
    pad = (-len(ent)) % 8
    return ent + b"\0" * pad


def pack_direntplus(entry_out: bytes, ino: int, off: int, dtype: int,
                    name: bytes) -> bytes:
    """One fuse_direntplus = fuse_entry_out + aligned dirent."""
    return entry_out + pack_dirent(ino, off, dtype, name)
