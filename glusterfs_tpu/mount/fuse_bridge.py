"""FUSE bridge: a real kernel mount over the client layer graph.

Reference: xlators/mount/fuse/src/fuse-bridge.c — glusterfs reads
``/dev/fuse`` raw (fuse_thread_proc, fuse-bridge.c:6096), decodes each
kernel request, resolves it against the inode table and winds it down
the client graph; replies are written back to the fd.  The TPU build
keeps that shape with idiomatic mechanisms: the device fd joins the
asyncio loop via ``add_reader`` (instead of a reader thread +
``gf_async``), every kernel request becomes a task awaiting the graph
top's async fop (instead of ``STACK_WIND`` CPS), and mounting is a
direct ``mount(2)`` of fstype ``fuse`` (the reference vendors
contrib/fuse-lib/mount.c for the same job).

Nodeid management mirrors fuse-bridge's inode table: kernel nodeids map
to (gfid, parent, name); paths are computed by walking the parent
chain so a directory rename never leaves stale child paths.  Hardlinks
share a nodeid via the gfid index, exactly as inodes do.

Run as a daemon:  ``gftpu-fuse --server H:P --volume vol /mnt``
(the ``glusterfs --volfile-server=H --volfile-id=vol /mnt`` analog).
"""

from __future__ import annotations

import argparse
import asyncio
import ctypes
import errno
import os
import queue
import stat as stat_mod
import sys
import threading
import time

from ..api.glfs import Client
from ..core import gflog, tracing
from ..core.fops import FopError
from ..core.iatt import IAType, Iatt
from ..core.layer import FdObj, Loc
from ..rpc.wire import SGBuf
from . import fuse_proto as fp

log = gflog.get_logger("fuse")

_libc = ctypes.CDLL(None, use_errno=True)

MS_NOSUID = 0x2
MS_NODEV = 0x4
MNT_DETACH = 0x2

_MAX_WRITE = 1 << 20
_READ_BUF = _MAX_WRITE + (64 << 10)

_DTYPE = {IAType.REG: 8, IAType.DIR: 4, IAType.LNK: 10, IAType.BLK: 6,
          IAType.CHR: 2, IAType.FIFO: 1, IAType.SOCK: 12}


def _gfid_ino(gfid: bytes) -> int:
    """Stable st_ino from a gfid (reference gf_fuse_nodeid semantics)."""
    return int.from_bytes(gfid[:8], "big") ^ int.from_bytes(
        gfid[8:], "big") or 1


class _Node:
    """Kernel nodeid -> identity (the fuse inode-table entry)."""

    __slots__ = ("nodeid", "gfid", "parent", "name", "nlookup", "is_dir")

    def __init__(self, nodeid: int, gfid: bytes, parent: int, name: str,
                 is_dir: bool):
        self.nodeid = nodeid
        self.gfid = gfid
        self.parent = parent
        self.name = name
        self.nlookup = 0
        self.is_dir = is_dir


class FuseBridge:
    """Serve one mountpoint from one mounted :class:`api.glfs.Client`."""

    def __init__(self, client: Client, mountpoint: str,
                 volname: str = "gftpu", keep_cache: bool = False,
                 writeback_cache: bool = True,
                 reader_split: bool = True, max_inflight: int = 64):
        self.client = client
        self.mountpoint = os.path.abspath(mountpoint)
        self.volname = volname
        # reader/writer-split event plane (ISSUE 7; the reference's
        # fuse_thread_proc reader thread + --reader-thread-count): a
        # dedicated thread blocks in read(2) on /dev/fuse and hands
        # requests to the loop through a bounded inflight window, and
        # a separate writer thread ships replies with writev(2) — so a
        # slow fop never stalls kernel request intake, and a blocking
        # device write never stalls the event loop.  Off = the legacy
        # single-loop add_reader plane (--no-reader-split).
        self.reader_split = reader_split
        self.max_inflight = max(1, int(max_inflight))
        self._intake: threading.BoundedSemaphore | None = None
        self._wq: "queue.SimpleQueue | None" = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._io_threads: list[threading.Thread] = []
        # split plane: each thread owns (and closes) its own fd —
        # teardown must NOT close a device fd another thread may be
        # blocked in read(2)/writev(2) on, or the number could be
        # recycled under the in-flight syscall
        self._rfd = -1
        self._wfd = -1
        # --fopen-keep-cache (fuse-bridge.c:1617-1635): let the kernel
        # keep a file's page cache across open()s.  Off by default like
        # the reference: safe for single-writer mounts, stale for
        # multi-client files unless upcall invalidation is on
        self.keep_cache = keep_cache
        # FUSE_WRITEBACK_CACHE (fuse-bridge.c kernel-writeback-cache +
        # INIT tuning, :5178): the kernel aggregates dirty pages and
        # sends up-to-max_write writes instead of one request per
        # ≤128KiB chunk, and absorbs rewrites entirely.  Default ON —
        # a mount is typically this machine's one writer; multi-mount
        # workloads needing write-through turn it off
        self.writeback_cache = writeback_cache
        self.dev_fd = -1
        self.proto_minor = 0
        self._nodes: dict[int, _Node] = {}
        self._by_gfid: dict[bytes, int] = {}
        self._next_nodeid = 2
        self._fhs: dict[int, FdObj] = {}
        self._next_fh = 1
        self._tasks: set[asyncio.Task] = set()
        self._closed = asyncio.Event()
        root = _Node(1, b"\x00" * 15 + b"\x01", 0, "/", True)
        root.nlookup = 1
        self._nodes[1] = root
        self._by_gfid[root.gfid] = 1

    # -- mount / unmount ---------------------------------------------------

    def mount(self) -> None:
        # O_NONBLOCK on BOTH planes: the legacy plane needs it for
        # add_reader, and the split reader needs select()+nonblocking
        # reads — a reader parked in a blocking read(2) on /dev/fuse
        # is NOT woken by an external unmount on every kernel (4.4
        # leaves it parked forever), while select sees the dead
        # connection as readable and the read then fails ENODEV
        self.dev_fd = os.open("/dev/fuse", os.O_RDWR | os.O_NONBLOCK)
        # default_permissions: the kernel enforces mode/uid/gid from the
        # attrs we return — without it, allow_other would let any local
        # user bypass file modes entirely (the bridge runs as root and
        # winds fops with its own identity)
        data = os.fsencode((f"fd={self.dev_fd},rootmode=40755,"
                f"user_id={os.getuid()},group_id={os.getgid()},"
                f"allow_other,default_permissions"))
        ret = _libc.mount(os.fsencode(self.volname),
                          os.fsencode(self.mountpoint), b"fuse",
                          MS_NOSUID | MS_NODEV, data)
        if ret != 0:
            err = ctypes.get_errno()
            os.close(self.dev_fd)
            self.dev_fd = -1
            raise OSError(err, f"mount(2) {self.mountpoint}: "
                               f"{os.strerror(err)}")
        self._loop = asyncio.get_running_loop()
        if self.reader_split:
            self._rfd = self.dev_fd
            self._wfd = os.dup(self.dev_fd)
            self._intake = threading.BoundedSemaphore(self.max_inflight)
            self._wq = queue.SimpleQueue()
            writer = threading.Thread(target=self._writer_main,
                                      name="fuse-writer", daemon=True)
            reader = threading.Thread(target=self._reader_main,
                                      name="fuse-reader", daemon=True)
            self._io_threads = [reader, writer]
            writer.start()
            reader.start()
        else:
            self._loop.add_reader(self.dev_fd, self._readable)
        log.info(1, "mounted %s on %s (%s plane)", self.volname,
                 self.mountpoint,
                 "split" if self.reader_split else "loop")

    async def unmount(self) -> None:
        if self.dev_fd < 0:
            return
        _libc.umount2(os.fsencode(self.mountpoint), MNT_DETACH)
        self._teardown()
        tasks = list(self._tasks)
        for t in tasks:
            t.cancel()
        if tasks:
            # drain before releasing: a mid-read task still holds its
            # brick fd; closing it under the task races fd reuse
            await asyncio.gather(*tasks, return_exceptions=True)
        for fd in self._fhs.values():
            try:
                await self.client.graph.top.release(fd)
            except Exception:
                pass
        self._fhs.clear()
        log.info(2, "unmounted %s", self.mountpoint)

    def _teardown(self) -> None:
        # dev_fd is the split plane's cross-context sentinel: loop-side
        # teardown writes -1, the reader/writer threads poll it to
        # stand down (each thread owns and closes its ACTUAL fd).  The
        # contract is a declared graft-race ownership row
        # (tables.OWNERSHIP["...FuseBridge.dev_fd"], GL09).
        if self.dev_fd < 0:
            return
        if self.reader_split:
            # the reader/writer threads own their fds: umount2 (already
            # issued, or issued by the kernel) aborts the connection, the
            # reader's blocked read returns ENODEV and it closes _rfd
            # itself; the sentinel below has the writer close _wfd
            self.dev_fd = -1
        else:
            try:
                asyncio.get_running_loop().remove_reader(self.dev_fd)
            except Exception:
                pass
            try:
                os.close(self.dev_fd)
            except OSError:
                pass
            self.dev_fd = -1
        if self._wq is not None:
            self._wq.put(None)  # writer thread: drain and exit
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()

    # -- device read loop --------------------------------------------------

    def _readable(self) -> None:
        while self.dev_fd >= 0:
            try:
                buf = os.read(self.dev_fd, _READ_BUF)
            except BlockingIOError:
                return
            except OSError as e:
                # ENOENT: a queued request was aborted before we read it
                # (libfuse and fuse_thread_proc both retry on it)
                if e.errno in (errno.EINTR, errno.ENOENT):
                    continue
                # ENODEV: the kernel unmounted us (external umount)
                self._teardown()
                return
            t = asyncio.get_running_loop().create_task(self._handle(buf))
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)

    # -- split plane: reader + writer threads ------------------------------

    def _reader_main(self) -> None:
        """Dedicated /dev/fuse intake (fuse_thread_proc): the device
        read runs off the event loop, bounded by the inflight window so
        a burst of kernel requests queues in the KERNEL (which has its
        own congestion control) instead of ballooning bridge memory.
        select()+nonblocking read instead of a blocking read: an
        external unmount makes the dead connection readable (POLLERR),
        and the read then surfaces the ENODEV a parked blocking read
        would never see on older kernels."""
        import select as select_mod

        loop = self._loop
        try:
            while True:
                # bounded handoff: don't read request N+max_inflight
                # until an earlier one answered.  Timeout polls for
                # teardown — a parked reader must notice the unmount
                if not self._intake.acquire(timeout=0.5):
                    if self.dev_fd < 0:
                        return
                    continue
                buf = None
                while buf is None:
                    if self.dev_fd < 0:
                        try:
                            self._intake.release()
                        except ValueError:
                            pass
                        return
                    try:
                        ready, _, _ = select_mod.select(
                            [self._rfd], [], [self._rfd], 0.5)
                    except (OSError, ValueError):
                        ready = [self._rfd]  # fd dying: let read say so
                    if not ready:
                        continue
                    try:
                        buf = os.read(self._rfd, _READ_BUF)
                    except BlockingIOError:
                        continue
                    except OSError as e:
                        if e.errno in (errno.EINTR, errno.ENOENT):
                            continue  # aborted request: retry, slot held
                        # ENODEV: unmounted under us; EBADF: teardown
                        try:
                            self._intake.release()
                        except ValueError:
                            pass
                        try:
                            loop.call_soon_threadsafe(self._teardown)
                        except RuntimeError:
                            pass
                        return
                try:
                    loop.call_soon_threadsafe(self._spawn_split, buf)
                except RuntimeError:  # loop gone: process exiting
                    return
        finally:
            try:
                os.close(self._rfd)  # the reader owns the read fd
            except OSError:
                pass

    def _spawn_split(self, buf: bytes) -> None:
        t = self._loop.create_task(self._handle_split(buf))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _handle_split(self, buf: bytes) -> None:
        try:
            await self._handle(buf)
        finally:
            try:
                self._intake.release()
            except ValueError:
                pass

    def _writer_main(self) -> None:
        """Dedicated reply writer: one writev(2) per reply (atomic at
        the device), so a momentarily-blocking device write never
        stalls the event loop or any other fop's reply."""
        try:
            while True:
                item = self._wq.get()
                if item is None:
                    return
                hdr, data = item
                if self.dev_fd < 0:
                    continue  # drain remaining items to the sentinel
                try:
                    if isinstance(data, SGBuf):
                        os.writev(self._wfd, (hdr, *data.segments))
                    else:
                        os.writev(self._wfd, (hdr, data))
                except OSError:
                    pass  # request raced an unmount/interrupt
        finally:
            try:
                os.close(self._wfd)  # the writer owns its dup
            except OSError:
                pass

    def _reply(self, unique: int, data: bytes = b"", error: int = 0) -> None:
        if self.dev_fd < 0:
            return
        hdr = fp.OUT_HEADER.pack(fp.OUT_HEADER.size + len(data),
                                 -error, unique)
        if self._wq is not None:
            # split plane: replies ship from the writer thread; the
            # payload is a view into a reply frame the finished fop
            # task no longer mutates, so the handoff is copy-free
            self._wq.put((hdr, data))
            return
        try:
            # vectored: read payloads arrive as memoryviews into the
            # RPC frame (wire blob lane) or as scatter-gather segment
            # vectors (SGBuf) — writev ships them to the kernel without
            # a concat copy (and bytes+memoryview would TypeError
            # anyway)
            if isinstance(data, SGBuf):
                os.writev(self.dev_fd, (hdr, *data.segments))
            else:
                os.writev(self.dev_fd, (hdr, data))
        except OSError:
            pass  # request raced an unmount/interrupt

    async def _handle(self, buf: bytes) -> None:
        (_, opcode, unique, nodeid, *_rest) = fp.IN_HEADER.unpack_from(buf)
        payload = buf[fp.IN_HEADER.size:]
        if opcode in (fp.FORGET, fp.BATCH_FORGET):
            self._op_forget(opcode, nodeid, payload)
            return  # forget has no reply
        if opcode == fp.INTERRUPT:
            return  # best-effort: fops run to completion
        handler = self._HANDLERS.get(opcode)
        if tracing.ENABLED:
            # one trace id per KERNEL request (the fuse analog of the
            # gateway's per-HTTP-request mint): every fop this request
            # winds through the graph — and every brick span re-armed
            # from the wire trace element — joins the same waterfall
            tracing.arm(tracing.new_trace_id())
        # a request that never gets a reply wedges its caller in an
        # unkillable D-state: whatever goes wrong, ALWAYS answer
        data, error = b"", 0
        try:
            if handler is None:
                raise FopError(errno.ENOSYS,
                               fp.OPCODE_NAMES.get(opcode, str(opcode)))
            data = await handler(self, nodeid, payload) or b""
        except FopError as e:
            error = e.err or errno.EIO
        except OSError as e:
            error = e.errno or errno.EIO
        except asyncio.CancelledError:
            error = errno.EINTR
        except Exception:
            error = errno.EIO
            try:
                import traceback

                log.warning(3, "fuse %s failed: %s",
                            fp.OPCODE_NAMES.get(opcode, opcode),
                            traceback.format_exc(limit=5))
            except Exception:
                pass
        self._reply(unique, data, error)

    # -- node table --------------------------------------------------------

    def _node(self, nodeid: int) -> _Node:
        node = self._nodes.get(nodeid)
        if node is None:
            raise FopError(errno.ESTALE, f"nodeid {nodeid}")
        return node

    def _path(self, node: _Node) -> str:
        if node.nodeid == 1:
            return "/"
        parts: list[str] = []
        cur = node
        while cur.nodeid != 1:
            parts.append(cur.name)
            cur = self._node(cur.parent)
        return "/" + "/".join(reversed(parts))

    def _loc(self, node: _Node) -> Loc:
        parent = self._nodes.get(node.parent)
        return Loc(self._path(node), gfid=node.gfid,
                   parent=parent.gfid if parent else None)

    def _remember(self, parent: int, name: str, ia: Iatt) -> _Node:
        nodeid = self._by_gfid.get(ia.gfid)
        if nodeid is not None and nodeid in self._nodes:
            node = self._nodes[nodeid]
            node.parent, node.name = parent, name
        else:
            node = _Node(self._next_nodeid, ia.gfid, parent, name,
                         ia.is_dir())
            self._next_nodeid += 1
            self._nodes[node.nodeid] = node
            self._by_gfid[ia.gfid] = node.nodeid
        node.nlookup += 1
        return node

    def _op_forget(self, opcode: int, nodeid: int, payload: bytes) -> None:
        pairs = []
        if opcode == fp.FORGET:
            (nlookup,) = fp.FORGET_IN.unpack_from(payload)
            pairs.append((nodeid, nlookup))
        else:
            (count, _) = fp.BATCH_FORGET_IN.unpack_from(payload)
            off = fp.BATCH_FORGET_IN.size
            for _ in range(count):
                pairs.append(fp.FORGET_ONE.unpack_from(payload, off))
                off += fp.FORGET_ONE.size
        for nid, nlookup in pairs:
            node = self._nodes.get(nid)
            if node is None or nid == 1:
                continue
            node.nlookup -= nlookup
            if node.nlookup <= 0:
                self._nodes.pop(nid, None)
                if self._by_gfid.get(node.gfid) == nid:
                    self._by_gfid.pop(node.gfid, None)

    # -- attr conversion ---------------------------------------------------

    @staticmethod
    def _attr_bytes(ia: Iatt) -> bytes:
        type_bits = {IAType.REG: stat_mod.S_IFREG, IAType.DIR: stat_mod.S_IFDIR,
                     IAType.LNK: stat_mod.S_IFLNK, IAType.BLK: stat_mod.S_IFBLK,
                     IAType.CHR: stat_mod.S_IFCHR, IAType.FIFO: stat_mod.S_IFIFO,
                     IAType.SOCK: stat_mod.S_IFSOCK}.get(ia.ia_type, 0)
        return fp.ATTR.pack(
            _gfid_ino(ia.gfid), ia.size, ia.blocks,
            int(ia.atime), int(ia.mtime), int(ia.ctime),
            int((ia.atime % 1) * 1e9), int((ia.mtime % 1) * 1e9),
            int((ia.ctime % 1) * 1e9),
            type_bits | ia.mode, ia.nlink, ia.uid, ia.gid, ia.rdev,
            ia.blksize, 0)

    def _entry_out(self, parent: int, name: str, ia: Iatt) -> bytes:
        node = self._remember(parent, name, ia)
        return fp.ENTRY_OUT.pack(node.nodeid, 0, 1, 0, 0, 0) \
            + self._attr_bytes(ia)

    def _attr_out(self, ia: Iatt) -> bytes:
        return fp.ATTR_OUT.pack(1, 0, 0) + self._attr_bytes(ia)

    async def _child(self, parent: _Node, name: str) -> tuple[Loc, Iatt]:
        """Resolve parent+name through lookup (fuse_resolve analog)."""
        base = self._path(parent)
        path = (base if base != "/" else "") + "/" + name
        ia, _ = await self.client.graph.top.lookup(
            Loc(path, parent=parent.gfid))
        return Loc(path, gfid=ia.gfid, parent=parent.gfid), ia

    def _fd(self, fh: int) -> FdObj:
        fd = self._fhs.get(fh)
        if fd is None:
            raise FopError(errno.EBADF, f"fh {fh}")
        return fd

    def _new_fh(self, fd: FdObj) -> int:
        fh = self._next_fh
        self._next_fh += 1
        self._fhs[fh] = fd
        return fh

    @property
    def _top(self):
        return self.client.graph.top

    # -- opcode handlers ---------------------------------------------------

    async def _op_init(self, nodeid: int, payload: bytes) -> bytes:
        major, minor, _ra, kflags = fp.INIT_IN.unpack_from(payload)
        self.proto_minor = min(minor, fp.FUSE_KERNEL_MINOR_VERSION)
        want = (fp.FUSE_ASYNC_READ | fp.FUSE_BIG_WRITES
                | fp.FUSE_PARALLEL_DIROPS | fp.FUSE_MAX_PAGES
                | fp.FUSE_DO_READDIRPLUS | fp.FUSE_READDIRPLUS_AUTO)
        if self.writeback_cache:
            want |= fp.FUSE_WRITEBACK_CACHE
        flags = want & kflags  # never claim a flag the kernel didn't offer
        return fp.INIT_OUT.pack(
            fp.FUSE_KERNEL_VERSION, self.proto_minor, 1 << 20, flags,
            64, 48, _MAX_WRITE, 1, _MAX_WRITE // 4096, 0, 0
        ) + b"\0" * fp.INIT_OUT_PAD

    async def _op_destroy(self, nodeid: int, payload: bytes) -> bytes:
        return b""

    async def _op_lookup(self, nodeid: int, payload: bytes) -> bytes:
        parent = self._node(nodeid)
        name = os.fsdecode(payload.split(b"\0", 1)[0])
        _, ia = await self._child(parent, name)
        return self._entry_out(nodeid, name, ia)

    async def _op_getattr(self, nodeid: int, payload: bytes) -> bytes:
        gflags, _, fh = fp.GETATTR_IN.unpack_from(payload)
        if gflags & 1 and fh in self._fhs:  # FUSE_GETATTR_FH
            ia = await self._top.fstat(self._fhs[fh])
        else:
            ia = await self._top.stat(self._loc(self._node(nodeid)))
        return self._attr_out(ia)

    async def _op_setattr(self, nodeid: int, payload: bytes) -> bytes:
        (valid, _, fh, size, _lock, atime, mtime, _ctime, _ansec, _mnsec,
         _cnsec, mode, _u4, uid, gid, _u5) = fp.SETATTR_IN.unpack_from(
            payload)
        node = self._node(nodeid)
        loc = self._loc(node)
        attrs: dict = {}
        if valid & fp.FATTR_MODE:
            attrs["mode"] = stat_mod.S_IMODE(mode)
        if valid & fp.FATTR_UID:
            attrs["uid"] = uid
        if valid & fp.FATTR_GID:
            attrs["gid"] = gid
        # None = UTIME_NOW: the distinction must survive to posix-acl,
        # which grants plain writers the touch-to-now path but demands
        # ownership for explicit timestamps (utimensat(2) semantics)
        if valid & (fp.FATTR_ATIME | fp.FATTR_ATIME_NOW):
            attrs["atime"] = (None
                              if valid & fp.FATTR_ATIME_NOW else atime)
        if valid & (fp.FATTR_MTIME | fp.FATTR_MTIME_NOW):
            attrs["mtime"] = (None
                              if valid & fp.FATTR_MTIME_NOW else mtime)
        truncating = bool(valid & fp.FATTR_SIZE)
        if truncating and attrs and self.client._use_compound():
            # truncate+chmod/chown/utimes arrive as ONE kernel SETATTR;
            # send them as one fused chain instead of two graph waves
            from ..rpc import compound as cfop

            if valid & fp.FATTR_FH and fh in self._fhs:
                first = ("ftruncate", (self._fhs[fh], size), {})
            else:
                first = ("truncate", (loc, size), {})
            replies = await self._top.compound(
                [first, ("setattr", (loc, attrs, valid), {})])
            return self._attr_out(cfop.unwrap(replies)[-1])
        if truncating:
            if valid & fp.FATTR_FH and fh in self._fhs:
                await self._top.ftruncate(self._fhs[fh], size)
            else:
                await self._top.truncate(loc, size)
        if attrs:
            ia = await self._top.setattr(loc, attrs, valid)
        else:
            ia = await self._top.stat(loc)
        return self._attr_out(ia)

    async def _op_readlink(self, nodeid: int, payload: bytes) -> bytes:
        target = await self._top.readlink(self._loc(self._node(nodeid)))
        return os.fsencode(target)

    async def _op_symlink(self, nodeid: int, payload: bytes) -> bytes:
        name, target = payload.split(b"\0")[:2]
        parent = self._node(nodeid)
        base = self._path(parent)
        loc = Loc((base if base != "/" else "") + "/" + os.fsdecode(name),
                  parent=parent.gfid)
        ia = await self._top.symlink(os.fsdecode(target), loc)
        return self._entry_out(nodeid, os.fsdecode(name), ia)

    async def _op_mknod(self, nodeid: int, payload: bytes) -> bytes:
        mode, rdev, umask, _ = fp.MKNOD_IN.unpack_from(payload)
        if not stat_mod.S_ISREG(mode):
            raise FopError(errno.EOPNOTSUPP, "only regular files")
        name = os.fsdecode(payload[fp.MKNOD_IN.size:].split(b"\0", 1)[0])
        parent = self._node(nodeid)
        base = self._path(parent)
        loc = Loc((base if base != "/" else "") + "/" + name,
                  parent=parent.gfid)
        ia = await self._top.mknod(loc, stat_mod.S_IMODE(mode & ~umask),
                                   rdev)
        return self._entry_out(nodeid, name, ia)

    async def _op_mkdir(self, nodeid: int, payload: bytes) -> bytes:
        mode, umask = fp.MKDIR_IN.unpack_from(payload)
        name = os.fsdecode(payload[fp.MKDIR_IN.size:].split(b"\0", 1)[0])
        parent = self._node(nodeid)
        base = self._path(parent)
        loc = Loc((base if base != "/" else "") + "/" + name,
                  parent=parent.gfid)
        ia = await self._top.mkdir(loc, stat_mod.S_IMODE(mode & ~umask))
        return self._entry_out(nodeid, name, ia)

    async def _op_unlink(self, nodeid: int, payload: bytes) -> bytes:
        parent = self._node(nodeid)
        name = os.fsdecode(payload.split(b"\0", 1)[0])
        loc, _ = await self._child(parent, name)
        await self._top.unlink(loc)
        return b""

    async def _op_rmdir(self, nodeid: int, payload: bytes) -> bytes:
        parent = self._node(nodeid)
        name = os.fsdecode(payload.split(b"\0", 1)[0])
        loc, _ = await self._child(parent, name)
        await self._top.rmdir(loc)
        return b""

    async def _rename(self, nodeid: int, newdir: int, names: bytes) -> bytes:
        oldname, newname = names.split(b"\0")[:2]
        parent = self._node(nodeid)
        newparent = self._node(newdir)
        oldloc, ia = await self._child(parent, os.fsdecode(oldname))
        base = self._path(newparent)
        newloc = Loc((base if base != "/" else "") + "/" + os.fsdecode(newname),
                     parent=newparent.gfid)
        await self._top.rename(oldloc, newloc)
        nid = self._by_gfid.get(ia.gfid)
        if nid is not None and nid in self._nodes:  # keep paths current
            self._nodes[nid].parent = newdir
            self._nodes[nid].name = os.fsdecode(newname)
        return b""

    async def _op_rename(self, nodeid: int, payload: bytes) -> bytes:
        (newdir,) = fp.RENAME_IN.unpack_from(payload)
        return await self._rename(nodeid, newdir,
                                  payload[fp.RENAME_IN.size:])

    async def _op_rename2(self, nodeid: int, payload: bytes) -> bytes:
        newdir, flags, _ = fp.RENAME2_IN.unpack_from(payload)
        if flags:  # RENAME_NOREPLACE / RENAME_EXCHANGE unsupported
            raise FopError(errno.EINVAL, "rename2 flags")
        return await self._rename(nodeid, newdir,
                                  payload[fp.RENAME2_IN.size:])

    async def _op_link(self, nodeid: int, payload: bytes) -> bytes:
        (oldnodeid,) = fp.LINK_IN.unpack_from(payload)
        name = os.fsdecode(payload[fp.LINK_IN.size:].split(b"\0", 1)[0])
        oldnode = self._node(oldnodeid)
        parent = self._node(nodeid)
        base = self._path(parent)
        newloc = Loc((base if base != "/" else "") + "/" + name,
                     parent=parent.gfid)
        ia = await self._top.link(self._loc(oldnode), newloc)
        return self._entry_out(nodeid, name, ia)

    async def _op_open(self, nodeid: int, payload: bytes) -> bytes:
        flags, _ = fp.OPEN_IN.unpack_from(payload)
        fd = await self._top.open(self._loc(self._node(nodeid)), flags)
        open_flags = fp.FOPEN_KEEP_CACHE if self.keep_cache else 0
        return fp.OPEN_OUT.pack(self._new_fh(fd), open_flags, 0)

    async def _op_opendir(self, nodeid: int, payload: bytes) -> bytes:
        fd = await self._top.opendir(self._loc(self._node(nodeid)))
        return fp.OPEN_OUT.pack(self._new_fh(fd), 0, 0)

    async def _op_create(self, nodeid: int, payload: bytes) -> bytes:
        flags, mode, umask, _ = fp.CREATE_IN.unpack_from(payload)
        name = os.fsdecode(payload[fp.CREATE_IN.size:].split(b"\0", 1)[0])
        parent = self._node(nodeid)
        base = self._path(parent)
        loc = Loc((base if base != "/" else "") + "/" + name,
                  parent=parent.gfid)
        fd, ia = await self._top.create(loc, flags,
                                        stat_mod.S_IMODE(mode & ~umask))
        return self._entry_out(nodeid, name, ia) \
            + fp.OPEN_OUT.pack(self._new_fh(fd), 0, 0)

    async def _op_read(self, nodeid: int, payload: bytes) -> bytes:
        fh, offset, size, *_ = fp.READ_IN.unpack_from(payload)
        return await self._top.readv(self._fd(fh), size, offset)

    async def _op_write(self, nodeid: int, payload: bytes) -> bytes:
        fh, offset, size, *_ = fp.WRITE_IN.unpack_from(payload)
        data = payload[fp.WRITE_IN.size:fp.WRITE_IN.size + size]
        await self._top.writev(self._fd(fh), bytes(data), offset)
        return fp.WRITE_OUT.pack(len(data), 0)

    async def _op_statfs(self, nodeid: int, payload: bytes) -> bytes:
        sv = await self._top.statfs(self._loc(self._node(nodeid)))
        return fp.KSTATFS.pack(sv.get("blocks", 0), sv.get("bfree", 0),
                               sv.get("bavail", 0), sv.get("files", 0),
                               sv.get("ffree", 0), sv.get("bsize", 4096),
                               255, sv.get("bsize", 4096), 0)

    async def _op_release(self, nodeid: int, payload: bytes) -> bytes:
        fh, *_ = fp.RELEASE_IN.unpack_from(payload)
        fd = self._fhs.pop(fh, None)
        if fd is not None:
            await self._top.release(fd)
        return b""

    _op_releasedir = _op_release

    async def _op_flush(self, nodeid: int, payload: bytes) -> bytes:
        fh, *_ = fp.FLUSH_IN.unpack_from(payload)
        await self._top.flush(self._fd(fh))
        return b""

    async def _op_fsync(self, nodeid: int, payload: bytes) -> bytes:
        fh, fsync_flags, _ = fp.FSYNC_IN.unpack_from(payload)
        await self._top.fsync(self._fd(fh), fsync_flags & 1)
        return b""

    async def _op_fsyncdir(self, nodeid: int, payload: bytes) -> bytes:
        fh, fsync_flags, _ = fp.FSYNC_IN.unpack_from(payload)
        await self._top.fsyncdir(self._fd(fh), fsync_flags & 1)
        return b""

    async def _op_access(self, nodeid: int, payload: bytes) -> bytes:
        mask, _ = fp.ACCESS_IN.unpack_from(payload)
        await self._top.access(self._loc(self._node(nodeid)), mask)
        return b""

    async def _op_setxattr(self, nodeid: int, payload: bytes) -> bytes:
        size, flags = fp.SETXATTR_IN.unpack_from(payload)
        rest = payload[fp.SETXATTR_IN.size:]
        name, rest = rest.split(b"\0", 1)
        await self._top.setxattr(self._loc(self._node(nodeid)),
                                 {os.fsdecode(name): bytes(rest[:size])}, flags)
        return b""

    async def _op_getxattr(self, nodeid: int, payload: bytes) -> bytes:
        size, _ = fp.GETXATTR_IN.unpack_from(payload)
        name = os.fsdecode(payload[fp.GETXATTR_IN.size:].split(b"\0", 1)[0])
        d = await self._top.getxattr(self._loc(self._node(nodeid)), name)
        if not d or name not in d:
            raise FopError(errno.ENODATA, name)
        val = d[name]
        if isinstance(val, str):
            val = os.fsencode(val)
        if size == 0:
            return fp.GETXATTR_OUT.pack(len(val), 0)
        if len(val) > size:
            raise FopError(errno.ERANGE, name)
        return val

    async def _op_listxattr(self, nodeid: int, payload: bytes) -> bytes:
        size, _ = fp.GETXATTR_IN.unpack_from(payload)
        d = await self._top.getxattr(self._loc(self._node(nodeid)), None)
        blob = b"".join(os.fsencode(k) + b"\0" for k in sorted(d or {}))
        if size == 0:
            return fp.GETXATTR_OUT.pack(len(blob), 0)
        if len(blob) > size:
            raise FopError(errno.ERANGE, "listxattr")
        return blob

    async def _op_removexattr(self, nodeid: int, payload: bytes) -> bytes:
        name = os.fsdecode(payload.split(b"\0", 1)[0])
        await self._top.removexattr(self._loc(self._node(nodeid)), name)
        return b""

    @staticmethod
    def _dirent_len(name: str, plus: bool) -> int:
        n = fp.DIRENT.size + len(os.fsencode(name))
        n += (-n) % 8
        if plus:
            n += fp.ENTRY_OUT.size + fp.ATTR.size
        return n

    async def _readdir_common(self, nodeid: int, payload: bytes,
                              plus: bool) -> bytes:
        fh, offset, size, *_ = fp.READ_IN.unpack_from(payload)
        fd = self._fd(fh)
        # the kernel reads a directory in small chunks; fetch the full
        # listing once per rewind and serve chunks from the fd-cached
        # copy (re-listing per chunk would be O(n^2) in graph fops)
        cached = None if offset == 0 else fd.ctx_get(self)
        if cached is None:
            # always readdirp: plain READDIR must still fill real
            # d_ino/d_type (getdents consumers alias to ino 1 otherwise);
            # the iatts are simply not turned into kernel entries then
            entries = await self._top.readdirp(fd, 0, 0)
            listing: list[tuple[str, Iatt | None]] = [(".", None),
                                                      ("..", None)]
            listing += [(n, ia) for n, ia in entries]
            fd.ctx_set(self, listing)
        else:
            listing = cached
        out = bytearray()
        for idx in range(offset, len(listing)):
            name, ia = listing[idx]
            # size-check BEFORE _remember: an entry the kernel never
            # receives must not acquire an nlookup it will never forget
            if len(out) + self._dirent_len(name, plus) > size:
                break
            nxt = idx + 1
            if ia is None:
                dtype = 4 if name in (".", "..") else 0
                if plus:
                    ent_attr = b"\0" * (fp.ENTRY_OUT.size + fp.ATTR.size)
                    ent = fp.pack_direntplus(ent_attr, 1, nxt, dtype,
                                             os.fsencode(name))
                else:
                    ent = fp.pack_dirent(1, nxt, dtype, os.fsencode(name))
            else:
                dtype = _DTYPE.get(ia.ia_type, 0)
                ino = _gfid_ino(ia.gfid)
                if plus:
                    ent = fp.pack_direntplus(
                        self._entry_out(nodeid, name, ia), ino, nxt,
                        dtype, os.fsencode(name))
                else:
                    ent = fp.pack_dirent(ino, nxt, dtype, os.fsencode(name))
            out += ent
        return bytes(out)

    async def _op_readdir(self, nodeid: int, payload: bytes) -> bytes:
        return await self._readdir_common(nodeid, payload, plus=False)

    async def _op_readdirplus(self, nodeid: int, payload: bytes) -> bytes:
        return await self._readdir_common(nodeid, payload, plus=True)

    async def _op_fallocate(self, nodeid: int, payload: bytes) -> bytes:
        fh, offset, length, mode, _ = fp.FALLOCATE_IN.unpack_from(payload)
        fd = self._fd(fh)
        if mode & 0x02:  # FALLOC_FL_PUNCH_HOLE
            await self._top.discard(fd, offset, length)
        elif mode & 0x10:  # FALLOC_FL_ZERO_RANGE
            await self._top.zerofill(fd, offset, length)
        else:
            await self._top.fallocate(fd, mode, offset, length)
        return b""

    async def _op_lseek(self, nodeid: int, payload: bytes) -> bytes:
        fh, offset, whence, _ = fp.LSEEK_IN.unpack_from(payload)
        what = "data" if whence == 3 else "hole"  # SEEK_DATA / SEEK_HOLE
        pos = await self._top.seek(self._fd(fh), offset, what)
        return fp.LSEEK_OUT.pack(pos)

    _HANDLERS = {
        fp.INIT: _op_init, fp.DESTROY: _op_destroy,
        fp.LOOKUP: _op_lookup, fp.GETATTR: _op_getattr,
        fp.SETATTR: _op_setattr, fp.READLINK: _op_readlink,
        fp.SYMLINK: _op_symlink, fp.MKNOD: _op_mknod,
        fp.MKDIR: _op_mkdir, fp.UNLINK: _op_unlink, fp.RMDIR: _op_rmdir,
        fp.RENAME: _op_rename, fp.RENAME2: _op_rename2, fp.LINK: _op_link,
        fp.OPEN: _op_open, fp.OPENDIR: _op_opendir, fp.CREATE: _op_create,
        fp.READ: _op_read, fp.WRITE: _op_write, fp.STATFS: _op_statfs,
        fp.RELEASE: _op_release, fp.RELEASEDIR: _op_releasedir,
        fp.FLUSH: _op_flush, fp.FSYNC: _op_fsync,
        fp.FSYNCDIR: _op_fsyncdir, fp.ACCESS: _op_access,
        fp.SETXATTR: _op_setxattr, fp.GETXATTR: _op_getxattr,
        fp.LISTXATTR: _op_listxattr, fp.REMOVEXATTR: _op_removexattr,
        fp.READDIR: _op_readdir, fp.READDIRPLUS: _op_readdirplus,
        fp.FALLOCATE: _op_fallocate, fp.LSEEK: _op_lseek,
    }


async def _amain(args) -> int:
    from ..core import flight
    from ..mgmt.glusterd import mount_volume

    flight.set_role("fuse")
    host, _, port = args.server.rpartition(":")
    client = await mount_volume(host or "127.0.0.1", int(port),
                                args.volume)
    bridge = FuseBridge(client, args.mountpoint, args.volume,
                        keep_cache=args.fopen_keep_cache,
                        writeback_cache=not args.no_writeback_cache,
                        reader_split=not args.no_reader_split,
                        max_inflight=args.fuse_inflight)
    bridge.mount()
    if args.readyfile:
        with open(args.readyfile + ".tmp", "w") as f:
            f.write("ok")
        os.replace(args.readyfile + ".tmp", args.readyfile)
    import signal

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    waiter = asyncio.ensure_future(bridge.wait_closed())
    stopper = asyncio.ensure_future(stop.wait())
    await asyncio.wait({waiter, stopper},
                       return_when=asyncio.FIRST_COMPLETED)
    waiter.cancel()
    stopper.cancel()
    await bridge.unmount()
    await client.unmount()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gftpu-fuse",
        description="mount a volume through the kernel (FUSE)")
    p.add_argument("--server", required=True, help="glusterd host:port")
    p.add_argument("--volume", required=True)
    p.add_argument("--readyfile", default="",
                   help="file created once the mount is live")
    p.add_argument("--fopen-keep-cache", action="store_true",
                   help="keep kernel page cache across opens "
                        "(glusterfs --fopen-keep-cache)")
    p.add_argument("--no-writeback-cache", action="store_true",
                   help="write-through: disable FUSE_WRITEBACK_CACHE "
                        "(glusterfs --kernel-writeback-cache=off); "
                        "needed when several mounts write one file")
    p.add_argument("--no-reader-split", action="store_true",
                   help="serve /dev/fuse from the event loop instead "
                        "of the dedicated reader + writer threads "
                        "(the pre-event-plane single-loop mode)")
    p.add_argument("--fuse-inflight", type=int, default=64,
                   help="bounded inflight handoff: kernel requests "
                        "admitted but not yet answered (reader-split "
                        "plane only; excess queues in the kernel)")
    p.add_argument("mountpoint")
    args = p.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
