"""performance/md-cache — stat/xattr cache with timeout + invalidation.

Reference: xlators/performance/md-cache (4.1k LoC): caches iatt and
xattrs per inode for ``timeout`` seconds; any modifying fop invalidates;
upcall events (when connected) invalidate remotely-changed entries.
"""

from __future__ import annotations

import time

from ..core.fops import WRITE_FOPS, Fop
from ..core.layer import Event, FdObj, Layer, Loc, register
from ..core.options import Option
from . import cache_metrics


@register("performance/md-cache")
class MdCacheLayer(Layer):
    OPTIONS = (
        Option("timeout", "time", default="1", min=0),
        Option("cache-xattrs", "bool", default="on"),
        # xattr-family toggles (mdc_key_load_set, md-cache.c): only
        # known-safe families are cached; each toggle admits its set
        Option("cache-swift-metadata", "bool", default="off",
               description="cache user.swift.metadata "
                           "(performance.cache-swift-metadata)"),
        Option("cache-samba-metadata", "bool", default="off",
               description="cache user.DOSATTRIB + security.NTACL "
                           "(performance.cache-samba-metadata)"),
        Option("cache-capability-xattrs", "bool", default="on",
               description="cache security.capability "
                           "(performance.cache-capability-xattrs)"),
        Option("cache-ima-xattrs", "bool", default="on",
               description="cache security.ima "
                           "(performance.cache-ima-xattrs)"),
        Option("xattr-cache-list", "str", default="",
               description="extra comma-separated fnmatch patterns of "
                           "cacheable xattr names "
                           "(performance.xattr-cache-list)"),
        Option("md-cache-statfs", "bool", default="off",
               description="cache statfs replies for one timeout "
                           "(performance.md-cache-statfs)"),
        Option("cache-invalidation", "bool", default="on",
               description="react to server upcalls by dropping the "
                           "entry (performance.cache-invalidation); "
                           "off = pure-TTL cache"),
    )

    _FAMILIES = (
        ("cache-swift-metadata", ("user.swift.metadata",)),
        ("cache-samba-metadata", ("user.DOSATTRIB", "security.NTACL")),
        ("cache-capability-xattrs", ("security.capability",)),
        ("cache-ima-xattrs", ("security.ima",)),
    )

    CACHE_KIND = "md"  # the gftpu_cache_* {cache=...} label

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._iatt: dict[bytes, tuple[float, object]] = {}
        self._xattr: dict[bytes, tuple[float, dict]] = {}
        self._statfs: tuple[float, object] | None = None
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0  # xattr payload served from cache
        self.invalidations = 0  # upcall-driven (not TTL, not local fop)
        # held-lease registry (api/glfs HeldLeases): while the mount
        # holds a lease on a gfid its entries never TTL out — recall
        # (which drops both the lease and, via upcall, the entry) is
        # the only invalidator.  None = unleased stack, pure TTL.
        self._lease_reg = None
        cache_metrics.track(self)

    def set_lease_registry(self, reg) -> None:
        self._lease_reg = reg

    def _xattr_cacheable(self, name: str) -> bool:
        """Internal (trusted.*/glusterfs.*) names always cache; user/
        security families by toggle; extra patterns by option list."""
        if not name.startswith(("user.", "security.")):
            return True
        for opt, names in self._FAMILIES:
            if name in names:
                return bool(self.opts[opt])
        import fnmatch

        return any(fnmatch.fnmatch(name, p.strip())
                   for p in str(self.opts["xattr-cache-list"]).split(",")
                   if p.strip())

    def invalidate(self, gfid: bytes) -> None:
        self._iatt.pop(gfid, None)
        self._xattr.pop(gfid, None)

    def notify(self, event: Event, source=None, data=None):
        """Upcall subscription (mdc_notify + mdc_invalidate analog):
        a server-pushed invalidation drops the entry immediately instead
        of waiting out the TTL."""
        if event is Event.UPCALL and isinstance(data, dict) and \
                data.get("gfid") and self.opts["cache-invalidation"]:
            self.invalidations += 1
            self.invalidate(data["gfid"])
        super().notify(event, source, data)

    def _fresh(self, entry, gfid=None) -> bool:
        if entry is None:
            return False
        # lease-held gfids never go stale by clock: the brick MUST
        # recall (→ upcall invalidation) before any conflicting write
        # proceeds, so presence implies validity — zero-RT mode
        if gfid is not None and self._lease_reg is not None and \
                self._lease_reg.held(gfid):
            return True
        return time.monotonic() - entry[0] < self.opts["timeout"]

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        if loc.gfid:
            entry = self._iatt.get(loc.gfid)
            if self._fresh(entry, loc.gfid):
                self.hits += 1
                return entry[1], {}
        self.misses += 1
        ia, xd = await self.children[0].lookup(loc, xdata)
        self._iatt[ia.gfid] = (time.monotonic(), ia)
        return ia, xd

    async def stat(self, loc: Loc, xdata: dict | None = None):
        if loc.gfid:
            entry = self._iatt.get(loc.gfid)
            if self._fresh(entry, loc.gfid):
                self.hits += 1
                return entry[1]
        self.misses += 1
        ia = await self.children[0].stat(loc, xdata)
        self._iatt[ia.gfid] = (time.monotonic(), ia)
        return ia

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        entry = self._iatt.get(fd.gfid)
        if self._fresh(entry, fd.gfid):
            self.hits += 1
            return entry[1]
        self.misses += 1
        ia = await self.children[0].fstat(fd, xdata)
        self._iatt[ia.gfid] = (time.monotonic(), ia)
        return ia

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        if self.opts["cache-xattrs"] and loc.gfid and name is not None \
                and self._xattr_cacheable(name):
            entry = self._xattr.get(loc.gfid)
            if self._fresh(entry, loc.gfid) and name in entry[1]:
                self.hits += 1
                val = entry[1][name]
                self.hit_bytes += len(val) if \
                    isinstance(val, (bytes, str)) else 0
                return {name: entry[1][name]}
        out = await self.children[0].getxattr(loc, name, xdata)
        if self.opts["cache-xattrs"] and loc.gfid:
            t, cur = self._xattr.get(loc.gfid, (0, {}))
            cur = dict(cur)
            cur.update({k: v for k, v in out.items()
                        if self._xattr_cacheable(k)})
            self._xattr[loc.gfid] = (time.monotonic(), cur)
        return out

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        if self.opts["md-cache-statfs"]:
            if self._fresh(self._statfs):
                self.hits += 1
                return self._statfs[1]
            out = await self.children[0].statfs(loc, xdata)
            self._statfs = (time.monotonic(), out)
            return out
        return await self.children[0].statfs(loc, xdata)

    def dump_private(self) -> dict:
        return {"iatts": len(self._iatt), "hits": self.hits,
                "misses": self.misses,
                "leased": 0 if self._lease_reg is None
                else len(self._lease_reg),
                "upcall_invalidations": self.invalidations}


_WRITE_NAMES = {f.value for f in WRITE_FOPS}


async def _mdc_compound(self, links, xdata: dict | None = None) -> list:
    """Forward chains intact and replay the cache maintenance the
    per-fop overrides would have done: write links invalidate their
    target's cached iatt, successful replies donate their postbufs
    (a fused create+writev still leaves the size a following stat
    expects)."""
    replies = await self.children[0].compound(links, xdata)
    now = time.monotonic()
    for (fop, args, _kw), (st, val) in zip(links, replies):
        if fop in _WRITE_NAMES:
            for a in args:
                gfid = a.gfid if isinstance(a, (Loc, FdObj)) else None
                if gfid:
                    self.invalidate(gfid)
        if st != "ok":
            continue
        ia = val
        if isinstance(ia, (tuple, list)):
            # composite replies park the iatt at either end: create is
            # (fd, iatt), lookup is (iatt, xdata) — take the first
            # element that actually is one
            ia = next((x for x in ia if hasattr(x, "gfid")
                       and hasattr(x, "size")), None)
        if hasattr(ia, "gfid") and hasattr(ia, "size") and ia.gfid:
            self._iatt[ia.gfid] = (now, ia)
    return replies


MdCacheLayer.compound = _mdc_compound


def _invalidating(op_name: str):
    async def fop(self, *args, **kwargs):
        ret = await getattr(self.children[0], op_name)(*args, **kwargs)
        for a in args:
            if isinstance(a, Loc) and a.gfid:
                self.invalidate(a.gfid)
            elif isinstance(a, FdObj):
                self.invalidate(a.gfid)
        # absorb the postbuf (mdc_writev_cbk and friends update from
        # postbuf iatts): a stat right after a write is served from
        # cache instead of paying a fresh cluster lookup wave
        ia = ret
        if isinstance(ia, (tuple, list)) and ia and \
                hasattr(ia[-1], "gfid"):
            ia = ia[-1]
        if hasattr(ia, "gfid") and hasattr(ia, "size") and ia.gfid:
            self._iatt[ia.gfid] = (time.monotonic(), ia)
        return ret
    fop.__name__ = op_name
    return fop


for _f in WRITE_FOPS:
    setattr(MdCacheLayer, _f.value, _invalidating(_f.value))
