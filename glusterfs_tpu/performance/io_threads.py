"""performance/io-threads — brick-side admission control with priority
classes.

Reference: xlators/performance/io-threads (1.7k LoC; io-threads.c:64-89):
a worker pool with 4 priority queues (fast/normal/slow/least) classified
by fop.  In this asyncio runtime the analog is a bounded-concurrency
gate per priority class: lookups/stats preempt bulk data, matching the
reference's scheduling intent without kernel threads."""

from __future__ import annotations

import asyncio

from ..core.fops import Fop
from ..core.layer import Layer, register
from ..core.options import Option

# fop -> priority class (io-threads.c:64-89)
FAST = {Fop.LOOKUP, Fop.STAT, Fop.FSTAT, Fop.ACCESS, Fop.READLINK,
        Fop.OPEN, Fop.OPENDIR, Fop.STATFS, Fop.GETXATTR, Fop.FGETXATTR}
NORMAL = {Fop.READV, Fop.WRITEV, Fop.FLUSH, Fop.FSYNC, Fop.CREATE,
          Fop.MKDIR, Fop.UNLINK, Fop.RMDIR, Fop.RENAME, Fop.LINK,
          Fop.SYMLINK, Fop.MKNOD, Fop.TRUNCATE, Fop.FTRUNCATE,
          Fop.SETXATTR, Fop.FSETXATTR, Fop.XATTROP, Fop.FXATTROP,
          Fop.SETATTR, Fop.FSETATTR, Fop.INODELK, Fop.FINODELK,
          Fop.ENTRYLK, Fop.FENTRYLK, Fop.LK}
# everything else -> slow; readdirp/rchecksum explicitly least
LEAST = {Fop.READDIRP, Fop.RCHECKSUM}


def _prio(fop: Fop) -> int:
    if fop in FAST:
        return 0
    if fop in NORMAL:
        return 1
    if fop in LEAST:
        return 3
    return 2


@register("performance/io-threads")
class IoThreadsLayer(Layer):
    OPTIONS = (
        Option("thread-count", "int", default=16, min=1, max=64),
        Option("high-prio-threads", "int", default=16, min=1, max=64),
        Option("low-prio-threads", "int", default=8, min=1, max=64),
        Option("least-prio-threads", "int", default=1, min=1, max=64),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._gates = [
            asyncio.Semaphore(self.opts["high-prio-threads"]),
            asyncio.Semaphore(self.opts["thread-count"]),
            asyncio.Semaphore(self.opts["low-prio-threads"]),
            asyncio.Semaphore(self.opts["least-prio-threads"]),
        ]
        self.queued = [0, 0, 0, 0]
        self.executed = [0, 0, 0, 0]

    def dump_private(self) -> dict:
        return {"queued": list(self.queued),
                "executed": list(self.executed)}


def _gated(fop: Fop):
    pri = _prio(fop)
    name = fop.value

    async def fop_impl(self, *args, **kwargs):
        self.queued[pri] += 1
        try:
            async with self._gates[pri]:
                self.executed[pri] += 1
                return await getattr(self.children[0], name)(*args, **kwargs)
        finally:
            self.queued[pri] -= 1
    fop_impl.__name__ = name
    return fop_impl


for _f in Fop:
    setattr(IoThreadsLayer, _f.value, _gated(_f))
