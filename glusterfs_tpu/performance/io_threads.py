"""performance/io-threads — brick-side worker threads + admission
control with priority classes.

Reference: xlators/performance/io-threads (1.7k LoC; io-threads.c:64-89
priority map, :236 iot_worker): a worker pool with 4 priority queues
(fast/normal/slow/least) classified by fop, whose whole point is that a
slow disk syscall occupies a worker thread, never the brick's event
engine.  Two mechanisms here:

* a REAL ``ThreadPoolExecutor`` (``thread-count`` workers) injected into
  the storage/posix descendant, which routes its blocking data-plane
  syscalls through it — one stuck pread no longer stalls every
  connection on the brick;
* bounded-concurrency gates per priority class on the async side, so
  lookups/stats preempt bulk data (the queue-priority scheduling
  intent).

With the concurrent event plane (server.event-threads, ISSUE 7) this
layer is the brick's real parallel dispatcher: the event pool feeds
independent fops from different connections into the graph
concurrently, the priority gates admit them side by side, and the
injected executor runs their posix syscalls on parallel worker
threads.  Both pools resize LIVE: ``reconfigure`` grows/shrinks the
executor (a fresh pool swaps in; in-flight syscalls finish on the old
one) and the gates (a :class:`ResizableGate` re-admits parked waiters
when its limit grows), never dropping queued work.  ``inflight`` /
``peak_inflight`` make the achieved parallelism observable
(dump_private + the callpool status plane)."""

from __future__ import annotations

import asyncio
import collections
import errno
from concurrent.futures import ThreadPoolExecutor

from ..core.fops import Fop, FopError
from ..core.layer import Layer, register
from ..core.options import Option
from ..core import metrics as _metrics

_PRIO_NAMES = ("fast", "normal", "slow", "least")

#: live io-threads layers, scraped by the unified registry (weak: a
#: retired graph's layers age out with the GC); both families hang off
#: the one population
_LIVE_IOT_LAYERS = _metrics.REGISTRY.register_objects(
    "gftpu_io_threads_queued", "gauge",
    "fops currently queued or executing per priority class",
    lambda l: [({"layer": l.name, "prio": _PRIO_NAMES[i]}, v)
               for i, v in enumerate(l.queued)])
_metrics.REGISTRY.register_objects(
    "gftpu_io_threads_executed_total", "counter",
    "fops admitted through each priority gate",
    lambda l: [({"layer": l.name, "prio": _PRIO_NAMES[i]}, v)
               for i, v in enumerate(l.executed)],
    live=_LIVE_IOT_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_io_threads_deadline_dropped_total", "counter",
    "queued fops dropped at gate admission because the client's "
    "propagated deadline budget had already expired (the client "
    "abandoned the call — answering would burn a worker for nothing)",
    lambda l: [({"layer": l.name}, l.deadline_dropped)],
    live=_LIVE_IOT_LAYERS)

# fop -> priority class (io-threads.c:64-89)
FAST = {Fop.LOOKUP, Fop.STAT, Fop.FSTAT, Fop.ACCESS, Fop.READLINK,
        Fop.OPEN, Fop.OPENDIR, Fop.STATFS, Fop.GETXATTR, Fop.FGETXATTR}
NORMAL = {Fop.READV, Fop.WRITEV, Fop.FLUSH, Fop.FSYNC, Fop.CREATE,
          Fop.MKDIR, Fop.UNLINK, Fop.RMDIR, Fop.RENAME, Fop.LINK,
          Fop.SYMLINK, Fop.MKNOD, Fop.TRUNCATE, Fop.FTRUNCATE,
          Fop.SETXATTR, Fop.FSETXATTR, Fop.XATTROP, Fop.FXATTROP,
          Fop.SETATTR, Fop.FSETATTR,
          # the write vocabulary's long tail rides the same queue as
          # its siblings: allocation fops beside truncate, put/
          # copy_file_range beside writev, removexattr beside
          # setxattr, icreate/namelink beside mknod — graft-lint GL01
          # caught all nine silently falling to the slow queue, which
          # would invert them vs sibling writes of the SAME workload
          Fop.FALLOCATE, Fop.DISCARD, Fop.ZEROFILL, Fop.PUT,
          Fop.COPY_FILE_RANGE, Fop.REMOVEXATTR, Fop.FREMOVEXATTR,
          Fop.ICREATE, Fop.NAMELINK,
          # parity-delta applies are data-path write work: the slow
          # queue would invert them vs the sibling data writevs of
          # the SAME delta wave
          Fop.XORV,
          # fused chains are data-path work (create+writev+flush);
          # the slow queue would invert their priority vs their links
          Fop.COMPOUND}
# everything else -> slow; readdirp/rchecksum explicitly least
LEAST = {Fop.READDIRP, Fop.RCHECKSUM}
# Lock fops are NEVER admission-gated: an inodelk can legitimately
# block until another client unlocks — if waiters held gate slots, the
# unlock that frees them could queue behind them and deadlock the brick
# (the reference parks lock waits off-thread in features/locks, without
# occupying an iot worker).
UNGATED = {Fop.INODELK, Fop.FINODELK, Fop.ENTRYLK, Fop.FENTRYLK, Fop.LK,
           Fop.GETACTIVELK, Fop.SETACTIVELK, Fop.LEASE}


def _prio(fop: Fop) -> int:
    if fop in FAST:
        return 0
    if fop in NORMAL:
        return 1
    if fop in LEAST:
        return 3
    return 2


class ResizableGate:
    """A counting gate whose limit can change live (asyncio.Semaphore
    cannot): shrink applies as holders release, grow re-admits parked
    waiters immediately — queued fops are never dropped either way
    (the live-reconfigure contract of performance.*-prio-threads)."""

    __slots__ = ("limit", "_active", "_waiters")

    def __init__(self, limit: int):
        self.limit = int(limit)
        self._active = 0
        self._waiters: collections.deque = collections.deque()

    def resize(self, limit: int) -> None:
        self.limit = int(limit)
        self._kick()

    def _kick(self) -> None:
        while self._waiters and self._active < self.limit:
            fut = self._waiters.popleft()
            if not fut.done():
                self._active += 1
                fut.set_result(None)

    async def __aenter__(self):
        if self._active < self.limit and not self._waiters:
            self._active += 1
            return self
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # granted and cancelled in the same tick: hand the
                # slot on, or it leaks forever
                self._active -= 1
                self._kick()
            else:
                try:
                    self._waiters.remove(fut)
                except ValueError:
                    pass
            raise
        return self

    async def __aexit__(self, *exc):
        self._active -= 1
        self._kick()


@register("performance/io-threads")
class IoThreadsLayer(Layer):
    OPTIONS = (
        Option("thread-count", "int", default=16, min=1, max=64),
        Option("high-prio-threads", "int", default=16, min=1, max=64),
        Option("normal-prio-threads", "int", default=16, min=1, max=64,
               description="concurrency of the normal queue "
                           "(performance.normal-prio-threads; the pool "
                           "itself stays thread-count wide)"),
        Option("low-prio-threads", "int", default=8, min=1, max=64),
        Option("least-prio-threads", "int", default=1, min=1, max=64),
        Option("enable-least-priority", "bool", default="on",
               description="off: least-priority fops (readdirp, "
                           "rchecksum scrub reads) ride the normal "
                           "queue instead of the starvable one "
                           "(performance.enable-least-priority)"),
    )

    _GATE_KEYS = ("high-prio-threads", "normal-prio-threads",
                  "low-prio-threads", "least-prio-threads")

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._gates = [ResizableGate(self.opts[k])
                       for k in self._GATE_KEYS]
        self.queued = [0, 0, 0, 0]
        self.executed = [0, 0, 0, 0]
        # achieved parallelism (the "real parallel dispatch" proof
        # counters): fops currently inside the gates, and the high
        # watermark since init
        self.inflight = 0
        self.peak_inflight = 0
        # abandoned work shed at admission (deadline propagation)
        self.deadline_dropped = 0
        self._pool: ThreadPoolExecutor | None = None
        self._pool_width = 0
        _LIVE_IOT_LAYERS.add(self)

    async def init(self):
        self._pool_width = self.opts["thread-count"]
        self._pool = ThreadPoolExecutor(
            max_workers=self._pool_width,
            thread_name_prefix=f"{self.name}-iot")
        # hand the worker pool to every storage/posix below us (the
        # reference's iot_worker continues the wind in a worker thread;
        # here the leaf offloads its blocking sections instead)
        self._set_executors(self._pool)
        await super().init()

    async def fini(self):
        self._set_executors(None)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        await super().fini()

    def reconfigure(self, options: dict) -> None:
        """Live pool geometry (performance.io-thread-count + the
        *-prio-threads gates): the executor is swapped — in-flight
        syscalls complete on the retiring pool, new ones land on the
        fresh one — and the gates resize in place, re-admitting parked
        waiters on growth.  Nothing queued is dropped."""
        super().reconfigure(options)
        for gate, key in zip(self._gates, self._GATE_KEYS):
            gate.resize(self.opts[key])
        want = self.opts["thread-count"]
        if self._pool is not None and want != self._pool_width:
            old = self._pool
            self._pool = ThreadPoolExecutor(
                max_workers=want, thread_name_prefix=f"{self.name}-iot")
            self._pool_width = want
            self._set_executors(self._pool)
            # retire without waiting: already-submitted syscalls run to
            # completion on the old pool's threads
            old.shutdown(wait=False)

    def _set_executors(self, pool) -> None:
        from ..core.layer import walk

        for layer in walk(self):
            hook = getattr(layer, "set_io_executor", None)
            if hook is not None:
                hook(pool)

    def dump_private(self) -> dict:
        return {"queued": list(self.queued),
                "executed": list(self.executed),
                "inflight": self.inflight,
                "peak_inflight": self.peak_inflight,
                "deadline_dropped": self.deadline_dropped,
                "pool_threads": self._pool_width or
                self.opts["thread-count"]}


def _gated(fop: Fop):
    pri = _prio(fop)
    name = fop.value

    async def fop_impl(self, *args, **kwargs):
        from ..rpc import wire as _wire

        p = pri
        if p != 3 and _wire.CURRENT_LANE.get() == "least":
            # per-client priority lane (features/qos): the server
            # demoted this request — a currently-shaped client or
            # rebalance-origin traffic rides the least-priority class
            # (io-threads' enable-least-priority model, applied per
            # REQUEST instead of per fop type)
            p = 3
        if p == 3 and not self.opts["enable-least-priority"]:
            p = 1  # least-priority disabled: ride the normal queue
        self.queued[p] += 1
        try:
            async with self._gates[p]:
                # abandoned-work shedding (network.deadline-propagation):
                # if the client's budget expired while this fop queued
                # behind the gate, drop it NOW — the reply would be
                # discarded by a caller that already raised ETIMEDOUT,
                # and the worker slot belongs to a live request
                dl = _wire.CURRENT_DEADLINE.get()
                if dl is not None and \
                        asyncio.get_running_loop().time() > dl:
                    self.deadline_dropped += 1
                    raise FopError(
                        errno.ETIMEDOUT,
                        f"{name} dropped at io-threads: client "
                        "deadline budget expired before dispatch")
                self.executed[p] += 1
                self.inflight += 1
                if self.inflight > self.peak_inflight:
                    self.peak_inflight = self.inflight
                try:
                    return await getattr(self.children[0],
                                         name)(*args, **kwargs)
                finally:
                    self.inflight -= 1
        finally:
            self.queued[p] -= 1
    fop_impl.__name__ = name
    return fop_impl


for _f in Fop:
    if _f not in UNGATED:
        setattr(IoThreadsLayer, _f.value, _gated(_f))
