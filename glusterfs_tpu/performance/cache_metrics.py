"""performance/cache_metrics — the one shared cache-counter family set.

``MetricsRegistry.register`` is last-registration-wins by name, so the
``gftpu_cache_*`` families MUST be registered exactly once, from one
module, over one live population — a per-cache-module registration
would silently clobber every sibling's samples.  Every cache that wants
scraping (md-cache, quick-read, io-cache, the gateway object cache)
calls :func:`track` and exposes::

    CACHE_KIND  : str   — the {cache=...} label value
    hits        : int
    misses      : int
    hit_bytes   : int   — payload bytes served from cache
"""

from __future__ import annotations

from ..core.metrics import REGISTRY


def _samples(attr: str):
    def of(c) -> list:
        return [({"cache": c.CACHE_KIND}, getattr(c, attr, 0))]
    return of


_LIVE_CACHES = REGISTRY.register_objects(
    "gftpu_cache_hits_total", "counter",
    "cache hits by cache plane (md = attr/xattr, quick-read = whole "
    "small files, io-cache = read pages, gateway = whole objects)",
    _samples("hits"))
REGISTRY.register_objects(
    "gftpu_cache_misses_total", "counter",
    "cache misses by cache plane", _samples("misses"),
    live=_LIVE_CACHES)
REGISTRY.register_objects(
    "gftpu_cache_bytes_total", "counter",
    "payload bytes served from cache by cache plane",
    _samples("hit_bytes"), live=_LIVE_CACHES)


def track(cache) -> None:
    """Join the scrape population (weak — a dead cache drops out)."""
    _LIVE_CACHES.add(cache)
