"""performance/nl-cache — negative-lookup cache.

Reference: xlators/performance/nl-cache (2.3k LoC): remember ENOENT
lookups so repeated misses (e.g. PATH searches) skip the wire; any
entry-creating fop in the parent invalidates."""

from __future__ import annotations

import errno
import time

from ..core.fops import FopError
from ..core.layer import Layer, Loc, register
from ..core.options import Option


@register("performance/nl-cache")
class NlCacheLayer(Layer):
    OPTIONS = (
        Option("nl-cache-timeout", "time", default="60"),
        Option("nl-cache-limit", "int", default=65536),
        Option("positive-entry", "bool", default="off",
               description="cache successful lookups too "
                           "(performance.nl-cache-positive-entry): "
                           "repeated path walks skip the wire until "
                           "timeout or a mutation under the parent"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._neg: dict[str, float] = {}
        self._pos: dict[str, tuple[float, object]] = {}
        self.hits = 0

    def _key(self, loc: Loc) -> str:
        return loc.path

    def _invalidate_parent(self, path: str) -> None:
        self._neg.pop(path, None)
        self._pos.pop(path, None)

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        key = self._key(loc)
        t = self._neg.get(key)
        if t is not None:
            if time.monotonic() - t < self.opts["nl-cache-timeout"]:
                self.hits += 1
                raise FopError(errno.ENOENT, f"{key} (cached)")
            del self._neg[key]
        if self.opts["positive-entry"]:
            ent = self._pos.get(key)
            if ent is not None and time.monotonic() - ent[0] < \
                    self.opts["nl-cache-timeout"]:
                self.hits += 1
                return ent[1]
        try:
            ret = await self.children[0].lookup(loc, xdata)
        except FopError as e:
            if e.err == errno.ENOENT:
                if len(self._neg) < self.opts["nl-cache-limit"]:
                    self._neg[key] = time.monotonic()
            raise
        if self.opts["positive-entry"] and \
                len(self._pos) < self.opts["nl-cache-limit"]:
            self._pos[key] = (time.monotonic(), ret)
        return ret

    def dump_private(self) -> dict:
        return {"negative_entries": len(self._neg), "hits": self.hits}


async def _nlc_compound(self, links, xdata: dict | None = None) -> list:
    """Forward chains intact; stale every parent entry a namespace link
    touches (the per-fop _creating overrides' job)."""
    replies = await self.children[0].compound(links, xdata)
    for (fop, args, _kw), _entry in zip(links, replies):
        if fop in ("create", "mkdir", "mknod", "symlink", "link",
                   "rename", "unlink", "rmdir"):
            for a in args:
                if isinstance(a, Loc):
                    self._invalidate_parent(a.path)
    return replies


NlCacheLayer.compound = _nlc_compound


def _creating(op_name: str, loc_arg: int):
    async def fop(self, *args, **kwargs):
        ret = await getattr(self.children[0], op_name)(*args, **kwargs)
        # every Loc involved goes stale (rename touches BOTH names)
        for a in args:
            if isinstance(a, Loc):
                self._invalidate_parent(a.path)
        return ret
    fop.__name__ = op_name
    return fop


for _op, _idx in (("create", 0), ("mkdir", 0), ("mknod", 0),
                  ("symlink", 1), ("link", 1), ("rename", 1),
                  # removals: the positive entry (and, for rename's
                  # source, both sides) must drop immediately
                  ("unlink", 0), ("rmdir", 0)):
    setattr(NlCacheLayer, _op, _creating(_op, _idx))
