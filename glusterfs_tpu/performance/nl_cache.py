"""performance/nl-cache — negative-lookup cache.

Reference: xlators/performance/nl-cache (2.3k LoC): remember ENOENT
lookups so repeated misses (e.g. PATH searches) skip the wire; any
entry-creating fop in the parent invalidates."""

from __future__ import annotations

import errno
import time

from ..core.fops import FopError
from ..core.layer import Layer, Loc, register
from ..core.options import Option


@register("performance/nl-cache")
class NlCacheLayer(Layer):
    OPTIONS = (
        Option("nl-cache-timeout", "time", default="60"),
        Option("nl-cache-limit", "int", default=65536),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._neg: dict[str, float] = {}
        self.hits = 0

    def _key(self, loc: Loc) -> str:
        return loc.path

    def _invalidate_parent(self, path: str) -> None:
        self._neg.pop(path, None)

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        key = self._key(loc)
        t = self._neg.get(key)
        if t is not None:
            if time.monotonic() - t < self.opts["nl-cache-timeout"]:
                self.hits += 1
                raise FopError(errno.ENOENT, f"{key} (cached)")
            del self._neg[key]
        try:
            return await self.children[0].lookup(loc, xdata)
        except FopError as e:
            if e.err == errno.ENOENT:
                if len(self._neg) < self.opts["nl-cache-limit"]:
                    self._neg[key] = time.monotonic()
            raise

    def dump_private(self) -> dict:
        return {"negative_entries": len(self._neg), "hits": self.hits}


def _creating(op_name: str, loc_arg: int):
    async def fop(self, *args, **kwargs):
        ret = await getattr(self.children[0], op_name)(*args, **kwargs)
        loc = args[loc_arg]
        if isinstance(loc, Loc):
            self._invalidate_parent(loc.path)
        return ret
    fop.__name__ = op_name
    return fop


for _op, _idx in (("create", 0), ("mkdir", 0), ("mknod", 0),
                  ("symlink", 1), ("link", 1), ("rename", 1)):
    setattr(NlCacheLayer, _op, _creating(_op, _idx))
