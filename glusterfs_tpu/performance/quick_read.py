"""performance/quick-read — small-file content cache.

Reference: xlators/performance/quick-read (1.8k LoC): content of files
under ``max-file-size`` is cached whole so repeated small-file reads
skip the data path (the reference piggybacks content on lookup; here it
is filled on first read and invalidated on writes)."""

from __future__ import annotations

import collections
import time

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from . import cache_metrics


@register("performance/quick-read")
class QuickReadLayer(Layer):
    OPTIONS = (
        Option("max-file-size", "size", default="64KB", min=0),
        Option("cache-size", "size", default="16MB"),
        Option("cache-timeout", "time", default="1"),
        Option("cache-invalidation", "bool", default="on",
               description="drop a cached file on a server upcall "
                           "(performance.quick-read-cache-invalidation) "
                           "instead of waiting out the timeout"),
    )

    def notify(self, event, source=None, data=None):
        from ..core.layer import Event

        if event is Event.UPCALL and isinstance(data, dict) and \
                data.get("gfid") and self.opts["cache-invalidation"]:
            self._invalidate(data["gfid"])
        super().notify(event, source, data)

    CACHE_KIND = "quick-read"  # the gftpu_cache_* {cache=...} label

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._files: collections.OrderedDict[bytes, tuple[float, bytes]] = \
            collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        # held-lease registry (api/glfs HeldLeases): leased content
        # never times out — a recall drops it via the upcall path
        self._lease_reg = None
        # gfids known to exceed max-file-size (TTL'd): a large file
        # must not pay a size probe on EVERY read just to learn, again,
        # that it doesn't qualify (the reference learns size from the
        # lookup it piggybacks content on)
        self._too_big: dict[bytes, float] = {}
        cache_metrics.track(self)

    def set_lease_registry(self, reg) -> None:
        self._lease_reg = reg

    def _leased(self, gfid) -> bool:
        return self._lease_reg is not None and self._lease_reg.held(gfid)

    def _invalidate(self, gfid: bytes) -> None:
        ent = self._files.pop(gfid, None)
        if ent is not None:
            self._bytes -= len(ent[1])
        self._too_big.pop(gfid, None)

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        maxsz = self.opts["max-file-size"]
        ent = self._files.get(fd.gfid)
        if ent is not None and \
                (self._leased(fd.gfid) or
                 time.monotonic() - ent[0] < self.opts["cache-timeout"]):
            self.hits += 1
            self._files.move_to_end(fd.gfid)
            out = ent[1][offset: offset + size]
            self.hit_bytes += len(out)
            return out
        self.misses += 1
        big = self._too_big.get(fd.gfid)
        if big is not None and \
                time.monotonic() - big < self.opts["cache-timeout"]:
            return await self.children[0].readv(fd, size, offset, xdata)
        if size > maxsz:
            # a request larger than any qualifying file needs no size
            # probe — but it says nothing about the FILE's size (the
            # kernel and read_file read small files with big buffers),
            # so no blacklisting here.  If the EOF-truncated answer
            # turns out to BE a whole small file, cache it in passing.
            data = await self.children[0].readv(fd, size, offset, xdata)
            if offset == 0 and len(data) <= maxsz:
                content = bytes(data)
                self._invalidate(fd.gfid)  # replace, don't double-count
                self._files[fd.gfid] = (time.monotonic(), content)
                self._bytes += len(content)
                while self._bytes > self.opts["cache-size"] \
                        and self._files:
                    _, (_, old) = self._files.popitem(last=False)
                    self._bytes -= len(old)
            return data
        ia = await self.children[0].fstat(fd)
        if ia.size > maxsz:
            self._too_big[fd.gfid] = time.monotonic()
        if ia.size <= maxsz:
            # bytes() copy: a memoryview off the wire blob lane would
            # pin its whole RPC frame for the cache's lifetime
            content = bytes(
                await self.children[0].readv(fd, maxsz + 1, 0))
            self._invalidate(fd.gfid)  # replace, don't double-count
            self._files[fd.gfid] = (time.monotonic(), content)
            self._bytes += len(content)
            while self._bytes > self.opts["cache-size"] and self._files:
                _, (_, old) = self._files.popitem(last=False)
                self._bytes -= len(old)
            return content[offset: offset + size]
        return await self.children[0].readv(fd, size, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].writev(fd, data, offset, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].ftruncate(fd, size, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        ia = await self.children[0].truncate(loc, size, xdata)
        self._invalidate(ia.gfid)
        return ia

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Forward chains intact; replay the whole-file-cache
        invalidation the per-fop write overrides would have done."""
        from ..rpc import compound as cfop

        replies = await self.children[0].compound(links, xdata)
        cfop.replay_write_invalidation(links, replies, self._invalidate)
        return replies

    def dump_private(self) -> dict:
        return {"files": len(self._files), "bytes": self._bytes,
                "hits": self.hits, "misses": self.misses}
