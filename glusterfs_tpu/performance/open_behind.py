"""performance/open-behind — defer open() until a fop needs the fd.

Reference: xlators/performance/open-behind (1.2k LoC): open returns
immediately; the real open is wound lazily on first fd use (helps
open/read/close small-file workloads)."""

from __future__ import annotations

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


class _ObCtx:
    __slots__ = ("loc", "flags", "real_fd")

    def __init__(self, loc: Loc, flags: int):
        self.loc = loc
        self.flags = flags
        self.real_fd: FdObj | None = None


@register("performance/open-behind")
class OpenBehindLayer(Layer):
    OPTIONS = (
        Option("lazy-open", "bool", default="on"),
    )

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        if not self.opts["lazy-open"]:
            return await self.children[0].open(loc, flags, xdata)
        # validate existence cheaply, defer the real open
        ia, _ = await self.children[0].lookup(loc)
        fd = FdObj(ia.gfid, flags, path=loc.path)
        fd.ctx_set(self, _ObCtx(Loc(loc.path, gfid=ia.gfid), flags))
        return fd

    async def _real(self, fd: FdObj) -> FdObj:
        ctx: _ObCtx | None = fd.ctx_get(self)
        if ctx is None:
            return fd  # not ours (e.g. create path)
        if ctx.real_fd is None:
            ctx.real_fd = await self.children[0].open(ctx.loc, ctx.flags)
        return ctx.real_fd

    async def release(self, fd: FdObj):
        ctx: _ObCtx | None = fd.ctx_del(self)
        if ctx is not None:
            if ctx.real_fd is not None:
                await super().release(ctx.real_fd)
            return
        await super().release(fd)

    def dump_private(self) -> dict:
        return {"lazy_open": self.opts["lazy-open"]}


def _lazy(op_name: str):
    async def fop(self, fd: FdObj, *args, **kwargs):
        real = await self._real(fd)
        return await getattr(self.children[0], op_name)(real, *args,
                                                        **kwargs)
    fop.__name__ = op_name
    return fop


for _op in ("readv", "writev", "fstat", "fsync", "flush", "ftruncate",
            "fgetxattr", "fsetxattr", "fxattrop", "fremovexattr", "seek",
            "fallocate", "discard", "zerofill", "rchecksum", "lk",
            "fsetattr"):
    setattr(OpenBehindLayer, _op, _lazy(_op))
