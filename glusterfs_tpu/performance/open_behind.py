"""performance/open-behind — defer open() until a fop needs the fd.

Reference: xlators/performance/open-behind (1.2k LoC): open returns
immediately; the real open is wound lazily on first fd use (helps
open/read/close small-file workloads)."""

from __future__ import annotations

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


class _ObCtx:
    __slots__ = ("loc", "flags", "real_fd", "anon_fd")

    def __init__(self, loc: Loc, flags: int):
        self.loc = loc
        self.flags = flags
        self.real_fd: FdObj | None = None
        # ONE anonymous stand-in per open: downstream layers key per-fd
        # state (read-ahead windows, EC fd ctx) off the fd object — a
        # fresh FdObj per read would reset them every call
        self.anon_fd: FdObj | None = None


@register("performance/open-behind")
class OpenBehindLayer(Layer):
    OPTIONS = (
        Option("lazy-open", "bool", default="on"),
        Option("use-anonymous-fd", "bool", default="on",
               description="serve reads on a never-opened fd through an "
                           "anonymous (gfid-addressed) fd instead of "
                           "forcing the deferred open (the reference's "
                           "open-behind option of the same name): an "
                           "open/read/close pass never pays open or "
                           "release round trips"),
        Option("read-after-open", "bool", default="off",
               description="the first read materializes the REAL open "
                           "instead of riding an anonymous fd "
                           "(performance.read-after-open): apps that "
                           "read-then-write want the fd identity "
                           "stable from the first byte"),
    )

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        if not self.opts["lazy-open"]:
            return await self.children[0].open(loc, flags, xdata)
        if loc.gfid:
            # already resolved (the api walks the path before open):
            # no validation round trip — a vanished file surfaces
            # ESTALE/ENOENT on first use, same as a raced open
            gfid = loc.gfid
        else:
            ia, _ = await self.children[0].lookup(loc)
            gfid = ia.gfid
        fd = FdObj(gfid, flags, path=loc.path)
        fd.ctx_set(self, _ObCtx(Loc(loc.path, gfid=gfid), flags))
        return fd

    async def _real(self, fd: FdObj) -> FdObj:
        ctx: _ObCtx | None = fd.ctx_get(self)
        if ctx is None:
            return fd  # not ours (e.g. create path)
        if ctx.real_fd is None:
            ctx.real_fd = await self.children[0].open(ctx.loc, ctx.flags)
            if ctx.anon_fd is not None:
                # retire the anonymous stand-in BEFORE the first fop on
                # the real fd: downstream per-fd state keyed on it — a
                # read-ahead window with an in-flight prefetch task —
                # must not keep racing I/O against the now-materialized
                # open (stale prefetched pages would otherwise survive
                # a write that only invalidates the REAL fd's window)
                anon, ctx.anon_fd = ctx.anon_fd, None
                try:
                    await self.children[0].release(anon)
                except Exception:  # advisory cleanup: never fail the fop
                    pass
        return ctx.real_fd

    def _anon(self, fd: FdObj) -> FdObj | None:
        """Anonymous stand-in for a read on a still-unopened lazy fd."""
        ctx: _ObCtx | None = fd.ctx_get(self)
        if ctx is None or ctx.real_fd is not None or \
                not self.opts["use-anonymous-fd"] or \
                self.opts["read-after-open"]:
            return None
        import os as _os

        if ctx.flags & (_os.O_WRONLY | _os.O_RDWR):
            return None  # writes need the real fd (wb/locks semantics)
        if ctx.anon_fd is None:
            ctx.anon_fd = FdObj(ctx.loc.gfid, ctx.flags,
                                path=ctx.loc.path, anonymous=True)
        return ctx.anon_fd

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        ctx: _ObCtx | None = fd.ctx_get(self)
        if ctx is not None and ctx.real_fd is None:
            return {}  # never materialized, never wrote: nothing to push
        real = await self._real(fd)
        return await self.children[0].flush(real, xdata)

    async def release(self, fd: FdObj):
        ctx: _ObCtx | None = fd.ctx_del(self)
        if ctx is not None:
            if ctx.anon_fd is not None:
                # the anonymous stand-in accumulated downstream per-fd
                # state (read-ahead pages, running prefetch tasks) —
                # release it or every lazy open/read/close pass leaks
                # that state and its in-flight I/O
                try:
                    await super().release(ctx.anon_fd)
                except Exception:
                    pass
            if ctx.real_fd is not None:
                await super().release(ctx.real_fd)
            return
        await super().release(fd)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains whose fds are all chain-internal (FdRef) or foreign
        forward intact; a lazy fd of OURS in the chain decomposes so
        the per-fop materialization/anonymous routing applies."""
        from ..rpc import compound as cfop

        for _fop, args, kwargs in links:
            for a in list(args) + list((kwargs or {}).values()):
                if isinstance(a, FdObj) and a.ctx_get(self) is not None:
                    return await cfop.decompose(self, links, xdata)
        return await self.children[0].compound(links, xdata)

    def dump_private(self) -> dict:
        return {"lazy_open": self.opts["lazy-open"]}


def _lazy(op_name: str, anon_ok: bool = False):
    async def fop(self, fd: FdObj, *args, **kwargs):
        if anon_ok:
            anon = self._anon(fd)
            if anon is not None:
                return await getattr(self.children[0], op_name)(
                    anon, *args, **kwargs)
        real = await self._real(fd)
        return await getattr(self.children[0], op_name)(real, *args,
                                                        **kwargs)
    fop.__name__ = op_name
    return fop


# read-class fops ride anonymous fds (no open/release round trips for
# an open/read/close pass); write-class and lock fops force the real
# open — write-behind flushing and posix lock-loss semantics need a
# stable fd identity
for _op in ("readv", "fstat", "fgetxattr", "seek", "rchecksum"):
    setattr(OpenBehindLayer, _op, _lazy(_op, anon_ok=True))
for _op in ("writev", "fsync", "ftruncate",
            "fsetxattr", "fxattrop", "fremovexattr",
            "fallocate", "discard", "zerofill", "lk", "fsetattr"):
    setattr(OpenBehindLayer, _op, _lazy(_op))
