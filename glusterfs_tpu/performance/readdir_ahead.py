"""performance/readdir-ahead — directory listing prefetch.

Reference: xlators/performance/readdir-ahead (1.5k LoC): fill the whole
listing on opendir, serve readdir windows from the buffer."""

from __future__ import annotations

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("performance/readdir-ahead")
class ReaddirAheadLayer(Layer):
    OPTIONS = (
        Option("rda-request-size", "size", default="128KB"),
    )

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        fd = await self.children[0].opendir(loc, xdata)
        try:
            entries = await self.children[0].readdir(fd, 0, 0)
            fd.ctx_set(self, entries)
        except Exception:
            pass
        return fd

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        cached = fd.ctx_get(self)
        if cached is not None:
            return cached[offset:]
        return await self.children[0].readdir(fd, size, offset, xdata)
