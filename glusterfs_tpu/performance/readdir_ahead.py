"""performance/readdir-ahead — directory listing prefetch.

Reference: xlators/performance/readdir-ahead (1.5k LoC): fill the whole
listing on opendir, serve readdir windows from the buffer."""

from __future__ import annotations

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("performance/readdir-ahead")
class ReaddirAheadLayer(Layer):
    OPTIONS = (
        Option("rda-request-size", "size", default="128KB"),
        Option("rda-cache-limit", "size", default="10MB",
               description="total bytes of buffered listings across "
                           "open dir fds (performance.rda-cache-limit): "
                           "past it new opendirs stop prefetching"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        import collections

        # fd-id -> (fd, weight), LRU: rda-cache-limit evicts the oldest
        # buffered listings (the reference prunes per-fd rda buffers
        # against its global cache limit the same way)
        self._lru: "collections.OrderedDict[int, tuple]" = \
            collections.OrderedDict()
        self._cached_bytes = 0

    @staticmethod
    def _weight(entries) -> int:
        # rough per-entry footprint (name + iatt) for the cache budget
        return sum(64 + len(getattr(e, "name", "") or "")
                   for e in entries) if entries else 0

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        fd = await self.children[0].opendir(loc, xdata)
        try:
            entries = await self.children[0].readdir(fd, 0, 0)
            fd.ctx_set(self, entries)
            w = self._weight(entries)
            self._lru[id(fd)] = (fd, w)
            self._cached_bytes += w
            limit = self.opts["rda-cache-limit"]
            while self._cached_bytes > limit and self._lru:
                _, (ofd, ow) = self._lru.popitem(last=False)
                ofd.ctx_del(self)
                self._cached_bytes -= ow
        except Exception:
            pass
        return fd

    async def release(self, fd: FdObj):
        ent = self._lru.pop(id(fd), None)
        if ent is not None:
            fd.ctx_del(self)
            self._cached_bytes -= ent[1]
        await super().release(fd)

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        cached = fd.ctx_get(self)
        if cached is not None:
            return cached[offset:]
        return await self.children[0].readdir(fd, size, offset, xdata)
