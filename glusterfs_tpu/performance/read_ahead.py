"""performance/read-ahead — sequential read prefetch.

Reference: xlators/performance/read-ahead (2.1k LoC): detect sequential
access per fd and prefetch ``page-count`` pages ahead, dropping the
cache on writes/seeks.

Two additions over the reference shape (ISSUE 3 read pipeline):

* **Fused demand+prefetch chains** (``compound-fops on``): the demand
  readv and the look-ahead window ride ONE compound frame
  (readv+readv), so a sequential stream pays one round trip where the
  task-based prefetch paid two — this is the fusion site behind both
  the fuse READ path and api reads (both flow through this layer).
  Mixed-version peers and mid-graph decomposition fall back to plain
  serial readvs with identical results (rpc/compound semantics).
* **Adaptive window doubling** (``adaptive-window on``): the window
  starts at one page and doubles per sustained-sequential prefetch up
  to ``page-count``, so a short sequential burst never pays a full
  window of wasted reads while a long stream converges on the
  operator's ceiling (the read-ahead-page-count semantics, grown
  adaptively).

Cache hits are served as scatter-gather page views (wire.SGBuf): the
pages are immutable bytes, so the reply crosses the stack — and
/dev/fuse — without a join copy.
"""

from __future__ import annotations

import asyncio

from ..core.layer import FdObj, Layer, register
from ..core.options import Option
from ..rpc.wire import as_single_buffer, serve_pages


class _RaFd:
    __slots__ = ("next_offset", "pages", "task", "task_range", "window")

    def __init__(self):
        self.next_offset = 0
        self.pages: dict[int, bytes] = {}
        self.task: asyncio.Task | None = None
        self.task_range = (0, 0)  # [first, last] page of the in-flight fetch
        self.window = 1  # adaptive look-ahead pages (doubles, capped)


@register("performance/read-ahead")
class ReadAheadLayer(Layer):
    OPTIONS = (
        Option("page-count", "int", default=8, min=1, max=64),
        Option("page-size", "size", default="128KB", min=4096),
        Option("adaptive-window", "bool", default="on",
               description="grow the look-ahead window from 1 page, "
                           "doubling per sustained-sequential prefetch "
                           "up to page-count (performance.read-ahead-"
                           "adaptive); off = always page-count pages"),
        Option("compound-fops", "bool", default="off",
               description="fuse the demand readv and its look-ahead "
                           "window into one compound frame "
                           "(cluster.use-compound-fops read half): a "
                           "sequential stream costs one round trip per "
                           "window instead of two.  Decomposes "
                           "harmlessly below mixed-version or "
                           "non-transparent layers"),
    )

    def _ctx(self, fd: FdObj) -> _RaFd:
        ctx = fd.ctx_get(self)
        if ctx is None:
            ctx = _RaFd()
            fd.ctx_set(self, ctx)
        return ctx

    def _grow_window(self, ctx: _RaFd) -> int:
        """Pages for the NEXT look-ahead fetch: the current window,
        doubling for the one after (adaptive ramp starts at 1 page)."""
        count = self.opts["page-count"]
        if not self.opts["adaptive-window"]:
            ctx.window = count
            return count
        window = min(count, max(1, ctx.window))
        ctx.window = min(count, window * 2)
        return window

    def _store_window(self, ctx: _RaFd, start_page: int, data) -> None:
        """Split a fetched window into owned page copies (a memoryview
        off the wire blob lane must not be pinned by the cache)."""
        psz = self.opts["page-size"]
        count = self.opts["page-count"]
        view = memoryview(as_single_buffer(data))
        for i in range((len(view) + psz - 1) // psz or 1):
            page = bytes(view[i * psz:(i + 1) * psz])
            ctx.pages[start_page + i] = page
            if len(ctx.pages) > 4 * count:
                ctx.pages.pop(min(ctx.pages))
            if len(page) < psz:
                return

    async def _prefetch(self, fd: FdObj, start_page: int,
                        window: int) -> None:
        """Fetch the whole look-ahead window in ONE child readv (the
        reference pipelines its pages; issuing them as serial fops
        would pay the cluster read-txn latency page-count times)."""
        psz = self.opts["page-size"]
        ctx = self._ctx(fd)
        while start_page in ctx.pages:
            start_page += 1
        try:
            data = await self.children[0].readv(fd, window * psz,
                                                start_page * psz)
        except Exception:
            return
        self._store_window(ctx, start_page, data)

    async def _chain_readv(self, fd: FdObj, size: int, offset: int,
                           nxt: int, window: int,
                           xdata: dict | None):
        """Demand + look-ahead window as ONE compound frame.  Returns
        the demand data; window data lands in the page cache.  A failed
        window link is ignored (prefetch is advisory); a failed demand
        link raises exactly like the unchained read."""
        psz = self.opts["page-size"]
        kw = {"xdata": xdata} if xdata else {}
        replies = await self.children[0].compound([
            ("readv", (fd, size, offset), kw),
            ("readv", (fd, window * psz, nxt * psz), {})])
        st, demand = replies[0]
        if st != "ok":
            raise demand
        wst, wdata = replies[1]
        if wst == "ok" and wdata is not None:
            self._store_window(self._ctx(fd), nxt, wdata)
        return demand

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        psz = self.opts["page-size"]
        sequential = offset == ctx.next_offset
        if not sequential and self.opts["adaptive-window"]:
            ctx.window = 1  # a seek restarts the doubling ramp
        ctx.next_offset = offset + size
        # serve from prefetched pages when fully covered
        idx = offset // psz
        end = offset + size

        def _covered():
            return all(i in ctx.pages
                       for i in range(idx, (end - 1) // psz + 1))

        covered = _covered()
        last = (end - 1) // psz
        if not covered and ctx.task is not None and \
                not ctx.task.done() and \
                idx <= ctx.task_range[1] and last >= ctx.task_range[0]:
            # an in-flight prefetch is fetching (part of) this range:
            # wait for it instead of issuing a DUPLICATE cluster read
            # (the reference parks readers on the page's wait queue,
            # page.c ioc/ra waitq semantics).  Non-overlapping reads
            # (a seek elsewhere) don't wait — they'd pay the whole
            # window's latency for zero hit-rate benefit.
            try:
                await asyncio.shield(ctx.task)
            except asyncio.CancelledError:
                raise  # OUR fop was cancelled: honor it
            except Exception:
                pass
            covered = _covered()
        if covered:
            # zero-copy page views (SGBuf) — shared serve loop
            data = serve_pages(ctx.pages, offset, end, psz)
        elif sequential and self.opts["compound-fops"] and \
                size <= self.opts["page-count"] * psz and \
                (ctx.task is None or ctx.task.done()):
            # window-shaped (streaming) demands only: a huge one-shot
            # read truncates at EOF, where the task path would never
            # have prefetched — chaining a past-EOF window readv onto
            # it would serialize a wasted cluster read wave in front
            # of the reply
            # fused demand+window: one frame on the wire.  The chain
            # runs as a task so concurrent overlapping readers park on
            # it (task_range) instead of duplicating the window.
            nxt = (end + psz - 1) // psz
            while nxt in ctx.pages:  # never re-fetch cached pages
                nxt += 1
            window = self._grow_window(ctx)
            ctx.task_range = (nxt, nxt + window - 1)
            ctx.task = asyncio.create_task(
                self._chain_readv(fd, size, offset, nxt, window, xdata))
            try:
                return await asyncio.shield(ctx.task)
            except asyncio.CancelledError:
                if ctx.task.cancelled():
                    # release() cancelled the chain under us (close
                    # racing a read): the fd is going away but OUR fop
                    # must still answer — serve the demand directly
                    return await self.children[0].readv(fd, size,
                                                        offset, xdata)
                raise  # our own fop was cancelled: honor it
        else:
            data = await self.children[0].readv(fd, size, offset, xdata)
        if sequential and len(data) == size:
            nxt = (end + psz - 1) // psz
            if ctx.task is None or ctx.task.done():
                window = self._grow_window(ctx)
                ctx.task_range = (nxt, nxt + window - 1)
                ctx.task = asyncio.create_task(
                    self._prefetch(fd, nxt, window))
        return data

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ctx = self._ctx(fd)
        ctx.pages.clear()
        return await self.children[0].writev(fd, data, offset, xdata)

    async def release(self, fd: FdObj):
        ctx: _RaFd | None = fd.ctx_del(self)
        if ctx is not None and ctx.task is not None:
            ctx.task.cancel()
        await super().release(fd)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Forward chains intact; drop the read-ahead pages of any fd a
        write link touches (the per-fop writev override's job)."""
        for fop, args, _kw in links:
            if fop in ("writev", "ftruncate", "discard", "zerofill",
                       "fallocate"):
                for a in args:
                    if isinstance(a, FdObj):
                        ctx = a.ctx_get(self)
                        if ctx is not None:
                            ctx.pages.clear()
        return await self.children[0].compound(links, xdata)
