"""performance/read-ahead — sequential read prefetch.

Reference: xlators/performance/read-ahead (2.1k LoC): detect sequential
access per fd and prefetch ``page-count`` pages ahead, dropping the
cache on writes/seeks.
"""

from __future__ import annotations

import asyncio

from ..core.layer import FdObj, Layer, register
from ..core.options import Option


class _RaFd:
    __slots__ = ("next_offset", "pages", "task", "task_range")

    def __init__(self):
        self.next_offset = 0
        self.pages: dict[int, bytes] = {}
        self.task: asyncio.Task | None = None
        self.task_range = (0, 0)  # [first, last] page of the in-flight fetch


@register("performance/read-ahead")
class ReadAheadLayer(Layer):
    OPTIONS = (
        Option("page-count", "int", default=8, min=1, max=64),
        Option("page-size", "size", default="128KB", min=4096),
    )

    def _ctx(self, fd: FdObj) -> _RaFd:
        ctx = fd.ctx_get(self)
        if ctx is None:
            ctx = _RaFd()
            fd.ctx_set(self, ctx)
        return ctx

    async def _prefetch(self, fd: FdObj, start_page: int) -> None:
        """Fetch the whole look-ahead window in ONE child readv (the
        reference pipelines its pages; issuing them as serial fops
        would pay the cluster read-txn latency page-count times)."""
        psz = self.opts["page-size"]
        count = self.opts["page-count"]
        ctx = self._ctx(fd)
        while start_page in ctx.pages:
            start_page += 1
        try:
            data = await self.children[0].readv(fd, count * psz,
                                                start_page * psz)
        except Exception:
            return
        data = bytes(data) if not isinstance(data, bytes) else data
        for i in range(count):
            page = data[i * psz:(i + 1) * psz]
            ctx.pages[start_page + i] = page
            if len(ctx.pages) > 4 * count:
                ctx.pages.pop(min(ctx.pages))
            if len(page) < psz:
                return

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        psz = self.opts["page-size"]
        sequential = offset == ctx.next_offset
        ctx.next_offset = offset + size
        # serve from prefetched pages when fully covered
        idx = offset // psz
        end = offset + size

        def _covered():
            return all(i in ctx.pages
                       for i in range(idx, (end - 1) // psz + 1))

        covered = _covered()
        last = (end - 1) // psz
        if not covered and ctx.task is not None and \
                not ctx.task.done() and \
                idx <= ctx.task_range[1] and last >= ctx.task_range[0]:
            # an in-flight prefetch is fetching (part of) this range:
            # wait for it instead of issuing a DUPLICATE cluster read
            # (the reference parks readers on the page's wait queue,
            # page.c ioc/ra waitq semantics).  Non-overlapping reads
            # (a seek elsewhere) don't wait — they'd pay the whole
            # window's latency for zero hit-rate benefit.
            try:
                await asyncio.shield(ctx.task)
            except asyncio.CancelledError:
                raise  # OUR fop was cancelled: honor it
            except Exception:
                pass
            covered = _covered()
        if covered:
            out = bytearray()
            pos = offset
            while pos < end:
                i = pos // psz
                page = ctx.pages[i]
                start = pos - i * psz
                if start >= len(page):
                    break
                take = page[start: min(len(page), start + (end - pos))]
                out += take
                if len(page) < psz:
                    break
                pos += len(take)
            data = bytes(out)
        else:
            data = await self.children[0].readv(fd, size, offset, xdata)
        if sequential and len(data) == size:
            nxt = (end + psz - 1) // psz
            if ctx.task is None or ctx.task.done():
                ctx.task_range = (nxt,
                                  nxt + self.opts["page-count"] - 1)
                ctx.task = asyncio.create_task(self._prefetch(fd, nxt))
        return data

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ctx = self._ctx(fd)
        ctx.pages.clear()
        return await self.children[0].writev(fd, data, offset, xdata)

    async def release(self, fd: FdObj):
        ctx: _RaFd | None = fd.ctx_del(self)
        if ctx is not None and ctx.task is not None:
            ctx.task.cancel()
        await super().release(fd)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Forward chains intact; drop the read-ahead pages of any fd a
        write link touches (the per-fop writev override's job)."""
        for fop, args, _kw in links:
            if fop in ("writev", "ftruncate", "discard", "zerofill",
                       "fallocate"):
                for a in args:
                    if isinstance(a, FdObj):
                        ctx = a.ctx_get(self)
                        if ctx is not None:
                            ctx.pages.clear()
        return await self.children[0].compound(links, xdata)
