"""performance/io-cache — page cache for reads.

Reference: xlators/performance/io-cache (3.9k LoC): page-granular read
cache (rbthash + LRU), invalidated by writes/truncates, bounded by
``cache-size``.
"""

from __future__ import annotations

import collections

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..rpc.wire import as_single_buffer, serve_pages
from . import cache_metrics


@register("performance/io-cache")
class IoCacheLayer(Layer):
    OPTIONS = (
        Option("cache-size", "size", default="32MB", min=4096),
        Option("page-size", "size", default="128KB", min=4096),
        Option("cache-timeout", "time", default="1"),
        Option("max-file-size", "size", default="0", min=0,
               description="pages at offsets past this are never "
                           "cached (performance.cache-max-file-size; "
                           "0 = unlimited): one huge streaming file "
                           "must not wash the cache"),
        Option("min-file-size", "size", default="0", min=0,
               description="files KNOWN (from their EOF page) to be "
                           "smaller than this are not cached "
                           "(performance.cache-min-file-size; 0 = no "
                           "floor — quick-read owns tiny files)"),
        Option("priority", "str", default="",
               description="comma list of pattern:level pairs "
                           "(performance.cache-priority, ioc_priority): "
                           "higher-level paths evict LAST — e.g. "
                           "'*.db:3,*.tmp:0'"),
    )

    CACHE_KIND = "io-cache"  # the gftpu_cache_* {cache=...} label

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # (gfid, page_index) -> bytes; OrderedDict as LRU
        self._pages: collections.OrderedDict[tuple, bytes] = \
            collections.OrderedDict()
        self._bytes = 0
        # gfid -> (mtime, validated_at): cross-client coherence —
        # cached pages older than cache-timeout are revalidated with an
        # fstat before use and dropped on an mtime change
        # (ioc_cache_validate; local writes invalidate directly and
        # upcall events invalidate remotely-changed inodes)
        self._seen: dict[bytes, tuple[float, float]] = {}
        self._prio: dict[bytes, int] = {}  # gfid -> cache-priority level
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.validations = 0
        # held-lease registry (api/glfs HeldLeases): a leased gfid's
        # pages skip the fstat revalidation entirely — the brick's
        # recall contract replaces the mtime probe
        self._lease_reg = None
        cache_metrics.track(self)

    def set_lease_registry(self, reg) -> None:
        self._lease_reg = reg

    def _priority_of(self, path: str) -> int:
        """performance.cache-priority (ioc_get_priority): first
        matching pattern's level; unmatched paths are level 1."""
        spec = str(self.opts["priority"]).strip()
        if not spec:
            return 1
        import fnmatch
        import os as _os

        base = _os.path.basename(path or "")
        for part in spec.split(","):
            pat, _, lvl = part.strip().rpartition(":")
            if pat and fnmatch.fnmatch(base, pat):
                try:
                    return int(lvl)
                except ValueError:
                    return 1
        return 1

    def _evict(self) -> None:
        limit = self.opts["cache-size"]
        if self._bytes <= limit:
            return
        # evict lowest priority level first, LRU within a level
        # (ioc_prune walks the per-priority page lists in order)
        levels = sorted({self._prio.get(g, 1)
                         for g, _ in self._pages}) if self._prio else [1]
        for lvl in levels:
            for key in [k for k in self._pages
                        if self._prio.get(k[0], 1) == lvl]:
                if self._bytes <= limit:
                    return
                self._bytes -= len(self._pages.pop(key))
        while self._bytes > limit and self._pages:
            _, page = self._pages.popitem(last=False)
            self._bytes -= len(page)

    def _invalidate(self, gfid: bytes) -> None:
        for key in [k for k in self._pages if k[0] == gfid]:
            self._bytes -= len(self._pages.pop(key))
        self._prio.pop(gfid, None)
        self._seen.pop(gfid, None)

    def notify(self, event, source=None, data=None):
        """Upcall invalidation (another client changed the inode)."""
        from ..core.layer import Event

        if event is Event.UPCALL and isinstance(data, dict) and \
                data.get("gfid"):
            self._invalidate(data["gfid"])
        super().notify(event, source, data)

    async def _revalidate(self, fd: FdObj) -> None:
        """Drop stale pages before serving hits older than
        cache-timeout: one fstat, compare mtime (ioc_cache_validate —
        what makes an on-by-default read cache coherent across
        clients)."""
        import time

        if self._lease_reg is not None and self._lease_reg.held(fd.gfid):
            # zero-RT mode: cached pages can't be stale while the lease
            # holds — a conflicting writer is recalled (→ upcall →
            # _invalidate) before its write proceeds
            return
        ent = self._seen.get(fd.gfid)
        now = time.monotonic()
        if ent is not None and now - ent[1] < self.opts["cache-timeout"]:
            return
        if not any(k[0] == fd.gfid for k in self._pages):
            return  # nothing cached: first read fills below
        ia = await self.children[0].fstat(fd)
        self.validations += 1
        if ent is None or ent[0] is None or ia.mtime != ent[0]:
            # changed, or no mtime baseline yet (pages filled without
            # one): drop conservatively — the refill right after pairs
            # the new pages with the mtime recorded here
            self._invalidate(fd.gfid)
        self._seen[fd.gfid] = (ia.mtime, now)

    def _store(self, gfid: bytes, index: int, page: bytes) -> None:
        key = (gfid, index)
        old = self._pages.pop(key, None)
        if old is not None:
            self._bytes -= len(old)
        self._pages[key] = page
        self._bytes += len(page)

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        """Page-granular cache with ONE child readv per miss span: a
        large read over cold pages goes down as a single fop (the
        reference fans pages out in parallel through ioc_dispatch;
        splitting a 1 MiB read into eight serial 128 KiB fops would pay
        the cluster txn latency eight times)."""
        await self._revalidate(fd)
        psz = self.opts["page-size"]
        end = offset + size
        first = offset // psz
        last = (end - 1) // psz if size else first
        pages: dict[int, bytes] = {}
        missing: list[int] = []
        for i in range(first, last + 1):
            page = self._pages.get((fd.gfid, i))
            if page is None:
                missing.append(i)
            else:
                self.hits += 1
                self.hit_bytes += len(page)
                self._pages.move_to_end((fd.gfid, i))
                pages[i] = page
                if len(page) < psz:
                    # short page = EOF as of cache time (revalidation
                    # drops it if the file grew): pages past it do not
                    # exist — a big-buffer read must not treat them as
                    # misses and re-fetch the whole span
                    missing = [m for m in missing if m < i]
                    break
        if missing:
            self.misses += len(missing)
            m0, m1 = missing[0], missing[-1]
            # one span read covering every missing page (holes between
            # cached pages re-read cheaply vs extra round trips)
            raw = await self.children[0].readv(
                fd, (m1 - m0 + 1) * psz, m0 * psz, xdata)
            # per-page bytes() copies give the cache OWNED pages (a
            # memoryview off the wire blob lane would pin its whole RPC
            # frame for the cache's lifetime); the serve path below
            # references these pages zero-copy
            data = memoryview(as_single_buffer(raw))
            maxsz = self.opts["max-file-size"]
            minsz = self.opts["min-file-size"]
            self._prio.setdefault(fd.gfid,
                                  self._priority_of(fd.path))
            for i in range(m0, m1 + 1):
                page = bytes(data[(i - m0) * psz: (i - m0 + 1) * psz])
                pages[i] = page
                if not maxsz or (i + 1) * psz <= maxsz:
                    # cache-max-file-size: the tail of a huge file
                    # streams through without washing the cache
                    self._store(fd.gfid, i, page)
                if len(page) < psz:
                    if minsz and i * psz + len(page) < minsz:
                        # whole file is under the floor: tiny files
                        # belong to quick-read, not page cache
                        self._invalidate(fd.gfid)
                        pages = dict(pages)  # serve this read, drop cache
                    break  # EOF: later pages don't exist
            self._evict()
            if fd.gfid not in self._seen:
                # fresh fill: trusted for one cache-timeout, then the
                # first revalidation establishes the mtime baseline
                import time

                self._seen[fd.gfid] = (None, time.monotonic())
        # serve as a scatter-gather vector of page VIEWS: pages are
        # immutable bytes, so segments stay valid past eviction and the
        # reply crosses the stack (and the wire, and /dev/fuse) without
        # ever being joined here (ioc_frame_fill builds the same iovec)
        return serve_pages(pages, offset, end, psz)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].writev(fd, data, offset, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].ftruncate(fd, size, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        ia = await self.children[0].truncate(loc, size, xdata)
        self._invalidate(ia.gfid)
        return ia

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Forward chains intact; replay the page-cache invalidation
        the per-fop write overrides would have done."""
        from ..rpc import compound as cfop

        replies = await self.children[0].compound(links, xdata)
        cfop.replay_write_invalidation(links, replies, self._invalidate)
        return replies

    def dump_private(self) -> dict:
        return {"pages": len(self._pages), "bytes": self._bytes,
                "hits": self.hits, "misses": self.misses,
                "validations": self.validations}
