"""performance/io-cache — page cache for reads.

Reference: xlators/performance/io-cache (3.9k LoC): page-granular read
cache (rbthash + LRU), invalidated by writes/truncates, bounded by
``cache-size``.
"""

from __future__ import annotations

import collections

from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("performance/io-cache")
class IoCacheLayer(Layer):
    OPTIONS = (
        Option("cache-size", "size", default="32MB", min=4096),
        Option("page-size", "size", default="128KB", min=4096),
        Option("cache-timeout", "time", default="1"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # (gfid, page_index) -> bytes; OrderedDict as LRU
        self._pages: collections.OrderedDict[tuple, bytes] = \
            collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def _evict(self) -> None:
        limit = self.opts["cache-size"]
        while self._bytes > limit and self._pages:
            _, page = self._pages.popitem(last=False)
            self._bytes -= len(page)

    def _invalidate(self, gfid: bytes) -> None:
        for key in [k for k in self._pages if k[0] == gfid]:
            self._bytes -= len(self._pages.pop(key))

    async def _page(self, fd: FdObj, index: int) -> bytes:
        psz = self.opts["page-size"]
        key = (fd.gfid, index)
        page = self._pages.get(key)
        if page is not None:
            self.hits += 1
            self._pages.move_to_end(key)
            return page
        self.misses += 1
        page = await self.children[0].readv(fd, psz, index * psz)
        self._pages[key] = page
        self._bytes += len(page)
        self._evict()
        return page

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        psz = self.opts["page-size"]
        out = bytearray()
        pos = offset
        end = offset + size
        while pos < end:
            idx = pos // psz
            page = await self._page(fd, idx)
            start = pos - idx * psz
            if start >= len(page):
                break  # EOF
            take = page[start: min(len(page), start + (end - pos))]
            out += take
            if len(page) < psz:  # short page = EOF
                break
            pos += len(take)
        return bytes(out)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].writev(fd, data, offset, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        self._invalidate(fd.gfid)
        return await self.children[0].ftruncate(fd, size, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        ia = await self.children[0].truncate(loc, size, xdata)
        self._invalidate(ia.gfid)
        return ia

    def dump_private(self) -> dict:
        return {"pages": len(self._pages), "bytes": self._bytes,
                "hits": self.hits, "misses": self.misses}
